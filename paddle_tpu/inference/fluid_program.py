"""Load + serve reference-produced inference artifacts (VERDICT r3 item 3).

A reference-format inference model directory holds
  __model__ (or *.pdmodel)      serialized framework.proto ProgramDesc
  per-param files / __params__  LoDTensors via SerializeToStream
(reference: inference/api/analysis_predictor.cc:201 PrepareProgram,
inference/io.cc LoadModel, framework/framework.proto:202,
framework/lod_tensor.cc:244 SerializeToStream,
framework/tensor_util.cc:771 TensorToStream).

TPU-native serving: instead of the reference's scope+OperatorBase executor,
block 0's op list is replayed through a jnp op table and the whole program
is `jax.jit`ed — the ProgramDesc IR lowers to ONE XLA module (the
BASELINE.json north-star contract: "the static-graph Executor lowers the
Fluid ProgramDesc IR to an XLA HLO module").

The protobuf wire parsing is hand-rolled (proto2 subset: varint / 64-bit /
length-delimited / 32-bit fields) like onnx.py's hand-rolled writer — no
protobuf runtime dependency.
"""
import functools
import os
import struct

import numpy as np

__all__ = ['parse_program_desc', 'load_fluid_model', 'FluidProgram',
           'read_lod_tensor', 'FLUID_OP_TABLE']


# -- protobuf wire-format reader ---------------------------------------------

def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError('malformed varint')


def _parse_fields(buf):
    """Yield (field_number, wire_type, value) over a message's bytes."""
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:        # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 1:      # 64-bit
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:      # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:      # 32-bit
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError('unsupported wire type %d' % wire)
        yield field, wire, val


def _zigzag_i64(v):
    """proto2 int64 fields arrive as two's-complement varints."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _to_i32(v):
    """proto2 int32: negatives arrive sign-extended as 64-bit varints
    (e.g. -1 is 2^64-1), so the 64-bit correction must apply FIRST."""
    if v >= (1 << 63):
        v -= (1 << 64)
    elif v >= (1 << 31):
        v -= (1 << 32)
    return v


def _f32(raw):
    return struct.unpack('<f', raw)[0]


def _f64(raw):
    return struct.unpack('<d', raw)[0]


# -- framework.proto message readers (subset the loader needs) ---------------

class Attr:
    __slots__ = ('name', 'type', 'value')

    def __init__(self, name, type_, value):
        self.name, self.type, self.value = name, type_, value


def _parse_attr(buf):
    """OpDesc.Attr (framework.proto:45)."""
    name = atype = None
    scalar = None
    ints, floats, strings, bools, longs, f64s = [], [], [], [], [], []
    for field, wire, val in _parse_fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 2:
            atype = val
        elif field == 3:
            scalar = _to_i32(val)
        elif field == 4:
            scalar = _f32(val)
        elif field == 5:
            scalar = val.decode()
        elif field == 6:
            if wire == 2:  # packed
                p = 0
                while p < len(val):
                    v, p = _read_varint(val, p)
                    ints.append(_to_i32(v))
            else:
                ints.append(_to_i32(val))
        elif field == 7:
            if wire == 2 and len(val) != 4:
                floats.extend(struct.unpack('<%df' % (len(val) // 4), val))
            else:
                floats.append(_f32(val))
        elif field == 8:
            strings.append(val.decode())
        elif field == 10:
            scalar = bool(val)
        elif field == 11:
            if wire == 2:
                bools.extend(bool(b) for b in val)
            else:
                bools.append(bool(val))
        elif field == 12:
            scalar = val  # block_idx
        elif field == 13:
            scalar = _zigzag_i64(val)
        elif field == 15:
            if wire == 2:
                p = 0
                while p < len(val):
                    v, p = _read_varint(val, p)
                    longs.append(_zigzag_i64(v))
            else:
                longs.append(_zigzag_i64(val))
        elif field == 16:
            if wire == 2 and len(val) != 8:
                f64s.extend(struct.unpack('<%dd' % (len(val) // 8), val))
            else:
                f64s.append(_f64(val))
    # AttrType enum: INT FLOAT STRING INTS FLOATS STRINGS BOOLEAN BOOLEANS
    #                BLOCK LONG BLOCKS LONGS FLOAT64S
    if atype == 3:
        value = ints
    elif atype == 4:
        value = floats
    elif atype == 5:
        value = strings
    elif atype == 7:
        value = bools
    elif atype == 11:
        value = longs
    elif atype == 12:
        value = f64s
    else:
        value = scalar
    return Attr(name, atype, value)


class OpDesc:
    __slots__ = ('type', 'inputs', 'outputs', 'attrs')

    def __init__(self):
        self.type = None
        self.inputs = {}    # parameter -> [var names]
        self.outputs = {}
        self.attrs = {}     # name -> python value

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])


def _parse_op_var(buf):
    param, args = None, []
    for field, _, val in _parse_fields(buf):
        if field == 1:
            param = val.decode()
        elif field == 2:
            args.append(val.decode())
    return param, args


def _parse_op(buf):
    op = OpDesc()
    for field, _, val in _parse_fields(buf):
        if field == 3:
            op.type = val.decode()
        elif field == 1:
            k, v = _parse_op_var(val)
            op.inputs[k] = v
        elif field == 2:
            k, v = _parse_op_var(val)
            op.outputs[k] = v
        elif field == 4:
            a = _parse_attr(val)
            op.attrs[a.name] = a.value
    return op


# VarType.Type enum values (framework.proto:107)
_DTYPES = {0: np.bool_, 1: np.int16, 2: np.int32, 3: np.int64,
           4: np.float16, 5: np.float32, 6: np.float64,
           20: np.uint8, 21: np.int8}
_BF16 = 22


def _np_dtype(code):
    if code == _BF16:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if code not in _DTYPES:
        raise ValueError('unsupported VarType.Type %d' % code)
    return np.dtype(_DTYPES[code])


def _parse_tensor_desc(buf):
    dtype, dims = None, []
    for field, wire, val in _parse_fields(buf):
        if field == 1:
            dtype = val
        elif field == 2:
            if wire == 2:
                p = 0
                while p < len(val):
                    v, p = _read_varint(val, p)
                    dims.append(_zigzag_i64(v))
            else:
                dims.append(_zigzag_i64(val))
    return dtype, dims


class VarDesc:
    __slots__ = ('name', 'persistable', 'dtype', 'shape', 'type_code')

    def __init__(self):
        self.name = None
        self.persistable = False
        self.dtype = None
        self.shape = None
        self.type_code = None


def _parse_var(buf):
    var = VarDesc()
    for field, _, val in _parse_fields(buf):
        if field == 1:
            var.name = val.decode()
        elif field == 3:
            var.persistable = bool(val)
        elif field == 2:
            # VarType: type enum (f1), lod_tensor (f3) -> LoDTensorDesc
            for f2, _, v2 in _parse_fields(val):
                if f2 == 1:
                    var.type_code = v2
                elif f2 == 3:
                    for f3, _, v3 in _parse_fields(v2):
                        if f3 == 1:
                            dt, dims = _parse_tensor_desc(v3)
                            var.dtype, var.shape = dt, dims
    return var


class BlockDesc:
    __slots__ = ('idx', 'parent_idx', 'vars', 'ops')

    def __init__(self):
        self.idx = 0
        self.parent_idx = -1
        self.vars = {}
        self.ops = []


def _parse_block(buf):
    blk = BlockDesc()
    for field, _, val in _parse_fields(buf):
        if field == 1:
            blk.idx = val
        elif field == 2:
            blk.parent_idx = val
        elif field == 3:
            v = _parse_var(val)
            blk.vars[v.name] = v
        elif field == 4:
            blk.ops.append(_parse_op(val))
    return blk


def parse_program_desc(data):
    """bytes of a serialized ProgramDesc -> list of BlockDesc."""
    blocks = []
    for field, _, val in _parse_fields(data):
        if field == 1:
            blocks.append(_parse_block(val))
    if not blocks:
        raise ValueError('no blocks: not a ProgramDesc (or empty model)')
    return blocks


# -- LoDTensor stream reader (lod_tensor.cc SerializeToStream) ---------------

def read_lod_tensor(f):
    """Read ONE serialized LoDTensor from a binary stream -> np.ndarray."""
    version = struct.unpack('<I', f.read(4))[0]
    if version != 0:
        raise ValueError('unsupported LoDTensor version %d' % version)
    lod_levels = struct.unpack('<Q', f.read(8))[0]
    for _ in range(lod_levels):
        nbytes = struct.unpack('<Q', f.read(8))[0]
        f.read(nbytes)  # LoD offsets (sequence metadata) — dropped (§7.5)
    tensor_version = struct.unpack('<I', f.read(4))[0]
    if tensor_version != 0:
        raise ValueError('unsupported Tensor version %d' % tensor_version)
    desc_size = struct.unpack('<i', f.read(4))[0]
    dtype_code, dims = _parse_tensor_desc(f.read(desc_size))
    dtype = _np_dtype(dtype_code)
    count = int(np.prod(dims)) if dims else 1
    raw = f.read(count * dtype.itemsize)
    return np.frombuffer(raw, dtype=dtype).reshape(dims).copy()


# -- program container --------------------------------------------------------

class FluidProgram:
    """Parsed ProgramDesc + loaded persistable vars, runnable via XLA."""

    def __init__(self, blocks, params):
        self.blocks = blocks
        self.params = params          # name -> np.ndarray
        blk = blocks[0]
        self.feed_names = []
        self.fetch_names = []
        for op in blk.ops:
            if op.type == 'feed':
                self.feed_names.append((op.attr('col', 0),
                                        op.output('Out')[0]))
            elif op.type == 'fetch':
                self.fetch_names.append((op.attr('col', 0),
                                         op.input('X')[0]))
        self.feed_names = [n for _, n in sorted(self.feed_names)]
        self.fetch_names = [n for _, n in sorted(self.fetch_names)]
        self._jitted = None

    def input_shapes(self):
        blk = self.blocks[0]
        out = {}
        for n in self.feed_names:
            v = blk.vars.get(n)
            out[n] = tuple(v.shape) if v is not None and v.shape else None
        return out

    def _run_block(self, params, feeds):
        """Trace block 0's op list against the jnp op table."""
        scope = dict(params)
        scope.update(feeds)
        for op in self.blocks[0].ops:
            if op.type in ('feed', 'fetch'):
                continue
            fn = FLUID_OP_TABLE.get(op.type)
            if fn is None:
                raise NotImplementedError(
                    'fluid op %r has no XLA lowering yet (supported: %s)'
                    % (op.type, ', '.join(sorted(FLUID_OP_TABLE))))
            fn(op, scope)
        return [scope[n] for n in self.fetch_names]

    def run(self, feed_dict):
        """feed_dict: {feed_var_name: np.ndarray} -> list of np.ndarray.

        The whole block compiles to one XLA executable on first call
        (per AnalysisPredictor's prepared-program contract); repeated
        runs reuse it via jax.jit's cache.
        """
        import jax
        missing = [n for n in self.feed_names if n not in feed_dict]
        if missing:
            raise ValueError('missing feeds: %s' % missing)
        if self._jitted is None:
            self._jitted = jax.jit(self._run_block)
        outs = self._jitted(self.params,
                            {n: feed_dict[n] for n in self.feed_names})
        return [np.asarray(o) for o in outs]


def load_fluid_model(model_path, params_path=None):
    """Load a reference-format inference model.

    model_path: a directory holding `__model__` (+ per-param files or a
    combined params file), or the path of the serialized ProgramDesc
    itself (`.pdmodel` / `__model__`); params_path then points at the
    combined params file (`.pdiparams` / `__params__`).

    Combined-file order: the reference's save/load programs list the
    persistable vars sorted by name (static.io serialize_persistables),
    which is the order the tensors are concatenated in.
    """
    if os.path.isdir(model_path):
        prog_file = os.path.join(model_path, '__model__')
        if not os.path.exists(prog_file):
            cands = [f for f in os.listdir(model_path)
                     if f.endswith('.pdmodel')]
            if not cands:
                raise FileNotFoundError(
                    'no __model__ or *.pdmodel under %s' % model_path)
            prog_file = os.path.join(model_path, cands[0])
            stem = prog_file[:-len('.pdmodel')]
            if params_path is None and os.path.exists(stem + '.pdiparams'):
                params_path = stem + '.pdiparams'
        base_dir = model_path
    else:
        prog_file = model_path
        base_dir = os.path.dirname(model_path)
        if params_path is None:
            stem, ext = os.path.splitext(model_path)
            if ext == '.pdmodel' and os.path.exists(stem + '.pdiparams'):
                params_path = stem + '.pdiparams'

    with open(prog_file, 'rb') as f:
        blocks = parse_program_desc(f.read())

    persistable = sorted(
        n for blk in blocks for n, v in blk.vars.items()
        if v.persistable and n not in ('feed', 'fetch'))
    params = {}
    if params_path is not None:
        with open(params_path, 'rb') as f:
            for name in persistable:
                params[name] = read_lod_tensor(f)
            trailing = f.read(1)
        if trailing:
            raise ValueError('combined params file has trailing bytes — '
                             'var-name ordering mismatch?')
    else:
        for name in persistable:
            p = os.path.join(base_dir, name)
            if not os.path.exists(p):
                raise FileNotFoundError(
                    'parameter file %s missing (separate-files layout)' % p)
            with open(p, 'rb') as f:
                params[name] = read_lod_tensor(f)
    return FluidProgram(blocks, params)


# -- the op table: fluid op -> jnp lowering ----------------------------------
#
# Eval-mode inference semantics of the reference CPU kernels
# (paddle/fluid/operators/*). Each entry mutates `scope` in place.

def _op(name):
    def deco(fn):
        FLUID_OP_TABLE[name] = fn
        return fn
    return deco


FLUID_OP_TABLE = {}


def _import_jnp():
    import jax.numpy as jnp
    return jnp


def _ew_broadcast(x, y, axis):
    """elementwise_* axis semantics: align y's dims starting at `axis`."""
    jnp = _import_jnp()
    if axis is None or axis == -1 or x.ndim == y.ndim:
        return y
    tail = x.ndim - axis - y.ndim
    return jnp.reshape(y, y.shape + (1,) * tail)


def _ew(name, fn):
    def impl(op, scope, fn=fn):
        x = scope[op.input('X')[0]]
        y = scope[op.input('Y')[0]]
        y = _ew_broadcast(x, y, op.attr('axis', -1))
        scope[op.output('Out')[0]] = fn(x, y)
    FLUID_OP_TABLE[name] = impl


def _act(name, fn):
    def impl(op, scope, fn=fn):
        scope[op.output('Out')[0]] = fn(scope[op.input('X')[0]])
    FLUID_OP_TABLE[name] = impl


def _init_table():
    import jax
    import jax.numpy as jnp

    _ew('elementwise_add', lambda x, y: x + y)
    _ew('elementwise_sub', lambda x, y: x - y)
    _ew('elementwise_mul', lambda x, y: x * y)
    _ew('elementwise_div', lambda x, y: x / y)
    _ew('elementwise_max', jnp.maximum)
    _ew('elementwise_min', jnp.minimum)
    _ew('elementwise_pow', jnp.power)

    _act('relu', jax.nn.relu)
    _act('sigmoid', jax.nn.sigmoid)
    _act('tanh', jnp.tanh)
    _act('sqrt', jnp.sqrt)
    _act('exp', jnp.exp)
    _act('square', jnp.square)
    _act('abs', jnp.abs)
    _act('relu6', lambda x: jnp.clip(x, 0, 6))
    _act('hard_swish', lambda x: x * jnp.clip(x + 3, 0, 6) / 6)
    _act('hard_sigmoid', lambda x: jnp.clip(0.2 * x + 0.5, 0, 1))
    _act('swish', lambda x: x * jax.nn.sigmoid(x))
    _act('mish', lambda x: x * jnp.tanh(jax.nn.softplus(x)))
    _act('softplus', jax.nn.softplus)
    _act('log_softmax', lambda x: jax.nn.log_softmax(x, axis=-1))
    _act('floor', jnp.floor)
    _act('ceil', jnp.ceil)
    _act('round', jnp.round)
    _act('sign', jnp.sign)
    _act('reciprocal', lambda x: 1.0 / x)
    _act('logical_not', jnp.logical_not)

    @_op('leaky_relu')
    def _leaky_relu(op, scope):
        x = scope[op.input('X')[0]]
        a = op.attr('alpha', 0.02)
        scope[op.output('Out')[0]] = jnp.where(x > 0, x, a * x)

    @_op('gelu')
    def _gelu(op, scope):
        x = scope[op.input('X')[0]]
        approx = 'tanh' if op.attr('approximate', False) else 'none'
        scope[op.output('Out')[0]] = jax.nn.gelu(
            x, approximate=(approx == 'tanh'))

    @_op('elu')
    def _elu(op, scope):
        x = scope[op.input('X')[0]]
        a = op.attr('alpha', 1.0)
        scope[op.output('Out')[0]] = jnp.where(
            x > 0, x, a * (jnp.exp(x) - 1))

    @_op('prelu')
    def _prelu(op, scope):
        x = scope[op.input('X')[0]]
        alpha = scope[op.input('Alpha')[0]]
        if op.attr('mode', 'all') == 'channel' and x.ndim >= 2:
            alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
        scope[op.output('Out')[0]] = jnp.where(x > 0, x, alpha * x)

    @_op('pow')
    def _pow(op, scope):
        x = scope[op.input('X')[0]]
        scope[op.output('Out')[0]] = x ** op.attr('factor', 1.0)

    # -- comparison (Out is bool) -------------------------------------------
    for cmp_name, cmp_fn in (('equal', jnp.equal),
                             ('not_equal', jnp.not_equal),
                             ('greater_than', jnp.greater),
                             ('greater_equal', jnp.greater_equal),
                             ('less_than', jnp.less),
                             ('less_equal', jnp.less_equal)):
        def _cmp(op, scope, fn=cmp_fn):
            scope[op.output('Out')[0]] = fn(scope[op.input('X')[0]],
                                            scope[op.input('Y')[0]])
        FLUID_OP_TABLE[cmp_name] = _cmp
    _ew('elementwise_floordiv', jnp.floor_divide)
    _ew('elementwise_mod', jnp.mod)

    # -- reductions (one decoder for the whole reduce_* family) ------------
    for red_name, red_fn in (('reduce_max', jnp.max),
                             ('reduce_min', jnp.min),
                             ('reduce_prod', jnp.prod),
                             ('reduce_mean', jnp.mean),
                             ('reduce_sum', jnp.sum)):
        def _red(op, scope, fn=red_fn):
            x = scope[op.input('X')[0]]
            dims = tuple(op.attr('dim', [0])) or None
            if op.attr('reduce_all', False):
                dims = None
            scope[op.output('Out')[0]] = fn(
                x, axis=dims, keepdims=op.attr('keep_dim', False))
        FLUID_OP_TABLE[red_name] = _red

    @_op('stack')
    def _stack(op, scope):
        xs = [scope[n] for n in op.input('X')]
        scope[op.output('Y')[0]] = jnp.stack(xs, axis=op.attr('axis', 0))

    @_op('split')
    def _split(op, scope):
        _no_dynamic(op, 'AxisTensor', 'SectionsTensorList')
        x = scope[op.input('X')[0]]
        axis = op.attr('axis', 0)
        sections = list(op.attr('sections', []))
        outs = op.output('Out')
        if sections:
            if sections.count(-1) > 1:
                raise ValueError('split: at most one -1 section')
            if -1 in sections:
                known = sum(s for s in sections if s != -1)
                sections[sections.index(-1)] = x.shape[axis] - known
            idx = np.cumsum(sections[:-1]).tolist()
            parts = jnp.split(x, idx, axis=axis)
        else:
            parts = jnp.split(x, op.attr('num', len(outs)), axis=axis)
        for name, part in zip(outs, parts):
            scope[name] = part

    @_op('shape')
    def _shape(op, scope):
        x = scope[op.input('Input')[0]]
        scope[op.output('Out')[0]] = jnp.asarray(x.shape, jnp.int32)

    @_op('fill_constant')
    def _fill_constant(op, scope):
        _no_dynamic(op, 'ShapeTensor', 'ShapeTensorList', 'ValueTensor')
        shape = [int(s) for s in op.attr('shape', [])]
        dtype = _np_dtype(op.attr('dtype', 5))
        scope[op.output('Out')[0]] = jnp.full(shape, op.attr('value', 0.0),
                                              dtype)

    @_op('expand_v2')
    def _expand_v2(op, scope):
        _no_dynamic(op, 'Shape', 'expand_shapes_tensor')
        x = scope[op.input('X')[0]]
        shape = [int(s) for s in op.attr('shape', [])]
        # paddle aligns x to the target from the RIGHT when the target
        # rank exceeds x's; -1/0 entries keep x's corresponding dim
        off = len(shape) - x.ndim
        if off < 0:
            raise ValueError('expand_v2: target rank %d < input rank %d'
                             % (len(shape), x.ndim))
        full = []
        for i, s in enumerate(shape):
            if s in (-1, 0):
                if i < off:
                    raise ValueError(
                        'expand_v2: -1/0 in a dim (%d) with no '
                        'corresponding input dim' % i)
                full.append(x.shape[i - off])
            else:
                full.append(s)
        scope[op.output('Out')[0]] = jnp.broadcast_to(x, full)

    @_op('tile')
    def _tile(op, scope):
        x = scope[op.input('X')[0]]
        scope[op.output('Out')[0]] = jnp.tile(
            x, tuple(op.attr('repeat_times', [1])))

    @_op('clip')
    def _clip(op, scope):
        _no_dynamic(op, 'Min', 'Max')
        x = scope[op.input('X')[0]]
        scope[op.output('Out')[0]] = jnp.clip(
            x, op.attr('min', float('-inf')), op.attr('max', float('inf')))

    @_op('one_hot_v2')
    def _one_hot_v2(op, scope):
        x = scope[op.input('X')[0]]
        depth = op.attr('depth', 1)
        scope[op.output('Out')[0]] = jax.nn.one_hot(x, depth,
                                                    dtype=jnp.float32)

    @_op('layer_norm')
    def _layer_norm(op, scope):
        x = scope[op.input('X')[0]]
        ax = op.attr('begin_norm_axis', 1)
        eps = op.attr('epsilon', 1e-5)
        red = tuple(range(ax, x.ndim))
        mean = jnp.mean(x, axis=red, keepdims=True)
        var = jnp.var(x, axis=red, keepdims=True)
        y = (x - mean) / jnp.sqrt(var + eps)
        shape = (1,) * ax + x.shape[ax:]
        if op.input('Scale'):
            y = y * scope[op.input('Scale')[0]].reshape(shape)
        if op.input('Bias'):
            y = y + scope[op.input('Bias')[0]].reshape(shape)
        scope[op.output('Y')[0]] = y

    @_op('instance_norm')
    def _instance_norm(op, scope):
        x = scope[op.input('X')[0]]  # NCHW
        eps = op.attr('epsilon', 1e-5)
        red = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=red, keepdims=True)
        var = jnp.var(x, axis=red, keepdims=True)
        y = (x - mean) / jnp.sqrt(var + eps)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        if op.input('Scale'):
            y = y * scope[op.input('Scale')[0]].reshape(shape)
        if op.input('Bias'):
            y = y + scope[op.input('Bias')[0]].reshape(shape)
        scope[op.output('Y')[0]] = y

    def _no_dynamic(op, *slots):
        """Raise loudly when a tensor-input override slot is wired (the
        export relied on runtime shapes/values this static lowering
        drops — silent fallback to attrs would compute wrong results)."""
        for s in slots:
            if op.input(s):
                raise NotImplementedError(
                    '%s: dynamic %r tensor input is not supported — '
                    're-export with static attrs' % (op.type, s))

    def _nearest_fluid(x, out_h, out_w, align_corners):
        """Fluid nearest sampling: floor(dst*scale) when
        align_corners=False (asymmetric), round(dst*(h-1)/(out-1)) when
        True — jax.image.resize's half-pixel centers match neither."""
        n, c, h, w = x.shape
        if align_corners and out_h > 1 and out_w > 1:
            ys = jnp.round(jnp.arange(out_h) * ((h - 1) / (out_h - 1)))
            xs = jnp.round(jnp.arange(out_w) * ((w - 1) / (out_w - 1)))
        else:
            ys = jnp.floor(jnp.arange(out_h) * (h / out_h))
            xs = jnp.floor(jnp.arange(out_w) * (w / out_w))
        ys = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
        xs = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
        return x[:, :, ys][:, :, :, xs]

    def _bilinear_asym(x, out_h, out_w):
        """align_corners=False, align_mode=1 (asymmetric): src = dst*scale
        — the fluid-era default, which jax.image.resize (half-pixel)
        does not implement."""
        n, c, h, w = x.shape
        fy = jnp.arange(out_h) * (h / out_h)
        fx = jnp.arange(out_w) * (w / out_w)
        y0 = jnp.clip(jnp.floor(fy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(fx).astype(jnp.int32), 0, w - 1)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (fy - y0).astype(x.dtype)[:, None]
        wx = (fx - x0).astype(x.dtype)[None, :]
        g = lambda yy, xx: x[:, :, yy][:, :, :, xx]
        top = g(y0, x0) * (1 - wx) + g(y0, x1) * wx
        bot = g(y1, x0) * (1 - wx) + g(y1, x1) * wx
        return top * (1 - wy) + bot * wy

    def _interp(op, scope, method):
        x = scope[op.input('Input')[0] if op.input('Input')
                  else op.input('X')[0]]  # NCHW
        if op.attr('data_layout', 'NCHW') != 'NCHW':
            raise NotImplementedError('interp: NCHW only')
        _no_dynamic(op, 'OutSize', 'SizeTensor', 'Scale')
        out_h = op.attr('out_h', -1)
        out_w = op.attr('out_w', -1)
        scale = op.attr('scale', [])
        if (out_h is None or out_h <= 0) and scale:
            if isinstance(scale, (int, float)):
                scale = [scale, scale]
            out_h = int(x.shape[2] * scale[0])
            out_w = int(x.shape[3] * scale[-1])
        if not out_h or out_h <= 0 or not out_w or out_w <= 0:
            raise NotImplementedError(
                'interp: no usable out_h/out_w attrs or scale')
        align = op.attr('align_corners', False)
        if align and method != 'nearest':
            raise NotImplementedError('interp: align_corners=True not '
                                      'supported — export with '
                                      'align_corners=False')
        if method == 'nearest':
            out = _nearest_fluid(x, out_h, out_w, align)
        elif op.attr('align_mode', 1) == 1:
            out = _bilinear_asym(x, out_h, out_w)
        else:
            out = jax.image.resize(x, x.shape[:2] + (out_h, out_w),
                                   method=method)
        scope[op.output('Out')[0]] = out.astype(x.dtype)

    for iname, imethod in (('nearest_interp', 'nearest'),
                           ('nearest_interp_v2', 'nearest'),
                           ('bilinear_interp', 'linear'),
                           ('bilinear_interp_v2', 'linear')):
        FLUID_OP_TABLE[iname] = functools.partial(_interp, method=imethod)

    @_op('pad3d')
    def _pad3d(op, scope):
        x = scope[op.input('X')[0]]  # NCDHW or NCHW-style use
        pads = op.attr('paddings', [0] * 6)
        if op.attr('mode', 'constant') != 'constant':
            raise NotImplementedError('pad3d: constant mode only')
        # paddle order: [front, back] per spatial dim, last dim first
        cfg = [(0, 0), (0, 0)]
        spatial = x.ndim - 2
        for d in range(spatial):
            lo = pads[2 * (spatial - 1 - d)]
            hi = pads[2 * (spatial - 1 - d) + 1]
            cfg.append((lo, hi))
        scope[op.output('Out')[0]] = jnp.pad(
            x, cfg, constant_values=op.attr('value', 0.0))

    @_op('pad2d')
    def _pad2d(op, scope):
        x = scope[op.input('X')[0]]  # NCHW
        pads = op.attr('paddings', [0, 0, 0, 0])  # t, b, l, r
        if op.attr('mode', 'constant') != 'constant':
            raise NotImplementedError('pad2d: constant mode only')
        scope[op.output('Out')[0]] = jnp.pad(
            x, [(0, 0), (0, 0), (pads[0], pads[1]), (pads[2], pads[3])],
            constant_values=op.attr('pad_value', 0.0))

    # -- detection (PP-YOLO family serving, BASELINE config 4) --------------
    # route to the vision implementations (vision/ops.py, vision/
    # detection.py — numerically validated against the reference in
    # tests/test_yolo.py + tests/test_detection_ops.py); fixed-size
    # padded outputs keep the whole program one XLA module

    def _arr(t):
        return t._data if hasattr(t, '_data') else t

    @_op('yolo_box')
    def _yolo_box(op, scope):
        from ..vision.ops import yolo_box
        x = scope[op.input('X')[0]]
        img = scope[op.input('ImgSize')[0]]
        boxes, scores = yolo_box(
            x, img, anchors=list(op.attr('anchors', [])),
            class_num=op.attr('class_num', 1),
            conf_thresh=op.attr('conf_thresh', 0.01),
            downsample_ratio=op.attr('downsample_ratio', 32),
            clip_bbox=op.attr('clip_bbox', True),
            scale_x_y=op.attr('scale_x_y', 1.0),
            iou_aware=op.attr('iou_aware', False),
            iou_aware_factor=op.attr('iou_aware_factor', 0.5))
        scope[op.output('Boxes')[0]] = _arr(boxes)
        scope[op.output('Scores')[0]] = _arr(scores)

    def _nms_common(op, scope, with_index):
        from ..vision.detection import multiclass_nms
        bboxes = scope[op.input('BBoxes')[0]]
        scores = scope[op.input('Scores')[0]]
        if op.attr('nms_eta', 1.0) != 1.0:
            raise NotImplementedError(
                'multiclass_nms: adaptive NMS (nms_eta != 1) is not '
                'implemented — suppression would silently use a fixed '
                'threshold')
        res = multiclass_nms(
            bboxes, scores,
            score_threshold=op.attr('score_threshold', 0.05),
            nms_top_k=op.attr('nms_top_k', 1000),
            keep_top_k=op.attr('keep_top_k', 100),
            nms_threshold=op.attr('nms_threshold', 0.3),
            normalized=op.attr('normalized', True),
            background_label=op.attr('background_label', 0),
            return_index=with_index, return_rois_num=True)
        scope[op.output('Out')[0]] = _arr(res[0])
        if with_index and op.output('Index'):
            scope[op.output('Index')[0]] = _arr(res[1])
        rois = op.output('NmsRoisNum') or op.output('RoisNum')
        if rois:
            scope[rois[0]] = _arr(res[-1])

    FLUID_OP_TABLE['multiclass_nms'] = functools.partial(
        _nms_common, with_index=False)
    FLUID_OP_TABLE['multiclass_nms2'] = functools.partial(
        _nms_common, with_index=True)
    FLUID_OP_TABLE['multiclass_nms3'] = functools.partial(
        _nms_common, with_index=True)

    @_op('roi_align')
    def _roi_align(op, scope):
        from ..vision.ops import roi_align
        if not op.input('RoisNum'):
            raise NotImplementedError(
                'roi_align: LoD-carried roi batching is not supported '
                '(SURVEY §7.5) — re-export with the RoisNum input')
        out = roi_align(
            scope[op.input('X')[0]], scope[op.input('ROIs')[0]],
            scope[op.input('RoisNum')[0]],
            output_size=(op.attr('pooled_height', 1),
                         op.attr('pooled_width', 1)),
            spatial_scale=op.attr('spatial_scale', 1.0),
            sampling_ratio=op.attr('sampling_ratio', -1),
            aligned=op.attr('aligned', True))
        scope[op.output('Out')[0]] = _arr(out)

    @_op('box_coder')
    def _box_coder(op, scope):
        from ..vision.ops import box_coder
        pbv = (scope[op.input('PriorBoxVar')[0]]
               if op.input('PriorBoxVar')
               else list(op.attr('variance', [])) or [1.0, 1.0, 1.0, 1.0])
        out = box_coder(
            scope[op.input('PriorBox')[0]], pbv,
            scope[op.input('TargetBox')[0]],
            code_type=op.attr('code_type', 'encode_center_size'),
            box_normalized=op.attr('box_normalized', True),
            axis=op.attr('axis', 0))
        scope[op.output('OutputBox')[0]] = _arr(out)

    @_op('prior_box')
    def _prior_box(op, scope):
        from ..vision.ops import prior_box
        boxes, variances = prior_box(
            scope[op.input('Input')[0]], scope[op.input('Image')[0]],
            min_sizes=list(op.attr('min_sizes', [])),
            max_sizes=list(op.attr('max_sizes', [])) or None,
            aspect_ratios=list(op.attr('aspect_ratios', [1.0])),
            variance=list(op.attr('variances', [0.1, 0.1, 0.2, 0.2])),
            flip=op.attr('flip', False), clip=op.attr('clip', False),
            steps=(op.attr('step_w', 0.0), op.attr('step_h', 0.0)),
            offset=op.attr('offset', 0.5),
            min_max_aspect_ratios_order=op.attr(
                'min_max_aspect_ratios_order', False))
        scope[op.output('Boxes')[0]] = _arr(boxes)
        scope[op.output('Variances')[0]] = _arr(variances)

    @_op('anchor_generator')
    def _anchor_generator(op, scope):
        from ..vision.detection import anchor_generator
        anchors, variances = anchor_generator(
            scope[op.input('Input')[0]],
            anchor_sizes=list(op.attr('anchor_sizes', [])),
            aspect_ratios=list(op.attr('aspect_ratios', [])),
            variances=list(op.attr('variances', [])) or None,
            stride=tuple(op.attr('stride', [])) or None,
            offset=op.attr('offset', 0.5))
        scope[op.output('Anchors')[0]] = _arr(anchors)
        scope[op.output('Variances')[0]] = _arr(variances)

    @_op('norm')
    def _norm(op, scope):
        x = scope[op.input('X')[0]]
        ax = op.attr('axis', -1)
        eps = op.attr('epsilon', 1e-10)
        scope[op.output('Out')[0]] = x / jnp.sqrt(
            jnp.sum(x * x, axis=ax, keepdims=True) + eps)

    @_op('mul')
    def _mul(op, scope):
        x = scope[op.input('X')[0]]
        y = scope[op.input('Y')[0]]
        xd = op.attr('x_num_col_dims', 1)
        yd = op.attr('y_num_col_dims', 1)
        xs, ys = x.shape, y.shape
        x2 = jnp.reshape(x, (int(np.prod(xs[:xd])), -1))
        y2 = jnp.reshape(y, (int(np.prod(ys[:yd])), -1))
        out = x2 @ y2
        scope[op.output('Out')[0]] = jnp.reshape(
            out, xs[:xd] + ys[yd:])

    @_op('matmul')
    def _matmul(op, scope):
        x = scope[op.input('X')[0]]
        y = scope[op.input('Y')[0]]
        if op.attr('transpose_X', False):
            x = jnp.swapaxes(x, -1, -2)
        if op.attr('transpose_Y', False):
            y = jnp.swapaxes(y, -1, -2)
        out = jnp.matmul(x, y) * op.attr('alpha', 1.0)
        scope[op.output('Out')[0]] = out

    @_op('matmul_v2')
    def _matmul_v2(op, scope):
        x = scope[op.input('X')[0]]
        y = scope[op.input('Y')[0]]
        if op.attr('trans_x', False):
            x = jnp.swapaxes(x, -1, -2)
        if op.attr('trans_y', False):
            y = jnp.swapaxes(y, -1, -2)
        scope[op.output('Out')[0]] = jnp.matmul(x, y)

    @_op('fc')
    def _fc(op, scope):
        x = scope[op.input('Input')[0]]
        w = scope[op.input('W')[0]]
        ncol = op.attr('in_num_col_dims', 1)
        x2 = jnp.reshape(x, (int(np.prod(x.shape[:ncol])), -1))
        out = x2 @ w
        if op.input('Bias'):
            out = out + scope[op.input('Bias')[0]]
        if op.attr('activation_type', '') == 'relu':
            out = jax.nn.relu(out)
        scope[op.output('Out')[0]] = jnp.reshape(
            out, x.shape[:ncol] + (w.shape[1],))

    @_op('softmax')
    def _softmax(op, scope):
        x = scope[op.input('X')[0]]
        scope[op.output('Out')[0]] = jax.nn.softmax(
            x, axis=op.attr('axis', -1))

    @_op('scale')
    def _scale(op, scope):
        x = scope[op.input('X')[0]]
        s = op.attr('scale', 1.0)
        b = op.attr('bias', 0.0)
        if op.attr('bias_after_scale', True):
            out = x * s + b
        else:
            out = (x + b) * s
        scope[op.output('Out')[0]] = out

    @_op('mean')
    def _mean(op, scope):
        scope[op.output('Out')[0]] = jnp.mean(scope[op.input('X')[0]])

    @_op('reshape2')
    def _reshape2(op, scope):
        x = scope[op.input('X')[0]]
        shape = [int(s) for s in op.attr('shape', [])]
        scope[op.output('Out')[0]] = jnp.reshape(x, shape)

    @_op('transpose2')
    def _transpose2(op, scope):
        x = scope[op.input('X')[0]]
        scope[op.output('Out')[0]] = jnp.transpose(
            x, op.attr('axis', list(range(x.ndim))[::-1]))

    @_op('flatten2')
    def _flatten2(op, scope):
        x = scope[op.input('X')[0]]
        ax = op.attr('axis', 1)
        scope[op.output('Out')[0]] = jnp.reshape(
            x, (int(np.prod(x.shape[:ax])), -1))

    @_op('flatten_contiguous_range')
    def _flatten_range(op, scope):
        x = scope[op.input('X')[0]]
        start = op.attr('start_axis', 1)
        stop = op.attr('stop_axis', -1)
        if stop < 0:
            stop += x.ndim
        shape = (x.shape[:start] +
                 (int(np.prod(x.shape[start:stop + 1])),) +
                 x.shape[stop + 1:])
        scope[op.output('Out')[0]] = jnp.reshape(x, shape)

    @_op('concat')
    def _concat(op, scope):
        xs = [scope[n] for n in op.input('X')]
        scope[op.output('Out')[0]] = jnp.concatenate(
            xs, axis=op.attr('axis', 0))

    @_op('dropout')
    def _dropout(op, scope):
        x = scope[op.input('X')[0]]
        # inference semantics only (is_test); downgrade_in_infer scales
        impl = op.attr('dropout_implementation', 'downgrade_in_infer')
        p = op.attr('dropout_prob', 0.5)
        if impl == 'downgrade_in_infer':
            x = x * (1.0 - p)
        scope[op.output('Out')[0]] = x

    @_op('conv2d')
    def _conv2d(op, scope):
        from jax import lax
        x = scope[op.input('Input')[0]]     # NCHW
        w = scope[op.input('Filter')[0]]    # OIHW
        strides = tuple(op.attr('strides', [1, 1]))
        algo = op.attr('padding_algorithm', 'EXPLICIT')
        if algo == 'SAME':
            padding = 'SAME'
        elif algo == 'VALID':
            padding = 'VALID'
        else:
            pads = op.attr('paddings', [0, 0])
            if len(pads) == 2:
                padding = [(pads[0], pads[0]), (pads[1], pads[1])]
            else:
                padding = [(pads[0], pads[1]), (pads[2], pads[3])]
        dil = tuple(op.attr('dilations', [1, 1]))
        groups = op.attr('groups', 1)
        out = lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dil, feature_group_count=groups,
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
        scope[op.output('Output')[0]] = out

    @_op('depthwise_conv2d')
    def _depthwise_conv2d(op, scope):
        _conv2d(op, scope)

    @_op('pool2d')
    def _pool2d(op, scope):
        from jax import lax
        x = scope[op.input('X')[0]]
        ptype = op.attr('pooling_type', 'max')
        ksize = tuple(op.attr('ksize', [2, 2]))
        strides = tuple(op.attr('strides', [2, 2]))
        pads = op.attr('paddings', [0, 0])
        if op.attr('global_pooling', False) or op.attr('adaptive', False):
            # adaptive with output 1x1 == global; other adaptive sizes
            # unsupported (raise rather than silently wrong)
            if op.attr('adaptive', False) and tuple(
                    op.attr('ksize', [1, 1])) != (1, 1):
                raise NotImplementedError('adaptive pool2d with output '
                                          '!= 1x1')
            fn = jnp.max if ptype == 'max' else jnp.mean
            scope[op.output('Out')[0]] = fn(x, axis=(2, 3), keepdims=True)
            return
        pad2 = [(0, 0), (0, 0),
                (pads[0], pads[0]), (pads[1], pads[1])]
        window = (1, 1) + ksize
        stride4 = (1, 1) + strides
        if ptype == 'max':
            init = -jnp.inf
            out = lax.reduce_window(x, init, lax.max, window, stride4, pad2)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, stride4, pad2)
            if op.attr('exclusive', True) and any(p for p in pads):
                ones = jnp.ones_like(x)
                cnt = lax.reduce_window(ones, 0.0, lax.add, window, stride4,
                                        pad2)
                out = s / cnt
            else:
                out = s / float(ksize[0] * ksize[1])
        scope[op.output('Out')[0]] = out

    @_op('batch_norm')
    def _batch_norm(op, scope):
        x = scope[op.input('X')[0]]
        mean = scope[op.input('Mean')[0]]
        var = scope[op.input('Variance')[0]]
        scale = scope[op.input('Scale')[0]]
        bias = scope[op.input('Bias')[0]]
        eps = op.attr('epsilon', 1e-5)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        out = (x - mean.reshape(shape)) / jnp.sqrt(
            var.reshape(shape) + eps)
        out = out * scale.reshape(shape) + bias.reshape(shape)
        scope[op.output('Y')[0]] = out

    @_op('lookup_table_v2')
    def _lookup_v2(op, scope):
        w = scope[op.input('W')[0]]
        ids = scope[op.input('Ids')[0]]
        scope[op.output('Out')[0]] = jnp.take(w, ids, axis=0)

    @_op('lookup_table')
    def _lookup(op, scope):
        w = scope[op.input('W')[0]]
        ids = scope[op.input('Ids')[0]]
        scope[op.output('Out')[0]] = jnp.take(
            w, jnp.squeeze(ids, -1), axis=0)

    @_op('arg_max')
    def _arg_max(op, scope):
        x = scope[op.input('X')[0]]
        scope[op.output('Out')[0]] = jnp.argmax(
            x, axis=op.attr('axis', -1)).astype(jnp.int64)

    @_op('squeeze2')
    def _squeeze2(op, scope):
        x = scope[op.input('X')[0]]
        axes = tuple(op.attr('axes', []))
        scope[op.output('Out')[0]] = (
            jnp.squeeze(x, axis=axes) if axes else jnp.squeeze(x))

    @_op('unsqueeze2')
    def _unsqueeze2(op, scope):
        x = scope[op.input('X')[0]]
        out = x
        for ax in sorted(op.attr('axes', [])):
            out = jnp.expand_dims(out, ax)
        scope[op.output('Out')[0]] = out

    @_op('assign')
    def _assign(op, scope):
        scope[op.output('Out')[0]] = scope[op.input('X')[0]]

    @_op('cast')
    def _cast(op, scope):
        x = scope[op.input('X')[0]]
        scope[op.output('Out')[0]] = x.astype(
            _np_dtype(op.attr('out_dtype', 5)))

    @_op('slice')
    def _slice(op, scope):
        x = scope[op.input('Input')[0]]
        axes = op.attr('axes', [])
        starts = op.attr('starts', [])
        ends = op.attr('ends', [])
        idx = [slice(None)] * x.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = slice(st, en)
        out = x[tuple(idx)]
        dec = op.attr('decrease_axis', [])
        if dec:
            # dygraph-exported x[0]-style slices squeeze the unit dims
            out = jnp.squeeze(out, axis=tuple(dec))
        scope[op.output('Out')[0]] = out


_init_table()
