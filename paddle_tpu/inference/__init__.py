"""Inference engine (reference: paddle/fluid/inference/ AnalysisPredictor
api/analysis_predictor.h:53, AnalysisConfig paddle_analysis_config.h,
ZeroCopyTensor api/details/zero_copy_tensor.cc).

TPU-native (SURVEY.md §7.1): XLA is already the whole-graph compiler, so the
reference's 40-pass analysis pipeline + TensorRT subgraph offload collapse
to: load (jit.save artifact) -> AOT compile per input signature (the
"optimization") -> cached executable run with donated IO. The pass-pipeline
surface (Config.switch_ir_optim, enable_tensorrt_engine, ...) is kept and
maps to compile options.
"""
from .predictor import (Config, AnalysisConfig, Predictor,  # noqa: F401
                        AnalysisPredictor, create_predictor,
                        PrecisionType, PlaceType, Tensor as PaddleInferTensor)

__all__ = ['Config', 'AnalysisConfig', 'Predictor', 'AnalysisPredictor',
           'create_predictor', 'PrecisionType', 'PlaceType']
