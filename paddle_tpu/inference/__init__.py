"""Inference engine (reference: paddle/fluid/inference/ AnalysisPredictor
api/analysis_predictor.h:53, AnalysisConfig paddle_analysis_config.h,
ZeroCopyTensor api/details/zero_copy_tensor.cc).

TPU-native (SURVEY.md §7.1): XLA is already the whole-graph compiler, so the
reference's 40-pass analysis pipeline + TensorRT subgraph offload collapse
to: load (jit.save artifact) -> AOT compile per input signature (the
"optimization") -> cached executable run with donated IO. The pass-pipeline
surface (Config.switch_ir_optim, enable_tensorrt_engine, ...) is kept and
maps to compile options.
"""
from .predictor import (Config, AnalysisConfig, Predictor,  # noqa: F401
                        AnalysisPredictor, create_predictor,
                        PrecisionType, PlaceType, Tensor,
                        Tensor as PaddleInferTensor, get_version)


class DataType:
    """reference paddle_infer::DataType enum."""
    FLOAT32 = 'float32'
    INT64 = 'int64'
    INT32 = 'int32'
    UINT8 = 'uint8'
    INT8 = 'int8'
    FLOAT16 = 'float16'


_DTYPE_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
                DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2}


def get_num_bytes_of_data_type(dtype):
    return _DTYPE_BYTES[dtype]


class PredictorPool:
    """reference paddle_infer::services::PredictorPool: `size` predictors
    over one config for concurrent serving. The jitted executable cache is
    shared per-process by XLA, so the pool is cheap; each Retrieve(i)
    hands an independent Predictor (its own IO buffers).

    Registry-backed construction: `PredictorPool(registry=reg,
    model='m', version='v2')` resolves the artifact through a
    serving.registry.ModelRegistry instead of a hand-built Config —
    version=None follows the serving pointer, so a hot-swapped rollout
    changes what the NEXT pool loads without touching callers. The
    entry's content fingerprint is recorded on `self.fingerprint` (the
    compile-cache key dimension; same fingerprint == warm bring-up)."""

    def __init__(self, config=None, size=1, registry=None, model=None,
                 version=None):
        if size < 1:
            raise ValueError('pool size must be >= 1')
        self.fingerprint = None
        if registry is not None:
            if model is None:
                raise ValueError('registry-backed pool needs model=')
            entry = registry.resolve(model, version)
            self.fingerprint = entry.fingerprint
            if config is None:
                config = Config(model_path=entry.path)
            else:
                config.set_model(entry.path, config.params_file())
        if config is None:
            raise ValueError('need a Config or registry= + model=')
        self._preds = [create_predictor(config) for _ in range(size)]

    def retrive(self, idx):  # (sic) the reference binding's spelling
        if not 0 <= idx < len(self._preds):
            raise IndexError('predictor index %d out of range [0, %d)'
                             % (idx, len(self._preds)))
        return self._preds[idx]

    retrieve = retrive
    Retrieve = retrive


__all__ = ['Config', 'AnalysisConfig', 'Predictor', 'AnalysisPredictor',
           'create_predictor', 'PrecisionType', 'PlaceType', 'DataType',
           'Tensor', 'get_version', 'get_num_bytes_of_data_type',
           'PredictorPool']
