"""AnalysisPredictor-parity inference engine over AOT-compiled XLA.

Call stack parity (SURVEY.md §3.5): create_predictor(Config) loads the
jit.save artifact, "analysis" = jax.jit(...).lower().compile() per input
signature (cached), Run = cached-executable execution with buffer donation
of inputs (zero-copy contract).
"""
import os
import threading

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['Config', 'AnalysisConfig', 'Predictor', 'AnalysisPredictor',
           'create_predictor', 'PrecisionType', 'PlaceType', 'Tensor']


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    TPU = 4
    XPU = 2


class Config:
    """AnalysisConfig parity. The TensorRT/MKLDNN/IR switches are accepted;
    on TPU they all mean 'XLA compiles the whole graph' and only precision
    and device selection change behavior."""

    def __init__(self, model_path=None, params_path=None):
        self._model_path = model_path
        self._params_path = params_path
        self._device = 'tpu'
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._ir_optim = True
        self._memory_optim = True
        self._cache_dir = None
        self._trt = False
        self._cpu_math_threads = 1

    # -- model paths --------------------------------------------------------
    def set_model(self, model_path, params_path=None):
        self._model_path = model_path
        self._params_path = params_path

    def model_dir(self):
        return self._model_path

    def prog_file(self):
        return self._model_path

    def params_file(self):
        return self._params_path

    # -- device -------------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # GPU request maps to the accelerator backend (TPU here)
        self._device = 'tpu'
        self._device_id = device_id

    def enable_tpu(self, device_id=0):
        self._device = 'tpu'
        self._device_id = device_id

    def disable_gpu(self):
        self._device = 'cpu'

    def use_gpu(self):
        return self._device == 'tpu'

    def gpu_device_id(self):
        return self._device_id

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = n

    # -- optimization surface ------------------------------------------------
    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def switch_use_feed_fetch_ops(self, flag):
        pass

    def switch_specify_input_names(self, flag=True):
        pass

    def enable_tensorrt_engine(self, workspace_size=1 << 30, max_batch_size=1,
                               min_subgraph_size=3,
                               precision_mode=PrecisionType.Float32,
                               use_static=False, use_calib_mode=False):
        # TRT subgraph offload == whole-graph XLA on TPU; precision honored
        self._trt = True
        self._precision = precision_mode

    def tensorrt_engine_enabled(self):
        return self._trt

    def enable_mkldnn(self):
        pass

    def set_optim_cache_dir(self, path):
        self._cache_dir = path

    def enable_profile(self):
        pass

    def disable_glog_info(self):
        pass

    def summary(self):
        return ('device: %s, precision: %s, ir_optim(XLA): %s'
                % (self._device, self._precision, self._ir_optim))


AnalysisConfig = Config


class Tensor:
    """Input/output handle (ZeroCopyTensor parity)."""

    def __init__(self, name, predictor):
        self._name = name
        self._predictor = predictor

    def name(self):
        return self._name

    # input side
    def reshape(self, shape):
        self._predictor._input_shapes[self._name] = tuple(shape)

    def copy_from_cpu(self, data):
        self._predictor._check_input_name(self._name)
        self._predictor._inputs[self._name] = np.ascontiguousarray(data)

    def share_external_data(self, data):
        self._predictor._check_input_name(self._name)
        self._predictor._inputs[self._name] = np.asarray(data)

    # output side
    def copy_to_cpu(self):
        return np.asarray(self._predictor._outputs[self._name])

    def to_numpy(self):
        return self.copy_to_cpu()

    def shape(self):
        if self._name in self._predictor._outputs:
            return list(self._predictor._outputs[self._name].shape)
        return list(self._predictor._input_shapes.get(self._name, ()))

    def type(self):
        return PrecisionType.Float32


class Predictor:
    """AnalysisPredictor parity over a jit.save'd model."""

    def __init__(self, config):
        self._config = config
        self._inputs = {}
        self._outputs = {}
        self._input_shapes = {}
        self._compiled = {}
        self._lock = threading.Lock()
        self._load()

    @staticmethod
    def _is_fluid_artifact(path):
        """Reference-produced artifact? (__model__ / *.pdmodel ProgramDesc,
        analysis_predictor.cc:201 PrepareProgram's input format)."""
        if os.path.isdir(path):
            if os.path.exists(os.path.join(path, '__model__')):
                return True
            return any(f.endswith('.pdmodel') for f in os.listdir(path))
        return (path.endswith('.pdmodel')
                or os.path.basename(path) == '__model__')

    def _load_fluid(self, path):
        """Serve a reference-format model: ProgramDesc block 0 lowers to
        one XLA module via the fluid op table (fluid_program.py)."""
        from .fluid_program import load_fluid_model
        prog = load_fluid_model(path, self._config.params_file())
        self._fluid = prog
        self._layer = None
        self._translated = None
        self._buffers = {}
        self._params = prog.params
        self._input_names = list(prog.feed_names)

        def pure(params, *arrays):
            feeds = dict(zip(prog.feed_names, arrays))
            outs = prog._run_block(params, feeds)
            return tuple(outs) if len(outs) != 1 else outs[0]
        self._fn = pure

    def _enable_optim_cache(self):
        """Config.set_optim_cache_dir maps onto jax's persistent
        compilation cache (the reference persists its IR-pass/TensorRT
        engine cache there; here the compiled XLA executables persist, so
        a restarted server skips compilation entirely). Routed through
        framework.compile_cache — the one repo-wide configuration path —
        so several Predictors (or a Predictor plus the bench harness) in
        one process configure the cache once, idempotently."""
        cache_dir = self._config._cache_dir
        if not cache_dir:
            return
        from ..framework import compile_cache
        compile_cache.configure(cache_dir)

    def _load(self):
        from .. import jit as jit_mod
        from ..framework import functional as func_mod
        path = self._config.model_dir()
        if path is None:
            raise ValueError('Config.set_model(path) required')
        self._enable_optim_cache()
        if self._is_fluid_artifact(path):
            self._load_fluid(path)
            return
        self._translated = jit_mod.load(path)
        layer = self._translated._layer
        if layer is None:
            raise RuntimeError('model artifact missing architecture payload')
        layer.eval()
        if self._config._precision == PrecisionType.Bfloat16:
            layer.bfloat16()
        self._layer = layer
        self._params = func_mod.extract_params(layer)
        self._buffers = func_mod.extract_buffers(layer)
        # input names from the saved input spec when available; otherwise
        # arity is unknown until run() and positional input_<i> names are
        # accepted open-endedly
        meta = getattr(self._translated, '_meta', None) or {}
        spec = meta.get('input_spec')
        if spec:
            self._input_names = [
                (s[2] if len(s) > 2 and s[2] else 'input_%d' % i)
                for i, s in enumerate(spec)]
        else:
            # no saved spec: derive arity from forward's required
            # positional params so get_input_names() stays discoverable;
            # variadic forwards stay fully dynamic (None)
            self._input_names = None
            import inspect
            try:
                sig = inspect.signature(layer.forward)
                ps = list(sig.parameters.values())
                if not any(p.kind == p.VAR_POSITIONAL for p in ps):
                    req = [p for p in ps
                           if p.kind in (p.POSITIONAL_ONLY,
                                         p.POSITIONAL_OR_KEYWORD)
                           and p.default is p.empty]
                    self._input_names = ['input_%d' % i
                                         for i in range(len(req))]
            except (TypeError, ValueError):
                pass
        self._fn = self._make_fn()

    def _make_fn(self):
        from ..framework import functional as func_mod
        layer = self._layer
        buffers = self._buffers

        def pure(params, *arrays):
            out, _ = func_mod.functional_call(layer, params, buffers,
                                              args=arrays, training=False)
            return out
        return pure

    def _check_input_name(self, name):
        if self._input_names is not None:
            if name not in self._input_names:
                raise ValueError(
                    'unknown input %r; model inputs are %s'
                    % (name, self._input_names))
        elif not (name.startswith('input_')
                  and name[len('input_'):].isdigit()):
            raise ValueError(
                'model was saved without an input spec; use positional '
                'names input_0..input_<n-1>, got %r' % name)

    def _gather_inputs(self):
        """Assemble run arguments in declared order, failing loudly on
        missing inputs instead of silently dropping them."""
        if self._input_names is not None:
            missing = [n for n in self._input_names if n not in self._inputs]
            if missing:
                raise ValueError('inputs not set: %s' % missing)
            return [self._inputs[n] for n in self._input_names]
        idx = sorted(int(n[len('input_'):]) for n in self._inputs)
        if idx != list(range(len(idx))):
            raise ValueError(
                'positional inputs must be contiguous input_0..input_%d, '
                'got %s' % (len(idx) - 1, sorted(self._inputs)))
        return [self._inputs['input_%d' % i] for i in idx]

    # -- handles -------------------------------------------------------------
    def get_input_names(self):
        if self._input_names is not None:
            return list(self._input_names)
        return sorted(self._inputs, key=lambda n: int(n[len('input_'):]))

    def get_input_handle(self, name):
        return Tensor(name, self)

    def get_input_tensor(self, name):
        return Tensor(name, self)

    def get_output_names(self):
        return list(self._outputs.keys()) or ['output_0']

    def get_output_handle(self, name):
        return Tensor(name, self)

    def get_output_tensor(self, name):
        return Tensor(name, self)

    # -- run ------------------------------------------------------------------
    def run(self, input_list=None):
        """ZeroCopyRun: compile-once per signature, then cached executes."""
        if input_list is not None:
            # paddle-inference python API: run([np arrays]) -> [np arrays]
            arrays = [np.asarray(a) for a in input_list]
        else:
            arrays = self._gather_inputs()
        sig = tuple((a.shape, str(a.dtype)) for a in arrays)
        with self._lock:
            if sig not in self._compiled:
                jitted = jax.jit(self._fn)
                lowered = jitted.lower(self._params,
                                       *[jnp.asarray(a) for a in arrays])
                self._compiled[sig] = lowered.compile()
            executable = self._compiled[sig]
        out = executable(self._params, *[jnp.asarray(a) for a in arrays])
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = {'output_%d' % i: np.asarray(o)
                         for i, o in enumerate(outs)}
        if input_list is not None:
            return [self._outputs['output_%d' % i] for i in range(len(outs))]
        return True

    def clone(self):
        return Predictor(self._config)

    def decode_engine(self, num_slots=8, max_len=None, prefill_chunk=16,
                      decode_block=4, paged=False, **paged_kwargs):
        """Continuous-batching front door over the loaded model.

        Only meaningful when the artifact is a causal LM with the slot-
        cache decode path (GPTForCausalLM); anything else fails here
        with a clear error instead of deep inside the first step().
        `paged=True` returns the page-granular engine (prefix sharing,
        optional speculative decoding); extra keyword args — page_size,
        num_pages, spec_k, prefix_cache, ... — pass through to it.
        """
        layer = self._layer
        if layer is None or not (hasattr(layer, 'generate')
                                 and hasattr(layer, 'gpt')
                                 and hasattr(layer, 'config')):
            raise TypeError(
                'decode_engine() needs a causal-LM artifact '
                '(GPTForCausalLM with a KV-cache decode path); loaded '
                'model is %s' % type(layer).__name__)
        if paged:
            from ..serving import PagedContinuousBatchingEngine
            return PagedContinuousBatchingEngine(
                layer, num_seqs=num_slots, max_len=max_len,
                prefill_chunk=prefill_chunk, decode_block=decode_block,
                **paged_kwargs)
        if paged_kwargs:
            raise TypeError('decode_engine() got paged-only arguments %r '
                            'without paged=True' % sorted(paged_kwargs))
        from ..serving import ContinuousBatchingEngine
        return ContinuousBatchingEngine(
            layer, num_slots=num_slots, max_len=max_len,
            prefill_chunk=prefill_chunk, decode_block=decode_block)

    def decode_gateway(self, replicas=2, router=None, autoscaler=None,
                       registry=None, **engine_kwargs):
        """Multi-replica serving front door: a ServingGateway whose
        replica factory clones this predictor's artifact into fresh
        decode engines (the reference's fleet-of-AnalysisPredictors
        deployment shape, in one process). Engine construction kwargs
        — num_slots, max_len, paged=True, page_size, ... — pass through
        to decode_engine() per replica."""
        # non-causal-LM artifacts fail in the first factory call (the
        # gateway builds its initial replicas eagerly), with
        # decode_engine()'s clear TypeError
        from ..serving import ServingGateway
        return ServingGateway(
            lambda: self.decode_engine(**engine_kwargs),
            replicas=replicas, router=router, autoscaler=autoscaler,
            registry=registry)

    def clear_intermediate_tensor(self):
        self._outputs = {}

    def try_shrink_memory(self):
        pass


AnalysisPredictor = Predictor


def create_predictor(config):
    return Predictor(config)


def create_paddle_predictor(config):
    return Predictor(config)


def get_version():
    from .. import __version__
    return __version__
