"""auto_cast context (reference: python/paddle/amp/auto_cast.py,
imperative/amp_auto_cast.cc allow/block lists)."""
import contextlib

from ..framework import dtype as dtype_mod

# reference amp lists (imperative/amp_auto_cast.cc:28-73)
WHITE_LIST = {'conv2d', 'matmul', 'matmul_v2', 'mul', 'linear', 'conv1d',
              'conv3d', 'einsum', 'bmm', 'mm'}
BLACK_LIST = {'exp', 'square', 'log', 'mean', 'sum', 'cos_sim',
              'softmax_with_cross_entropy', 'cross_entropy',
              'layer_norm', 'batch_norm', 'softmax', 'log_softmax'}

_STATE = {'enabled': False, 'dtype': 'float16', 'level': 'O1',
          'custom_white': set(), 'custom_black': set()}


def _install_hook():
    from ..framework import core
    core._amp_cast_hook[0] = _hook


def _hook(name, arrays):
    if not _STATE['enabled']:
        return arrays
    return amp_cast_inputs(name, arrays)


def white_list():
    return (WHITE_LIST | _STATE['custom_white']) - _STATE['custom_black']


def black_list():
    return (BLACK_LIST | _STATE['custom_black']) - _STATE['custom_white']


def amp_state():
    return _STATE


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level='O1', dtype='float16'):
    prev = dict(_STATE)
    _STATE['enabled'] = enable
    _STATE['dtype'] = dtype_mod.convert_dtype(dtype)
    _STATE['level'] = level
    _STATE['custom_white'] = set(custom_white_list or ())
    _STATE['custom_black'] = set(custom_black_list or ())
    try:
        yield
    finally:
        _STATE.update(prev)


amp_guard = auto_cast


def amp_cast_inputs(op_name, arrays):
    """Called by the op runner when amp is on: cast per the lists."""
    import jax.numpy as jnp
    if not _STATE['enabled']:
        return arrays
    target = dtype_mod.to_jax_dtype(_STATE['dtype'])
    if _STATE['level'] == 'O2':
        cast_it = op_name not in black_list()
    else:
        cast_it = op_name in white_list()
    if not cast_it:
        # black list ops compute in fp32
        return [a.astype(jnp.float32)
                if a.dtype in (jnp.float16, jnp.bfloat16) else a
                for a in arrays]
    return [a.astype(target) if jnp.issubdtype(a.dtype, jnp.floating) else a
            for a in arrays]


_install_hook()
