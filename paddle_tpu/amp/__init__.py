"""AMP (reference: python/paddle/amp/ + imperative/amp_auto_cast.cc).

TPU-native: bf16 is the native mixed-precision dtype; auto_cast casts
matmul/conv inputs to the target dtype (the reference's allow-list
mechanism), and GradScaler keeps the fp16 loss-scaling contract (a no-op
state machine for bf16, fully functional for fp16).
"""
from .auto_cast import auto_cast, amp_guard, white_list, black_list  # noqa: F401
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401

__all__ = ['auto_cast', 'GradScaler']
