"""GradScaler (reference: python/paddle/amp/grad_scaler.py +
operators/amp/check_finite_and_unscale_op, update_loss_scaling_op)."""
import jax.numpy as jnp

from ..framework.core import Tensor, no_grad_guard

__all__ = ['GradScaler', 'AmpScaler']


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=65536.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    @no_grad_guard()
    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        self._unscaled = True
        params = optimizer._parameter_list or []
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is None:
                continue
            g = p.grad._data * inv
            finite = bool(jnp.all(jnp.isfinite(g)))
            if not finite:
                found = True
            p.grad = Tensor(g)
        self._found_inf = found

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {'scale': self._scale, 'incr_ratio': self._incr_ratio,
                'decr_ratio': self._decr_ratio,
                'good_steps': self._good_steps, 'bad_steps': self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get('scale', self._scale)
        self._good_steps = state.get('good_steps', 0)
        self._bad_steps = state.get('bad_steps', 0)


AmpScaler = GradScaler
