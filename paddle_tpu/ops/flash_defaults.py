"""Flash-attention block-size knob defaults — the ONE copy.

Pure module (no jax import): bench.py's parent process reads it to
record/replay capture rows without touching a backend, and
ops/flash_attention.py reads it at import to configure the kernels.
Defaults are the measured in-window optima on v5e
(docs/bench_inwindow_r5.jsonl): 512/512 fwd blocks beat 256/512 by 5%
on the BERT-base rung; the long-path kernels stage O(block) bytes so
they prefer a wider KV block (8k rung: 285.6 ms at 512/1024 vs 426.6 ms
at 256/512).
"""
import os

BLOCK_Q = 512
BLOCK_K = 512
BLOCK_Q_LONG = 512
BLOCK_K_LONG = 1024
LONG_SEQ = 4096
FUSED_BWD = True


def env_int(name, default):
    return int(os.environ.get(name, default))


def resolve():
    """Effective knob values under the current environment. The bwd
    blocks inherit the (possibly overridden) fwd blocks when unset."""
    bq = env_int('PADDLE_TPU_FLASH_BLOCK_Q', BLOCK_Q)
    bk = env_int('PADDLE_TPU_FLASH_BLOCK_K', BLOCK_K)
    return {
        'block_q': bq,
        'block_k': bk,
        'block_q_bwd': env_int('PADDLE_TPU_FLASH_BLOCK_Q_BWD', bq),
        'block_k_bwd': env_int('PADDLE_TPU_FLASH_BLOCK_K_BWD', bk),
        'block_q_long': env_int('PADDLE_TPU_FLASH_BLOCK_Q_LONG',
                                BLOCK_Q_LONG),
        'block_k_long': env_int('PADDLE_TPU_FLASH_BLOCK_K_LONG',
                                BLOCK_K_LONG),
        'long_seq': env_int('PADDLE_TPU_FLASH_LONG_SEQ', LONG_SEQ),
        'fused_bwd': os.environ.get(
            'PADDLE_TPU_FLASH_FUSED_BWD',
            '1' if FUSED_BWD else '0') != '0',
    }
