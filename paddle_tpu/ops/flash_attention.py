"""Flash attention Pallas kernel (TPU).

Blockwise streaming softmax (Dao et al.) with custom VJP; the replacement for
the reference's fused attention CUDA ops (operators/fused/). Falls back to
the jnp reference on non-TPU backends.
"""
import functools
import math

import jax
import jax.numpy as jnp

_DEFAULT_BLOCK_Q = 512
_DEFAULT_BLOCK_K = 512


def is_available():
    try:
        return jax.devices()[0].platform == 'tpu'
    except Exception:
        return False


def _ref_bhnd(q, k, v, causal, scale):
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k) * scale
    if causal:
        n, m = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((n, m), bool)), s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum('bhqk,bhkd->bhqd', p, v)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal,
                      block_k, seq_k):
    from jax.experimental import pallas as pl

    q = q_ref[...].astype(jnp.float32) * scale
    block_q, head_dim = q.shape
    qi = pl.program_id(2)

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, head_dim), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m_prev, l_prev, acc_prev = carry
        k_blk = pl.load(k_ref, (pl.dslice(kb * block_k, block_k),
                                pl.dslice(None))).astype(jnp.float32)
        v_blk = pl.load(v_ref, (pl.dslice(kb * block_k, block_k),
                                pl.dslice(None))).astype(jnp.float32)
        s = q @ k_blk.T  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        alpha = jnp.exp(m_prev - m_cur)
        l_cur = alpha * l_prev + jnp.sum(p, axis=1)
        acc_cur = acc_prev * alpha[:, None] + p @ v_blk
        return m_cur, l_cur, acc_cur

    if causal:
        # only iterate over blocks at or before the diagonal
        last = jnp.minimum(num_kb, (qi + 1) * block_q // block_k + 1)
    else:
        last = num_kb
    m, l, acc = jax.lax.fori_loop(0, last, body, (m, l, acc))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_bhnd(q, k, v, causal, scale):
    return _flash_fwd(q, k, v, causal, scale)


def _flash_fwd_impl(q, k, v, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, n, d = q.shape
    m = k.shape[2]
    block_q = min(_DEFAULT_BLOCK_Q, n)
    block_k = min(_DEFAULT_BLOCK_K, m)
    if n % block_q or m % block_k or d % 128:
        return _ref_bhnd(q, k, v, causal, scale)

    grid = (b, h, n // block_q)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_k=m)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, n, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, m, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, m, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(q, k, v)


def strict_mode():
    """PADDLE_TPU_FLASH_STRICT=1 (set by bench/TPU tests): a Pallas
    failure must surface, not silently fall back to the jnp reference —
    a fallback would invalidate any reported TPU number."""
    import os
    return os.environ.get('PADDLE_TPU_FLASH_STRICT', '0') == '1'


def _flash_fwd(q, k, v, causal, scale):
    if strict_mode():
        return _flash_fwd_impl(q, k, v, causal, scale)
    try:
        return _flash_fwd_impl(q, k, v, causal, scale)
    except Exception:
        return _ref_bhnd(q, k, v, causal, scale)


def _fwd_rule(q, k, v, causal, scale):
    o = _flash_fwd(q, k, v, causal, scale)
    return o, (q, k, v)


def _bwd_rule(causal, scale, res, do):
    q, k, v = res
    # recomputed reference backward (flash-bwd kernel is a later optimization;
    # XLA still fuses this well and it is numerically exact)
    _, vjp = jax.vjp(lambda a, b, c: _ref_bhnd(a, b, c, causal, scale), q, k, v)
    return vjp(do)


_flash_bhnd.defvjp(_fwd_rule, _bwd_rule)


def flash_attention_bnhd(q, k, v, causal=False, scale=None):
    """Paddle layout [B, N, H, D] in/out."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = _flash_bhnd(qt, kt, vt, causal, scale)
    return jnp.swapaxes(o, 1, 2)


def flash_attention_bhnd(q, k, v, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_bhnd(q, k, v, causal, scale)
