"""Flash attention Pallas kernels (TPU): forward AND backward.

Blockwise streaming softmax (Dao et al.) with a custom VJP whose backward
is also a pair of Pallas kernels (dq, and dk/dv), so neither direction
materializes the [n, m] attention matrix in HBM — the replacement for the
reference's fused attention CUDA ops (operators/fused/).

head_dim needs only %64 == 0 (BERT/GPT-base d=64 runs the kernel; the MXU
contracts 64-wide fine, Mosaic pads lanes). Sequence lengths must divide
the block sizes; anything else falls back to the jnp reference — loudly
under PADDLE_TPU_FLASH_STRICT=1, where a silent fallback would invalidate
a reported TPU number.

PADDLE_TPU_FLASH_INTERPRET=1 runs the kernels through the Pallas
interpreter on CPU — the hardware-free correctness path for tests.
"""
import functools
import math
import os

import jax
import jax.numpy as jnp

from . import flash_defaults as _fd

# knob values latched at import (each bench child re-imports); the
# defaults, their rationale, and the bwd-inherits-fwd rule live in ONE
# place: ops/flash_defaults.py (bench.py records/replays from the same
# table)
_knobs = _fd.resolve()
_DEFAULT_BLOCK_Q = _knobs['block_q']
_DEFAULT_BLOCK_K = _knobs['block_k']
_BLOCK_Q_BWD = _knobs['block_q_bwd']
_BLOCK_K_BWD = _knobs['block_k_bwd']
_BLOCK_Q_LONG = _knobs['block_q_long']
_BLOCK_K_LONG = _knobs['block_k_long']
_NEG_INF = -1e30


def is_available():
    if os.environ.get('PADDLE_TPU_FLASH_DISABLE', '0') == '1':
        return False  # explicit off-switch (bench retry safety valve)
    if interpret_mode():
        return True
    try:
        return jax.devices()[0].platform == 'tpu'
    except Exception:
        return False


def strict_mode():
    """PADDLE_TPU_FLASH_STRICT=1 (set by bench/TPU tests): ANY fallback to
    the jnp reference — including a shape-based one — must raise, not
    silently return; a fallback would invalidate any reported TPU number."""
    return os.environ.get('PADDLE_TPU_FLASH_STRICT', '0') == '1'


def interpret_mode():
    return os.environ.get('PADDLE_TPU_FLASH_INTERPRET', '0') == '1'


def _supported(q, k, v):
    """None if the Pallas kernels can run on these shapes, else the reason."""
    b, h, n, d = q.shape
    m = k.shape[2]
    if not (q.dtype == k.dtype == v.dtype):
        # the kernels contract in the operands' native dtype (_mm_f32);
        # lax.dot_general has no implicit promotion, so mixed dtypes must
        # take the documented fallback path rather than an opaque error
        return 'mixed operand dtypes (%s, %s, %s)' % (q.dtype, k.dtype,
                                                      v.dtype)
    if d % 64:
        return 'head_dim %d %% 64 != 0' % d
    # validate against the blocks the dispatched path will actually use:
    # the long path has its own (wider) block defaults, and the standard
    # backward blocks are independently overridable
    if _use_long_path(n, m):
        if _long_blocks(n, m) is None:
            return 'seq (%d, %d) not tileable by any long-path block' \
                % (n, m)
    elif _std_blocks(n, m) is None or _std_bwd_blocks(n, m) is None:
        return 'seq (%d, %d) not tileable by any standard-path block' \
            % (n, m)
    if n % 8 or m % 128:
        return 'seq (%d, %d) below TPU tile granularity' % (n, m)
    if not interpret_mode():
        # the interpreter has no VMEM; the footprint gate only guards
        # real Mosaic compiles
        return _vmem_reason(n, m, d, q.dtype.itemsize)
    return None


def _ref_bhnd(q, k, v, causal, scale):
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k) * scale
    if causal:
        # bottom-right aligned: query i is at absolute position m-n+i
        # (KV-cache decode correctness; flash-attn convention)
        n, m = s.shape[-2], s.shape[-1]
        if n > m:
            raise ValueError(
                'causal attention with more queries (%d) than keys (%d)'
                % (n, m))
        s = jnp.where(jnp.tril(jnp.ones((n, m), bool), m - n), s,
                      _NEG_INF)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum('bhqk,bhkd->bhqd', p, v)


# -- forward -----------------------------------------------------------------

def _causal_mask(s, q_start, k_start):
    """Mask scores [bq, bk] whose global k position exceeds the global q
    position (top-left-aligned causal; the kernels' n == m contract —
    cross-length causal routes to blockwise before any kernel runs).
    q_start/k_start are the blocks' global offsets."""
    bq, bk = s.shape
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _mm_f32(a, b, transpose_a=False, transpose_b=False):
    """a @ b (with either operand logically transposed) in the operands'
    NATIVE dtype with f32 MXU accumulation (preferred_element_type).
    Upcasting the operands to f32 before the dot would run the systolic
    array at its f32 rate — ~8x slower than bf16 on v5e — for zero
    accuracy gain over f32-accumulated bf16, which is the standard
    flash-attention numeric contract. The transposes are expressed as
    contracting-dimension choices so Mosaic folds them into the MXU feed
    instead of materializing a relayout."""
    dims = (((0 if transpose_a else 1,), (1 if transpose_b else 0,)),
            ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, seq_k):
    from jax.experimental import pallas as pl

    q = q_ref[...]
    block_q, head_dim = q.shape
    qi = pl.program_id(2)

    m_i = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l_i = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, head_dim), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m_prev, l_prev, acc_prev = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = _mm_f32(q, k_blk, transpose_b=True) * scale  # [bq, bk] f32
        if causal:
            s = _causal_mask(s, qi * block_q, kb * block_k)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        alpha = jnp.exp(m_prev - m_cur)
        l_cur = alpha * l_prev + jnp.sum(p, axis=1)
        acc_cur = acc_prev * alpha[:, None] + \
            _mm_f32(p.astype(v_blk.dtype), v_blk)
        return m_cur, l_cur, acc_cur

    if causal:
        # only iterate over blocks at or before the diagonal
        last = jnp.minimum(num_kb, (qi + 1) * block_q // block_k + 1)
    else:
        last = num_kb
    m_i, l_i, acc = jax.lax.fori_loop(0, last, body, (m_i, l_i, acc))
    l_safe = jnp.maximum(l_i, 1e-30)
    o_ref[...] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse carries a trailing singleton dim: Mosaic wants >=2-D blocks with
    # an aligned (or full) minor dimension
    lse_ref[...] = (m_i + jnp.log(l_safe))[:, None]


def _fwd_impl(q, k, v, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, n, d = q.shape
    m = k.shape[2]
    block_q, block_k = _std_blocks(n, m)

    grid = (b, h, n // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_k=m)
    kwargs = {}
    if interpret_mode():
        kwargs['interpret'] = True
    else:
        kwargs['compiler_params'] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    o, lse = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((b, h, n, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, n, 1), jnp.float32)],
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, m, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, m, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, 1),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        **kwargs,
    )(q, k, v)
    return o, lse


# -- long-sequence kernels ---------------------------------------------------
#
# The short-seq kernels above stage the FULL K/V (and in the dk/dv pass,
# full Q/dO) into VMEM per grid cell and fori_loop over them — simple and
# fast at seq <= ~4k, but at 8192 the staged operands plus the loop-body
# temporaries exceed scoped VMEM (the r4 in-window failure:
# "kernel-vmem-stack-oom", docs/bench_inwindow_r4.jsonl 11:58). The long
# variants below use the canonical Mosaic structure instead: the KV (or
# Q) walk is the LAST grid dimension ("arbitrary" = sequential on TPU),
# each cell sees one [block, d] tile, and the online-softmax carry lives
# in VMEM scratch that persists across sequential grid steps. Staged
# bytes are then O(block) regardless of sequence length.

_LONG_SEQ = _knobs['long_seq']


def _use_long_path(n, m):
    if os.environ.get('PADDLE_TPU_FLASH_FORCE_LONG', '0') == '1':
        return True
    return max(n, m) >= _LONG_SEQ


def _fit_block(desired, dim):
    """Largest block <= desired that divides dim (halving from desired,
    floor 128 — the TPU lane tile; dims at/below 128 run as ONE block,
    preserving the old min(block, dim) behavior for short q). None if
    nothing fits: the caller routes to the fallback instead of
    truncating the walk."""
    if dim <= 128:
        return dim
    b = min(desired, dim)
    while b >= 128:
        if dim % b == 0:
            return b
        b //= 2
    return None


def _clamped(desired_q, desired_k, n, m):
    """(block_q, block_k) clamped so every sequence that divides SOME
    power-of-two block >= 128 stays on the kernel (e.g. seq 4608 runs
    the long path at 512/512 when the preferred 1024 KV block doesn't
    divide it; seq 768 runs the standard path at 256), or None if the
    shape can't tile."""
    bq = _fit_block(desired_q, n)
    bk = _fit_block(desired_k, m)
    if bq is None or bk is None:
        return None
    return bq, bk


def _long_blocks(n, m):
    return _clamped(_BLOCK_Q_LONG, _BLOCK_K_LONG, n, m)


def _std_blocks(n, m):
    return _clamped(_DEFAULT_BLOCK_Q, _DEFAULT_BLOCK_K, n, m)


def _std_bwd_blocks(n, m):
    return _clamped(_BLOCK_Q_BWD, _BLOCK_K_BWD, n, m)


# -- scoped-VMEM footprint gate ----------------------------------------------
#
# The block clamp above only guarantees DIVISIBILITY; it happily launched
# configs whose working set Mosaic cannot hold. The in-window failure it
# must refuse: seq 4096 on the STANDARD kernels at 512/1024 blocks died
# compiling with "kernel-vmem-stack-oom" (docs/bench_inwindow_r5.jsonl
# 09:32:35Z), while 2048 at the same blocks and 4096 at 256/512 both ran.
# The discriminating cost in those captures is the sequential walk: each
# fori_loop step's f32 score tile [block_q, block_k] lands on the scoped
# stack, so the standard kernels' footprint grows with steps x tile while
# the long kernels (grid-walked, one tile per cell) stay O(block). The
# estimate below — walk steps x score-tile bytes plus the double-buffered
# staged operand windows — reproduces every observed pass/fail with >2 MiB
# margin against a 12 MiB budget (VMEM is ~16 MiB/core; the margin leaves
# room for Mosaic's own buffers). Rejection routes through _supported, so
# strict mode raises and non-strict falls back to the reference.

_VMEM_BUDGET_MB_DEFAULT = 12


def _vmem_budget_bytes():
    return int(os.environ.get('PADDLE_TPU_FLASH_VMEM_BUDGET_MB',
                              _VMEM_BUDGET_MB_DEFAULT)) * 1024 * 1024


def _vmem_reason(n, m, d, itemsize):
    """None if every dispatched pass fits the scoped-VMEM budget, else a
    reason naming the worst pass, its estimate, and the knobs to turn."""
    if _use_long_path(n, m):
        bq, bk = _long_blocks(n, m)
        tiles = (bq + 2 * bk) * d * itemsize      # q + k/v tiles per cell
        passes = [('long fwd', 1, bq, bk, tiles + bq * d * 4),
                  ('long dq', 1, bq, bk, tiles + bq * d * 4),
                  ('long dk/dv', 1, bq, bk, tiles + 2 * bk * d * 4)]
    else:
        bq, bk = _std_blocks(n, m)
        bqb, bkb = _std_bwd_blocks(n, m)
        passes = [('fwd', m // bk, bq, bk, (2 * m + 2 * bq) * d * itemsize)]
        if bqb == n and bkb == m and _fused_bwd_enabled():
            passes.append(('fused bwd', 1, n, m, 4 * n * d * itemsize))
        else:
            passes.append(('dq', m // bkb, bqb, bkb,
                           (2 * m + 2 * bqb) * d * itemsize))
            passes.append(('dk/dv', n // bqb, bqb, bkb,
                           (2 * n + 2 * bkb) * d * itemsize))
    budget = _vmem_budget_bytes()
    for name, steps, pbq, pbk, staged in passes:
        est = steps * pbq * pbk * 4 + 2 * staged
        if est > budget:
            return ('blocks (%d, %d) at seq (%d, %d) cannot fit: the %s '
                    'pass needs ~%.1f MiB scoped VMEM (%d sequential '
                    'score tile(s) of %dx%d f32 plus staged operands) '
                    'but the budget is %d MiB '
                    '(PADDLE_TPU_FLASH_VMEM_BUDGET_MB); shrink the '
                    'PADDLE_TPU_FLASH_BLOCK_* knobs or lower '
                    'PADDLE_TPU_FLASH_LONG_SEQ to take the long-kernel '
                    'path'
                    % (pbq, pbk, n, m, name, est / 2 ** 20, steps, pbq,
                       pbk, _vmem_budget_bytes() // 2 ** 20))
    return None


def _fwd_kernel_long(q_ref, k_ref, v_ref, o_ref, lse_ref,
                     m_scr, l_scr, acc_scr, *, scale, causal, num_kb,
                     block_q, block_k):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: whole block above the diagonal contributes nothing
    diag_ok = True
    if causal:
        diag_ok = kb * block_k <= (qi + 1) * block_q - 1

    @pl.when(diag_ok)
    def _step():
        q = q_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = _mm_f32(q, k_blk, transpose_b=True) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, kb * block_k)
        m_prev = m_scr[...][:, 0]
        l_prev = l_scr[...][:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        alpha = jnp.exp(m_prev - m_cur)
        l_cur = alpha * l_prev + jnp.sum(p, axis=1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + \
            _mm_f32(p.astype(v_blk.dtype), v_blk)
        m_scr[...] = m_cur[:, None]
        l_scr[...] = l_cur[:, None]

    @pl.when(kb == num_kb - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[...][:, 0], 1e-30)
        o_ref[...] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[...] = (m_scr[...][:, 0] + jnp.log(l_safe))[:, None]


def _fwd_impl_long(q, k, v, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, n, d = q.shape
    m = k.shape[2]
    block_q, block_k = _long_blocks(n, m)
    num_kb = m // block_k

    grid = (b, h, n // block_q, num_kb)
    kernel = functools.partial(_fwd_kernel_long, scale=scale, causal=causal,
                               num_kb=num_kb, block_q=block_q,
                               block_k=block_k)
    kwargs = {}
    if interpret_mode():
        kwargs['interpret'] = True
    else:
        kwargs['compiler_params'] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    o, lse = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((b, h, n, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, n, 1), jnp.float32)],
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        **kwargs,
    )(q, k, v)
    return o, lse


def _bwd_dq_kernel_long(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, dq_scr, *, scale, causal, num_kb,
                        block_q, block_k):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    kb = pl.program_id(3)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    diag_ok = True
    if causal:
        diag_ok = kb * block_k <= (qi + 1) * block_q - 1

    @pl.when(diag_ok)
    def _step():
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]
        delta = delta_ref[...]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = _mm_f32(q, k_blk, transpose_b=True) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, kb * block_k)
        p = jnp.exp(jnp.minimum(s - lse, 30.0))  # see _bwd_dq_kernel
        dp = _mm_f32(do, v_blk, transpose_b=True)
        ds = p * (dp - delta) * scale
        dq_scr[...] = dq_scr[...] + _mm_f32(ds.astype(k_blk.dtype), k_blk)

    @pl.when(kb == num_kb - 1)
    def _finish():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_long(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                         num_qb, block_q, block_k):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qb = pl.program_id(3)

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    diag_ok = True
    if causal:
        # rows strictly above the diagonal see nothing of this k block
        diag_ok = (qb + 1) * block_q - 1 >= ki * block_k

    @pl.when(diag_ok)
    def _step():
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        q_b = q_ref[...]
        do_b = do_ref[...]
        lse_b = lse_ref[...]
        delta_b = delta_ref[...]
        s = _mm_f32(q_b, k_blk, transpose_b=True) * scale
        if causal:
            s = _causal_mask(s, qb * block_q, ki * block_k)
        p = jnp.exp(jnp.minimum(s - lse_b, 30.0))
        dv_scr[...] = dv_scr[...] + _mm_f32(p.astype(do_b.dtype), do_b,
                                            transpose_a=True)
        dp = _mm_f32(do_b, v_blk, transpose_b=True)
        ds = p * (dp - delta_b) * scale
        dk_scr[...] = dk_scr[...] + _mm_f32(ds.astype(q_b.dtype), q_b,
                                            transpose_a=True)

    @pl.when(qb == num_qb - 1)
    def _finish():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_impl_long(q, k, v, o, lse, do, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, n, d = q.shape
    m = k.shape[2]
    block_q, block_k = _long_blocks(n, m)
    num_kb = m // block_k
    num_qb = n // block_q

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [b, h, n, 1]

    kwargs = {}
    if interpret_mode():
        kwargs['interpret'] = True
    else:
        kwargs['compiler_params'] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))

    qspec = pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kspec_q = pl.BlockSpec((None, None, block_k, d),
                           lambda bi, hi, qi, ki: (bi, hi, ki, 0))
    rowq = pl.BlockSpec((None, None, block_q, 1),
                        lambda bi, hi, qi, ki: (bi, hi, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_long, scale=scale, causal=causal,
                          num_kb=num_kb, block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((b, h, n, d), q.dtype),
        grid=(b, h, num_qb, num_kb),
        in_specs=[qspec, kspec_q, kspec_q, qspec, rowq, rowq],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        **kwargs,
    )(q, k, v, do, lse, delta)

    # dk/dv: k block is the parallel axis, q walk is sequential
    qspec_k = pl.BlockSpec((None, None, block_q, d),
                           lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    kspec = pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0))
    rowq_k = pl.BlockSpec((None, None, block_q, 1),
                          lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_long, scale=scale, causal=causal,
                          num_qb=num_qb, block_q=block_q, block_k=block_k),
        out_shape=[jax.ShapeDtypeStruct((b, h, m, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, m, d), v.dtype)],
        grid=(b, h, m // block_k, num_qb),
        in_specs=[qspec_k, kspec, kspec, qspec_k, rowq_k, rowq_k],
        out_specs=[kspec, kspec],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        **kwargs,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# -- backward ----------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_k, seq_k):
    from jax.experimental import pallas as pl

    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...]     # [bq, 1]
    delta = delta_ref[...]  # [bq, 1]
    block_q, head_dim = q.shape
    qi = pl.program_id(2)

    dq = jnp.zeros((block_q, head_dim), jnp.float32)
    num_kb = seq_k // block_k

    def body(kb, dq_prev):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = _mm_f32(q, k_blk, transpose_b=True) * scale
        if causal:
            s = _causal_mask(s, qi * block_q, kb * block_k)
        # clamped exp: for valid rows s - lse <= ~0; the headroom only
        # matters when a caller (ring attention) zero-weights a block it
        # computed unmasked — without the clamp an overflowing exp would
        # turn 0 * inf into NaN
        p = jnp.exp(jnp.minimum(s - lse, 30.0))
        dp = _mm_f32(do, v_blk, transpose_b=True)
        ds = p * (dp - delta) * scale
        return dq_prev + _mm_f32(ds.astype(k_blk.dtype), k_blk)

    if causal:
        last = jnp.minimum(num_kb, (qi + 1) * block_q // block_k + 1)
    else:
        last = num_kb
    dq = jax.lax.fori_loop(0, last, body, dq)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, seq_q):
    from jax.experimental import pallas as pl

    k_blk = k_ref[...]
    v_blk = v_ref[...]
    block_k, head_dim = k_blk.shape
    ki = pl.program_id(2)

    dk = jnp.zeros((block_k, head_dim), jnp.float32)
    dv = jnp.zeros((block_k, head_dim), jnp.float32)
    num_qb = seq_q // block_q

    def body(qb, carry):
        dk_prev, dv_prev = carry
        q_b = q_ref[pl.ds(qb * block_q, block_q), :]
        do_b = do_ref[pl.ds(qb * block_q, block_q), :]
        lse_b = lse_ref[pl.ds(qb * block_q, block_q), :]      # [bq, 1]
        delta_b = delta_ref[pl.ds(qb * block_q, block_q), :]  # [bq, 1]
        s = _mm_f32(q_b, k_blk, transpose_b=True) * scale  # [bq, bk]
        if causal:
            s = _causal_mask(s, qb * block_q, ki * block_k)
        p = jnp.exp(jnp.minimum(s - lse_b, 30.0))  # [bq, bk]; see dq kernel
        dv_cur = dv_prev + _mm_f32(p.astype(do_b.dtype), do_b,
                                   transpose_a=True)
        dp = _mm_f32(do_b, v_blk, transpose_b=True)  # [bq, bk]
        ds = p * (dp - delta_b) * scale
        dk_cur = dk_prev + _mm_f32(ds.astype(q_b.dtype), q_b,
                                   transpose_a=True)
        return dk_cur, dv_cur

    if causal:
        # rows strictly above the diagonal contribute nothing to this
        # k block: start at the first q block that can see it
        first = (ki * block_k) // block_q
    else:
        first = 0
    dk, dv = jax.lax.fori_loop(first, num_qb, body, (dk, dv))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, scale, causal):
    """Single-cell backward: dq, dk, dv from ONE kernel invocation.

    When one (block_q, block_k) tile covers the whole [n, m] score
    matrix (the seq-512 training shape at the 512/512 defaults), the
    two-pass backward wastes work: the dq pass and the dk/dv pass each
    recompute s, p and dp (8 MXU contractions total). Computing them
    once and emitting all three grads needs 5. One pallas_call per
    (b, h) also halves the Mosaic dispatches."""
    q = q_ref[...]
    k_blk = k_ref[...]
    v_blk = v_ref[...]
    do = do_ref[...]
    lse = lse_ref[...]      # [n, 1]
    delta = delta_ref[...]  # [n, 1]
    s = _mm_f32(q, k_blk, transpose_b=True) * scale
    if causal:
        s = _causal_mask(s, 0, 0)
    p = jnp.exp(jnp.minimum(s - lse, 30.0))  # clamp: see _bwd_dq_kernel
    dp = _mm_f32(do, v_blk, transpose_b=True)
    ds = p * (dp - delta) * scale
    dq_ref[...] = _mm_f32(ds.astype(k_blk.dtype),
                          k_blk).astype(dq_ref.dtype)
    dk_ref[...] = _mm_f32(ds.astype(q.dtype), q,
                          transpose_a=True).astype(dk_ref.dtype)
    dv_ref[...] = _mm_f32(p.astype(do.dtype), do,
                          transpose_a=True).astype(dv_ref.dtype)


def _bwd_impl_fused(q, k, v, lse, do, delta, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, n, d = q.shape
    m = k.shape[2]
    kwargs = {}
    if interpret_mode():
        kwargs['interpret'] = True
    else:
        kwargs['compiler_params'] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"))
    full_q = pl.BlockSpec((None, None, n, d), lambda bi, hi: (bi, hi, 0, 0))
    full_k = pl.BlockSpec((None, None, m, d), lambda bi, hi: (bi, hi, 0, 0))
    full_rowq = pl.BlockSpec((None, None, n, 1),
                             lambda bi, hi: (bi, hi, 0, 0))
    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal),
        out_shape=[jax.ShapeDtypeStruct((b, h, n, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, m, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, m, d), v.dtype)],
        grid=(b, h),
        in_specs=[full_q, full_k, full_k, full_q, full_rowq, full_rowq],
        out_specs=[full_q, full_k, full_k],
        **kwargs,
    )(q, k, v, do, lse, delta)


def _fused_bwd_enabled():
    # re-read the env (not the import-latched copy): tests A/B this knob
    # in-process, and a kernel choice — unlike a block size — changes no
    # traced shapes, so late reads can't mix layouts. The default comes
    # from the ONE knob table (ops/flash_defaults.py).
    return _fd.resolve()['fused_bwd']


def _bwd_impl(q, k, v, o, lse, do, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, n, d = q.shape
    m = k.shape[2]
    block_q, block_k = _std_bwd_blocks(n, m)

    # delta = rowsum(do * o): one fused elementwise+reduce, tiny vs the
    # kernel FLOPs — leave it to XLA
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [b, h, n, 1]

    if block_q == n and block_k == m and _fused_bwd_enabled():
        # one tile covers the whole score matrix: single fused kernel
        return _bwd_impl_fused(q, k, v, lse, do, delta, causal, scale)

    kwargs = {}
    if interpret_mode():
        kwargs['interpret'] = True
    else:
        kwargs['compiler_params'] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    qspec = pl.BlockSpec((None, None, block_q, d),
                         lambda bi, hi, i: (bi, hi, i, 0))
    full_q = pl.BlockSpec((None, None, n, d), lambda bi, hi, i: (bi, hi, 0, 0))
    full_k = pl.BlockSpec((None, None, m, d), lambda bi, hi, i: (bi, hi, 0, 0))
    rowq = pl.BlockSpec((None, None, block_q, 1),
                        lambda bi, hi, i: (bi, hi, i, 0))
    full_rowq = pl.BlockSpec((None, None, n, 1),
                             lambda bi, hi, i: (bi, hi, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, seq_k=m),
        out_shape=jax.ShapeDtypeStruct((b, h, n, d), q.dtype),
        grid=(b, h, n // block_q),
        in_specs=[qspec, full_k, full_k, qspec, rowq, rowq],
        out_specs=qspec,
        **kwargs,
    )(q, k, v, do, lse, delta)

    kspec = pl.BlockSpec((None, None, block_k, d),
                         lambda bi, hi, i: (bi, hi, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, seq_q=n),
        out_shape=[jax.ShapeDtypeStruct((b, h, m, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, m, d), v.dtype)],
        grid=(b, h, m // block_k),
        in_specs=[full_q, kspec, kspec, full_q, full_rowq, full_rowq],
        out_specs=[kspec, kspec],
        **kwargs,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# -- custom-vjp wiring -------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_bhnd(q, k, v, causal, scale):
    o, _ = _dispatch_fwd(q, k, v, causal, scale)
    return o


def _dispatch_fwd(q, k, v, causal, scale):
    """Returns (o, lse_or_None); lse None means the jnp path ran."""
    if causal and q.shape[2] != k.shape[2]:
        # the Pallas kernels' causal block bounds assume self-attention
        # (q_pos = global q index); cross-length causal (KV-cache decode,
        # chunked prefill) takes the bottom-right-aligned blockwise path,
        # which keeps memory O(N*D) for a long cache. This is a semantics
        # contract, not a capability fallback — strict mode (a bench-
        # honesty guard for the n == m training shape) does not apply.
        from .blockwise_attention import blockwise_attention_bnhd
        return blockwise_attention_bnhd(q, k, v, causal=True,
                                        scale=scale), None
    reason = _supported(q, k, v)
    if reason is not None:
        if strict_mode():
            raise RuntimeError(
                'PADDLE_TPU_FLASH_STRICT=1 but the Pallas flash kernel '
                'cannot run: ' + reason)
        return _ref_bhnd(q, k, v, causal, scale), None
    impl = _fwd_impl_long if _use_long_path(q.shape[2], k.shape[2]) \
        else _fwd_impl
    if strict_mode():
        return impl(q, k, v, causal, scale)
    try:
        return impl(q, k, v, causal, scale)
    except Exception:
        return _ref_bhnd(q, k, v, causal, scale), None


def _fwd_rule(q, k, v, causal, scale):
    o, lse = _dispatch_fwd(q, k, v, causal, scale)
    return o, (q, k, v, o, lse)


def _bwd_rule(causal, scale, res, do):
    q, k, v, o, lse = res
    if causal and q.shape[2] != k.shape[2]:
        from .blockwise_attention import blockwise_attention_bnhd
        _, vjp = jax.vjp(lambda a, b, c: blockwise_attention_bnhd(
            a, b, c, causal=True, scale=scale), q, k, v)
        return vjp(do)
    if lse is not None:
        impl = _bwd_impl_long if _use_long_path(q.shape[2], k.shape[2]) \
            else _bwd_impl
        if strict_mode():
            return impl(q, k, v, o, lse, do, causal, scale)
        try:
            return impl(q, k, v, o, lse, do, causal, scale)
        except Exception:
            pass
    # jnp fallback: recomputed reference backward (numerically exact)
    _, vjp = jax.vjp(lambda a, b, c: _ref_bhnd(a, b, c, causal, scale),
                     q, k, v)
    return vjp(do)


_flash_bhnd.defvjp(_fwd_rule, _bwd_rule)


# -- public API --------------------------------------------------------------

def flash_attention_bnhd(q, k, v, causal=False, scale=None):
    """Paddle layout [B, N, H, D] in/out."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = _flash_bhnd(qt, kt, vt, causal, scale)
    return jnp.swapaxes(o, 1, 2)


def flash_attention_bhnd(q, k, v, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_bhnd(q, k, v, causal, scale)
