"""Blockwise (chunked) attention in pure XLA — flash's O(N) memory shape
without Mosaic.

Online-softmax over KV blocks (Dao et al. / Liu et al. "Blockwise Parallel
Transformer"), written as a `lax.scan` whose body is `jax.checkpoint`ed:
the scan's saved residuals are only the per-block running (m, l, acc)
carries, so neither forward nor backward ever materializes the [N, M]
score matrix. This is the fallback for hardware where the Pallas flash
kernels (ops/flash_attention.py) cannot compile — e.g. a relay whose
remote Mosaic service is unavailable — and the long-sequence path when
quadratic + jax.checkpoint would exceed HBM.

Reference counterpart: the fused attention family
/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu (spec
only — that is a cuBLAS/cuDNN kernel; this is an XLA-native algorithm).
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _pick_block(n, target):
    """Largest power-of-two-ish divisor of n that is <= target."""
    b = min(target, n)
    while b > 1 and n % b:
        b //= 2
    return max(b, 1)


def blockwise_attention_bnhd(q, k, v, causal=False, scale=None,
                             block_q=512, block_k=512):
    """Attention over [batch, heads, seq, head_dim] arrays.

    Numerically matches softmax(q k^T * scale) v with f32 accumulation;
    memory is O(seq * head_dim) instead of O(seq^2).

    Known cost: causal mode computes (then masks) the future KV blocks —
    the q-block loop is vmapped for MXU parallelism, so a lax.cond skip
    would lower to select and save nothing. The quadratic reference path
    pays the same 2x on masked flops; the Pallas flash kernels
    (flash_attention.py) are the zero-waste causal path when Mosaic is
    available. This op's win is the O(N) memory shape.
    """
    b, h, n, d = q.shape
    m = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bq = _pick_block(n, block_q)
    bk = _pick_block(m, block_k)
    tq, tk = n // bq, m // bk

    qb = q.reshape(b, h, tq, bq, d)
    kb = jnp.moveaxis(k.reshape(b, h, tk, bk, d), 2, 0)  # [tk, b, h, bk, d]
    vb = jnp.moveaxis(v.reshape(b, h, tk, bk, d), 2, 0)

    def one_qblock(qi, i):
        # qi: [b, h, bq, d]; i: scalar q-block index
        q32 = qi.astype(jnp.float32) * scale

        def body(carry, xs):
            m_prev, l_prev, acc = carry
            kj, vj, j = xs
            s = jnp.einsum('bhqd,bhkd->bhqk', q32, kj.astype(jnp.float32))
            if causal:
                qpos = i * bq + jnp.arange(bq)
                kpos = j * bk + jnp.arange(bk)
                keep = qpos[:, None] >= kpos[None, :]
                s = jnp.where(keep, s, _NEG_INF)
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_cur[..., None])
            if causal:
                # -1e30 sentinel rows: exp(-1e30 - -1e30) = 1 would leak
                # masked weight; zero them explicitly
                p = jnp.where(keep[None, None], p, 0.0)
            corr = jnp.exp(m_prev - m_cur)
            l_cur = l_prev * corr + jnp.sum(p, axis=-1)
            acc = acc * corr[..., None] + jnp.einsum(
                'bhqk,bhkd->bhqd', p, vj.astype(jnp.float32))
            return (m_cur, l_cur, acc), None

        init = (jnp.full((b, h, bq), _NEG_INF, jnp.float32),
                jnp.zeros((b, h, bq), jnp.float32),
                jnp.zeros((b, h, bq, d), jnp.float32))
        (m_f, l_f, acc), _ = lax.scan(jax.checkpoint(body), init,
                                      (kb, vb, jnp.arange(tk)))
        out = acc / jnp.maximum(l_f, 1e-30)[..., None]
        return out.astype(q.dtype)

    out = jax.vmap(one_qblock, in_axes=(2, 0), out_axes=2)(
        qb, jnp.arange(tq))
    return out.reshape(b, h, n, d)


def blockwise_attention(q, k, v, causal=False, scale=None,
                        block_q=512, block_k=512):
    """Paddle-layout entry: [batch, seq, heads, head_dim]."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = blockwise_attention_bnhd(qt, kt, vt, causal=causal, scale=scale,
                                 block_q=block_q, block_k=block_k)
    return jnp.swapaxes(o, 1, 2)
