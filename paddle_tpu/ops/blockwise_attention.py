"""Blockwise (chunked) attention in pure XLA — flash's O(N) memory shape
without Mosaic.

Online-softmax over KV blocks (Dao et al. / Liu et al. "Blockwise Parallel
Transformer"), written as a `lax.scan` whose body is `jax.checkpoint`ed:
the scan's saved residuals are only the per-block running (m, l, acc)
carries, so neither forward nor backward ever materializes the [N, M]
score matrix. This is the fallback for hardware where the Pallas flash
kernels (ops/flash_attention.py) cannot compile — e.g. a relay whose
remote Mosaic service is unavailable — and the long-sequence path when
quadratic + jax.checkpoint would exceed HBM.

Reference counterpart: the fused attention family
/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu (spec
only — that is a cuBLAS/cuDNN kernel; this is an XLA-native algorithm).
"""
import math
import os

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def env_block_size():
    """PADDLE_TPU_BLOCKWISE_BLOCK: the blockwise attention chunk size
    (default 512) - the one home for the default, shared by the SDPA
    routing and the Ulysses causal-skip route."""
    return int(os.environ.get('PADDLE_TPU_BLOCKWISE_BLOCK', 512))


def _pick_block(n, target):
    """Largest power-of-two-ish divisor of n that is <= target."""
    b = min(target, n)
    while b > 1 and n % b:
        b //= 2
    return max(b, 1)


def blockwise_attention_bnhd(q, k, v, causal=False, scale=None,
                             block_q=512, block_k=512):
    """Attention over [batch, heads, seq, head_dim] arrays.

    Numerically matches softmax(q k^T * scale) v with f32 accumulation;
    memory is O(seq * head_dim) instead of O(seq^2).

    Causal self-attention (n == m, equal blocks, modest block count) skips
    future KV blocks outright: the q-block count is static, so a Python
    unroll gives q-block i a STATIC kv slice [0..i] — only the lower
    triangle is ever computed (the diagonal block alone carries a mask),
    halving causal attention flops vs compute-then-mask. Cross-attention
    and very deep block counts (compile-size guard) fall back to the
    vmapped compute-then-mask path, which still has the O(N) memory win.
    """
    b, h, n, d = q.shape
    m = k.shape[2]
    if causal and n > m:
        raise ValueError(
            'causal attention with more queries (%d) than keys (%d)'
            % (n, m))
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bq = _pick_block(n, block_q)
    bk = _pick_block(m, block_k)
    tq, tk = n // bq, m // bk

    qb = q.reshape(b, h, tq, bq, d)
    kb = jnp.moveaxis(k.reshape(b, h, tk, bk, d), 2, 0)  # [tk, b, h, bk, d]
    vb = jnp.moveaxis(v.reshape(b, h, tk, bk, d), 2, 0)

    if causal and n == m and bq == bk and tq <= 64:
        return _causal_skip(qb, kb, vb, scale, q.dtype)

    def one_qblock(qi, i):
        # qi: [b, h, bq, d]; i: scalar q-block index

        def body(carry, xs):
            kj, vj, j = xs
            keep = None
            if causal:
                # bottom-right aligned: query row i*bq+row sits at
                # absolute key position (m - n) + i*bq + row, so causal
                # cross-attention (KV-cache decode, chunked prefill)
                # sees the full prefix
                qpos = (m - n) + i * bq + jnp.arange(bq)
                kpos = j * bk + jnp.arange(bk)
                keep = qpos[:, None] >= kpos[None, :]
            return _online_step(carry, qi, kj, vj, scale, keep), None

        init = _online_init(b, h, bq, d)
        (m_f, l_f, acc), _ = lax.scan(jax.checkpoint(body), init,
                                      (kb, vb, jnp.arange(tk)))
        out = acc / jnp.maximum(l_f, 1e-30)[..., None]
        return out.astype(q.dtype)

    out = jax.vmap(one_qblock, in_axes=(2, 0), out_axes=2)(
        qb, jnp.arange(tq))
    return out.reshape(b, h, n, d)


def _online_init(b, h, bq, d):
    return (jnp.full((b, h, bq), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, bq), jnp.float32),
            jnp.zeros((b, h, bq, d), jnp.float32))


def _online_step(carry, qn, kj, vj, scale, keep=None):
    """One online-softmax accumulation step over a single KV block.

    carry = (running max, running denom, running weighted-V accum), all
    f32. qn/kj/vj stay in their NATIVE dtype: the two einsums contract
    bf16 operands with f32 MXU accumulation (preferred_element_type) —
    upcasting first would run the MXU at its f32 rate, ~8x slower on
    v5e, for no accuracy gain (softmax math is f32 either way). `keep`
    is an optional [bq, bk] visibility mask. The single copy of this
    numerically delicate update serves the masked fallback, the
    causal-skip scan body, and the causal diagonal block.
    """
    m_prev, l_prev, acc = carry
    s = jnp.einsum('bhqd,bhkd->bhqk', qn, kj,
                   preferred_element_type=jnp.float32) * scale
    if keep is not None:
        s = jnp.where(keep, s, _NEG_INF)
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_cur[..., None])
    if keep is not None:
        # -1e30 sentinel rows: exp(-1e30 - -1e30) = 1 would leak masked
        # weight; zero them explicitly
        p = jnp.where(keep, p, 0.0)
    corr = jnp.exp(m_prev - m_cur)
    l_cur = l_prev * corr + jnp.sum(p, axis=-1)
    acc = acc * corr[..., None] + jnp.einsum(
        'bhqk,bhkd->bhqd', p.astype(vj.dtype), vj,
        preferred_element_type=jnp.float32)
    return m_cur, l_cur, acc


def _causal_skip(qb, kb, vb, scale, out_dtype):
    """Lower-triangle-only causal blockwise attention.

    qb: [b, h, tq, bq, d]; kb/vb: [tk, b, h, bk, d] with tq == tk,
    bq == bk. q-block i scans kv blocks 0..i-1 unmasked (all positions
    visible) via a static slice, then folds in the diagonal block with
    the in-block triangle mask — no future block is ever computed. Every
    step (diagonal included) sits under jax.checkpoint so backward only
    keeps the (m, l, acc) carries, preserving the O(seq*head_dim)
    residual contract.
    """
    b, h, tq, bq, d = qb.shape
    tri = jnp.arange(bq)[:, None] >= jnp.arange(bq)[None, :]

    def make_body(qn):
        def body(carry, xs):
            kj, vj = xs
            return _online_step(carry, qn, kj, vj, scale), None
        return body

    def diag_step(carry, qn, kj, vj):
        return _online_step(carry, qn, kj, vj, scale, tri)

    outs = []
    for i in range(tq):
        qn = qb[:, :, i]
        carry = _online_init(b, h, bq, d)
        if i > 0:
            carry, _ = lax.scan(jax.checkpoint(make_body(qn)), carry,
                                (kb[:i], vb[:i]))
        # diagonal block: the only one needing the triangle mask
        m_f, l_f, acc = jax.checkpoint(diag_step)(carry, qn, kb[i], vb[i])
        outs.append((acc / jnp.maximum(l_f, 1e-30)[..., None]
                     ).astype(out_dtype))
    return jnp.stack(outs, axis=2).reshape(b, h, tq * bq, d)


def blockwise_attention(q, k, v, causal=False, scale=None,
                        block_q=512, block_k=512):
    """Paddle-layout entry: [batch, seq, heads, head_dim]."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = blockwise_attention_bnhd(qt, kt, vt, causal=causal, scale=scale,
                                 block_q=block_q, block_k=block_k)
    return jnp.swapaxes(o, 1, 2)
