"""Ring attention: sequence-parallel exact attention over the 'sp' mesh axis.

Beyond-reference capability (SURVEY.md §5.7): the reference's long-sequence
levers are recompute+pipeline; TPU-native long context shards the sequence
over ICI and rotates K/V blocks with ppermute while accumulating streaming
softmax (Liu et al. ring attention; blockwise from Dao et al.).

Pure jax functions designed to run INSIDE shard_map (axis_name bound).
Complexity per rank: O((N/sp)^2 * sp) flops but N/sp memory — the point.
The per-block compute maps to the MXU via jnp.einsum; the ppermute rides
ICI concurrently with compute (XLA async collectives overlap the loop body).
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ['ring_attention', 'ulysses_attention', 'ring_attention_sharded',
           'ulysses_attention_sharded', 'ring_flash_attention',
           'ring_flash_attention_sharded']


def _block_attn(q, k, v, scale, mask, drop_p=0.0, drop_key=None):
    """One blockwise attention step in f32 accumulators.

    q: [B, Nq, H, D]; k/v: [B, Nk, H, D]; mask: [Nq, Nk] bool or None.
    Returns (scores_max [B,H,Nq], exp-sum [B,H,Nq], acc [B,Nq,H,D]).

    drop_p/drop_key: attention-prob dropout. The exp-sum `l` accumulates
    the UNdropped weights (dropout applies after softmax normalization:
    out_i = sum_j mask_ij p_ij v_j / (keep * sum_j p_ij)), so only the
    value accumulation sees the mask."""
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, -1e30)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    p_v = p
    if drop_p and drop_key is not None:
        keep = jax.random.bernoulli(drop_key, 1.0 - drop_p, p.shape)
        p_v = jnp.where(keep, p / (1.0 - drop_p), 0.0)
    acc = jnp.einsum('bhqk,bkhd->bqhd', p_v.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return m, l, acc


def ring_attention(q, k, v, axis_name='sp', causal=False, scale=None,
                   dropout_p=0.0, dropout_key=None):
    """Exact attention with K/V rotating around the ring.

    All inputs are the LOCAL sequence shard [B, N_local, H, D]; output is
    the local shard of the attention result. Call inside shard_map with
    `axis_name` bound to the sequence mesh axis.

    dropout_p/dropout_key: attention-prob dropout; the caller passes a
    key already folded per q-shard rank, and each ring step folds the kv
    source rank in, so every (q-block, kv-block) pair draws an
    independent mask.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n_dev = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, n_loc, h, d = q.shape

    q32 = q.astype(jnp.float32)

    # positions of the local q block (global)
    q_pos = my_idx * n_loc + jnp.arange(n_loc)

    def step(carry, r):
        m_prev, l_prev, acc_prev, k_cur, v_cur = carry
        # kv block currently held came from rank (my_idx - r) mod n_dev
        src = jnp.mod(my_idx - r, n_dev)
        if causal:
            k_pos = src * n_loc + jnp.arange(n_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        blk_key = (jax.random.fold_in(dropout_key, src)
                   if dropout_p and dropout_key is not None else None)
        m_blk, l_blk, acc_blk = _block_attn(q32, k_cur, v_cur, scale, mask,
                                            dropout_p, blk_key)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        beta = jnp.exp(m_blk - m_new)
        l_new = alpha * l_prev + beta * l_blk
        acc_new = acc_prev * jnp.moveaxis(alpha, 1, 2)[..., None] + \
            acc_blk * jnp.moveaxis(beta, 1, 2)[..., None]
        # rotate kv to the next rank (ring)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    m0 = jnp.full((b, h, n_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, n_loc), jnp.float32)
    acc0 = jnp.zeros((b, n_loc, h, d), jnp.float32)
    (m, l, acc, _, _), _ = lax.scan(step, (m0, l0, acc0, k, v),
                                    jnp.arange(n_dev))
    l = jnp.moveaxis(jnp.maximum(l, 1e-30), 1, 2)[..., None]
    return (acc / l).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name='sp', causal=False, scale=None,
                      attn_fn=None, dropout_p=0.0, dropout_key=None):
    """Ulysses (DeepSpeed) sequence parallelism: all_to_all swaps the
    sequence shard for a head shard, runs full-sequence attention on H/sp
    heads locally, and swaps back. Heads must divide the axis size."""
    n_dev = lax.axis_size(axis_name)
    b, n_loc, h, d = q.shape
    assert h % n_dev == 0, 'ulysses needs heads %% sp == 0'

    # tiled all_to_all: split one dim over the axis, concatenate shards
    # along another — dev-major ordering on both sides keeps head index
    # = dev*h_loc + local consistent between the two swaps. (The untiled
    # form mislowers inside shard_map when the mesh carries extra axes.)
    def seq2head(x):
        # [B, N/sp, H, D] -> [B, N, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(x):
        # [B, N, H/sp, D] -> [B, N/sp, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qf, kf, vf = seq2head(q), seq2head(k), seq2head(v)
    if attn_fn is None:
        if scale is None:
            scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jnp.einsum('bqhd,bkhd->bhqk', qf.astype(jnp.float32), kf,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            n = s.shape[-1]
            cm = jnp.tril(jnp.ones((n, n), bool))
            s = jnp.where(cm[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        if dropout_p and dropout_key is not None:
            # the caller folds the rank in; local heads draw iid masks
            keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                        p.shape)
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        of = jnp.einsum('bhqk,bkhd->bqhd', p.astype(vf.dtype), vf)
    else:
        of = attn_fn(qf, kf, vf)
    return head2seq(of.astype(q.dtype))


def _sharded(fn, mesh, axis_name, q, k, v, **kw):
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    spec = P(None, axis_name, None, None)
    wrapped = shard_map(
        functools.partial(fn, axis_name=axis_name, **kw), mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec, check_rep=False)
    return wrapped(q, k, v)


def ring_attention_sharded(q, k, v, mesh, axis_name='sp', causal=False):
    """Host-level entry: q/k/v are GLOBAL [B, N, H, D] arrays; shard_map
    splits the sequence over `axis_name` and runs the ring."""
    return _sharded(ring_attention, mesh, axis_name, q, k, v, causal=causal)


def ulysses_attention_sharded(q, k, v, mesh, axis_name='sp', causal=False):
    return _sharded(ulysses_attention, mesh, axis_name, q, k, v,
                    causal=causal)


# -- ring FLASH attention (SURVEY §5.7: 'ring attention as a Pallas kernel
# with ppermute over ICI') --------------------------------------------------
#
# Per ring step the LOCAL block runs the Pallas flash kernel
# (ops/flash_attention._fwd_impl) and the normalized partial outputs merge
# through their LSEs; the backward is a second ring that reuses the Pallas
# dq/dkv kernels with the GLOBAL lse/delta (blockwise-exact, Liu et al.),
# rotating the dk/dv accumulators alongside their k/v blocks so each
# block's grads arrive home after a full loop. Memory stays O(N_local);
# the quadratic [Nq, Nk] matrix never materializes.

def _lse_merge(o1, lse1, o2, lse2, w2):
    """Merge normalized flash outputs (o [B,H,N,D], lse [B,H,N,1]);
    w2 False masks block 2 out entirely."""
    neg = jnp.full_like(lse2, -jnp.inf)
    lse2w = jnp.where(w2, lse2, neg)
    m = jnp.maximum(lse1, lse2w)
    m_safe = jnp.where(jnp.isfinite(m), m, jnp.zeros_like(m))
    a1 = jnp.exp(lse1 - m_safe)
    a2 = jnp.exp(lse2w - m_safe)
    denom = jnp.maximum(a1 + a2, 1e-30)
    o = (o1 * a1 + o2 * a2) / denom
    return o, m_safe + jnp.log(denom)


def ring_flash_attention(q, k, v, axis_name='sp', causal=False, scale=None,
                         dropout_p=0.0, dropout_key=None):
    """Drop-in for ring_attention ([B, N_local, H, D] shards) running the
    Pallas flash kernels per block. Falls back to the jnp ring when the
    kernel cannot run (shape/backend), and routes attention-prob dropout
    to the jnp ring (the Pallas kernels are dropout-free)."""
    from . import flash_attention as fa
    if dropout_p and dropout_key is not None:
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              scale=scale, dropout_p=dropout_p,
                              dropout_key=dropout_key)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, N, D]
    reason = (None if fa.is_available() else 'flash unavailable on this '
              'backend') or fa._supported(qt, qt, qt)
    if reason is not None:
        if fa.strict_mode():
            raise RuntimeError(
                'PADDLE_TPU_FLASH_STRICT=1 but ring flash attention '
                'cannot run: %s' % reason)
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              scale=scale)

    n_dev = lax.axis_size(axis_name)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    @jax.custom_vjp
    def _ring(qb, kb, vb):
        o, lse, _, _ = _ring_fwd_impl(qb, kb, vb)
        return o

    def _ring_fwd_impl(qb, kb, vb):
        my = lax.axis_index(axis_name)
        # step 0: the diagonal block (causal inside the kernel)
        o, lse = fa._fwd_impl(qb, kb, vb, causal, scale)
        o = o.astype(jnp.float32)

        def step(carry, r):
            o_c, lse_c, k_c, v_c = carry
            k_n = lax.ppermute(k_c, axis_name, perm)
            v_n = lax.ppermute(v_c, axis_name, perm)
            o_b, lse_b = fa._fwd_impl(qb, k_n, v_n, False, scale)
            src = jnp.mod(my - r, n_dev)
            w = jnp.logical_or(jnp.asarray(not causal), src < my)
            o_c, lse_c = _lse_merge(o_c, lse_c,
                                    o_b.astype(jnp.float32), lse_b, w)
            return (o_c, lse_c, k_n, v_n), None

        (o, lse, k_last, v_last), _ = lax.scan(
            step, (o, lse, kb, vb), jnp.arange(1, n_dev))
        return o.astype(qb.dtype), lse, k_last, v_last

    def _ring_vjp_fwd(qb, kb, vb):
        o, lse, _, _ = _ring_fwd_impl(qb, kb, vb)
        return o, (qb, kb, vb, o, lse)

    def _ring_vjp_bwd(res, do):
        qb, kb, vb, o, lse = res
        my = lax.axis_index(axis_name)
        do = do.astype(qb.dtype)

        # step 0: diagonal block grads
        dq, dk0, dv0 = fa._bwd_impl(qb, kb, vb, o, lse, do, causal, scale)
        dq = dq.astype(jnp.float32)

        def step(carry, r):
            dq_c, k_c, v_c, dk_c, dv_c = carry
            # rotate the kv block AND its grad accumulators together
            k_n = lax.ppermute(k_c, axis_name, perm)
            v_n = lax.ppermute(v_c, axis_name, perm)
            dk_n = lax.ppermute(dk_c, axis_name, perm)
            dv_n = lax.ppermute(dv_c, axis_name, perm)
            dq_b, dk_b, dv_b = fa._bwd_impl(qb, k_n, v_n, o, lse, do,
                                            False, scale)
            src = jnp.mod(my - r, n_dev)
            w = jnp.logical_or(jnp.asarray(not causal),
                               src < my).astype(jnp.float32)
            dq_c = dq_c + dq_b.astype(jnp.float32) * w
            dk_n = dk_n + dk_b.astype(jnp.float32) * w
            dv_n = dv_n + dv_b.astype(jnp.float32) * w
            return (dq_c, k_n, v_n, dk_n, dv_n), None

        (dq, _, _, dk_acc, dv_acc), _ = lax.scan(
            step, (dq, kb, vb, dk0.astype(jnp.float32),
                   dv0.astype(jnp.float32)), jnp.arange(1, n_dev))
        # one final rotation brings each block's accumulators home
        dk_home = lax.ppermute(dk_acc, axis_name, perm)
        dv_home = lax.ppermute(dv_acc, axis_name, perm)
        return (dq.astype(qb.dtype), dk_home.astype(kb.dtype),
                dv_home.astype(vb.dtype))

    _ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)

    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    return jnp.swapaxes(_ring(qt, kt, vt), 1, 2)


def ring_flash_attention_sharded(q, k, v, mesh, axis_name='sp',
                                 causal=False):
    return _sharded(ring_flash_attention, mesh, axis_name, q, k, v,
                    causal=causal)
