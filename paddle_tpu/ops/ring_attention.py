"""Ring attention: sequence-parallel exact attention over the 'sp' mesh axis.

Beyond-reference capability (SURVEY.md §5.7): the reference's long-sequence
levers are recompute+pipeline; TPU-native long context shards the sequence
over ICI and rotates K/V blocks with ppermute while accumulating streaming
softmax (Liu et al. ring attention; blockwise from Dao et al.).

Pure jax functions designed to run INSIDE shard_map (axis_name bound).
Complexity per rank: O((N/sp)^2 * sp) flops but N/sp memory — the point.
The per-block compute maps to the MXU via jnp.einsum; the ppermute rides
ICI concurrently with compute (XLA async collectives overlap the loop body).
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax


def _axis_size(axis_name):
    """lax.axis_size where available; psum-of-1 (constant-folded to the
    static axis extent) on jax lines that predate it."""
    fn = getattr(lax, 'axis_size', None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)

__all__ = ['ring_attention', 'ulysses_attention', 'ring_attention_sharded',
           'ulysses_attention_sharded', 'ring_flash_attention',
           'ring_flash_attention_sharded', 'zigzag_ring_attention',
           'zigzag_layout_indices']


def _block_attn(q, k, v, scale, mask, drop_p=0.0, drop_key=None):
    """One blockwise attention step in f32 accumulators.

    q: [B, Nq, H, D]; k/v: [B, Nk, H, D]; mask: [Nq, Nk] bool or None.
    Returns (scores_max [B,H,Nq], exp-sum [B,H,Nq], acc [B,Nq,H,D]).

    drop_p/drop_key: attention-prob dropout. The exp-sum `l` accumulates
    the UNdropped weights (dropout applies after softmax normalization:
    out_i = sum_j mask_ij p_ij v_j / (keep * sum_j p_ij)), so only the
    value accumulation sees the mask."""
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, -1e30)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    p_v = p
    if drop_p and drop_key is not None:
        keep = jax.random.bernoulli(drop_key, 1.0 - drop_p, p.shape)
        p_v = jnp.where(keep, p / (1.0 - drop_p), 0.0)
    acc = jnp.einsum('bhqk,bkhd->bqhd', p_v.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return m, l, acc


def _merge_blocks(carry, blk):
    """Online-softmax merge of two (m, l, acc) streaming-attention states.
    Safe against an empty carry (m = -inf, l = 0, acc = 0) as long as the
    incoming block's m is finite."""
    m_prev, l_prev, acc_prev = carry
    m_blk, l_blk, acc_blk = blk
    m_new = jnp.maximum(m_prev, m_blk)
    alpha = jnp.exp(m_prev - m_new)
    beta = jnp.exp(m_blk - m_new)
    l_new = alpha * l_prev + beta * l_blk
    acc_new = acc_prev * jnp.moveaxis(alpha, 1, 2)[..., None] + \
        acc_blk * jnp.moveaxis(beta, 1, 2)[..., None]
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name='sp', causal=False, scale=None,
                   dropout_p=0.0, dropout_key=None):
    """Exact attention with K/V rotating around the ring.

    All inputs are the LOCAL sequence shard [B, N_local, H, D]; output is
    the local shard of the attention result. Call inside shard_map with
    `axis_name` bound to the sequence mesh axis.

    dropout_p/dropout_key: attention-prob dropout; the caller passes a
    key already folded per q-shard rank, and each ring step folds the kv
    source rank in, so every (q-block, kv-block) pair draws an
    independent mask.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n_dev = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, n_loc, h, d = q.shape

    # positions of the local q block (global)
    q_pos = my_idx * n_loc + jnp.arange(n_loc)

    def step(carry, r):
        m_prev, l_prev, acc_prev, k_cur, v_cur = carry
        # kv block currently held came from rank (my_idx - r) mod n_dev
        src = jnp.mod(my_idx - r, n_dev)
        if causal:
            k_pos = src * n_loc + jnp.arange(n_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        blk_key = (jax.random.fold_in(dropout_key, src)
                   if dropout_p and dropout_key is not None else None)
        # q in its native dtype: _block_attn contracts with f32 MXU
        # accumulation; a pre-upcast would force an f32-rate matmul
        blk = _block_attn(q, k_cur, v_cur, scale, mask,
                          dropout_p, blk_key)
        m_new, l_new, acc_new = _merge_blocks((m_prev, l_prev, acc_prev),
                                              blk)
        # rotate kv to the next rank (ring)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    m0 = jnp.full((b, h, n_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, n_loc), jnp.float32)
    acc0 = jnp.zeros((b, n_loc, h, d), jnp.float32)
    (m, l, acc, _, _), _ = lax.scan(step, (m0, l0, acc0, k, v),
                                    jnp.arange(n_dev))
    l = jnp.moveaxis(jnp.maximum(l, 1e-30), 1, 2)[..., None]
    return (acc / l).astype(q.dtype)


def zigzag_ring_attention(q, k, v, axis_name='sp', scale=None,
                          dropout_p=0.0, dropout_key=None, causal=True):
    """Load-balanced CAUSAL ring attention (zigzag layout).

    The plain causal ring computes every (q-shard, kv-shard) pair and
    masks the future ones — and since SPMD wall-clock is gated by the
    last rank (which masks nothing), the masked flops are pure waste.
    Zigzag rebalances by layout: with P ranks the sequence is cut into
    2P chunks of size c and rank r holds rows [chunk r ; chunk 2P-1-r]
    (the caller permutes — sp.sp_attention does this outside shard_map).
    Visibility then collapses to a uniform schedule:

      - local step: lo-lo (tri), hi-lo (full), hi-hi (tri)
      - every other ring step exactly TWO full c x c quadrants:
        hi-q vs src-lo-kv always, plus lo-q vs src-lo-kv when r > src
        else hi-q vs src-hi-kv — chosen by jnp.where on the operands,
        so every rank does identical work and no masked block is ever
        computed: ~2x the causal throughput of the plain ring.

    (Brandon et al. striped attention / zigzag ring — public technique.)
    Requires causal=True (the balance argument IS causality) and an even
    local row count.
    """
    assert causal, 'zigzag_ring_attention is causal-only; use ring_attention'
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n_dev = _axis_size(axis_name)
    r = lax.axis_index(axis_name)
    b, n_loc, h, d = q.shape
    assert n_loc % 2 == 0, 'zigzag needs an even local row count'
    c = n_loc // 2
    two_p = 2 * n_dev

    # native dtype: see ring_attention (f32 accumulation lives in
    # _block_attn's preferred_element_type)
    q_lo, q_hi = q[:, :c], q[:, c:]
    lo_chunk, hi_chunk = r, two_p - 1 - r
    tri = jnp.tril(jnp.ones((c, c), bool))

    def blk_key(q_chunk, kv_chunk):
        if not (dropout_p and dropout_key is not None):
            return None
        return jax.random.fold_in(
            jax.random.fold_in(dropout_key, q_chunk), kv_chunk)

    # local step (src == r): the only masked quadrants in the schedule
    k_lo, k_hi = k[:, :c], k[:, c:]
    v_lo, v_hi = v[:, :c], v[:, c:]
    lo_c = _block_attn(q_lo, k_lo, v_lo, scale, tri, dropout_p,
                       blk_key(lo_chunk, lo_chunk))
    hi_c = _block_attn(q_hi, k_lo, v_lo, scale, None, dropout_p,
                       blk_key(hi_chunk, lo_chunk))
    hi_c = _merge_blocks(hi_c, _block_attn(q_hi, k_hi, v_hi, scale, tri,
                                           dropout_p,
                                           blk_key(hi_chunk, hi_chunk)))

    def step(carry, t):
        lo_c, hi_c, k_cur, v_cur = carry
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        src = jnp.mod(r - t, n_dev)
        src_hi = two_p - 1 - src
        kl, kh = k_cur[:, :c], k_cur[:, c:]
        vl, vh = v_cur[:, :c], v_cur[:, c:]
        # quadrant A: hi-q sees every lo chunk — always full
        hi_c = _merge_blocks(hi_c, _block_attn(
            q_hi, kl, vl, scale, None, dropout_p, blk_key(hi_chunk, src)))
        # quadrant B: r > src -> lo-q vs src-lo; else hi-q vs src-hi.
        # Operand selects keep the program uniform across ranks — the
        # load-balance property — while only visible work is computed.
        pred = r > src
        qB = jnp.where(pred, q_lo, q_hi)
        kB = jnp.where(pred, kl, kh)
        vB = jnp.where(pred, vl, vh)
        keyB = blk_key(jnp.where(pred, lo_chunk, hi_chunk),
                       jnp.where(pred, src, src_hi))
        blkB = _block_attn(qB, kB, vB, scale, None, dropout_p, keyB)
        lo_new = _merge_blocks(lo_c, blkB)
        hi_new = _merge_blocks(hi_c, blkB)
        sel = lambda a, b_: jnp.where(pred, a, b_)
        lo_c = jax.tree_util.tree_map(sel, lo_new, lo_c)
        hi_c = jax.tree_util.tree_map(sel, hi_c, hi_new)
        return (lo_c, hi_c, k_cur, v_cur), None

    if n_dev > 1:
        (lo_c, hi_c, _, _), _ = lax.scan(
            step, (lo_c, hi_c, k, v), jnp.arange(1, n_dev))

    def finish(cr):
        m, l, acc = cr
        l = jnp.moveaxis(jnp.maximum(l, 1e-30), 1, 2)[..., None]
        return acc / l
    out = jnp.concatenate([finish(lo_c), finish(hi_c)], axis=1)
    return out.astype(q.dtype)


def zigzag_layout_indices(n, n_dev):
    """Global gather indices taking a contiguous sequence to the zigzag
    layout (rank r <- chunks r and 2P-1-r), and the inverse."""
    import numpy as np
    c = n // (2 * n_dev)
    idx = np.concatenate([
        np.concatenate([np.arange(r * c, (r + 1) * c),
                        np.arange((2 * n_dev - 1 - r) * c,
                                  (2 * n_dev - r) * c)])
        for r in range(n_dev)])
    inv = np.argsort(idx)
    return idx, inv


def ulysses_attention(q, k, v, axis_name='sp', causal=False, scale=None,
                      attn_fn=None, dropout_p=0.0, dropout_key=None):
    """Ulysses (DeepSpeed) sequence parallelism: all_to_all swaps the
    sequence shard for a head shard, runs full-sequence attention on H/sp
    heads locally, and swaps back. Heads must divide the axis size."""
    n_dev = _axis_size(axis_name)
    b, n_loc, h, d = q.shape
    assert h % n_dev == 0, 'ulysses needs heads %% sp == 0'

    # tiled all_to_all: split one dim over the axis, concatenate shards
    # along another — dev-major ordering on both sides keeps head index
    # = dev*h_loc + local consistent between the two swaps. (The untiled
    # form mislowers inside shard_map when the mesh carries extra axes.)
    def seq2head(x):
        # [B, N/sp, H, D] -> [B, N, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(x):
        # [B, N, H/sp, D] -> [B, N/sp, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qf, kf, vf = seq2head(q), seq2head(k), seq2head(v)
    if attn_fn is None:
        if scale is None:
            scale = 1.0 / (q.shape[-1] ** 0.5)
        n_full = qf.shape[1]
        from .blockwise_attention import env_block_size
        blk = env_block_size()
        if causal and not (dropout_p and dropout_key is not None) \
                and n_full >= 1024 and blk > 0 and n_full % blk == 0 \
                and n_full // blk <= 64:
            # (the divisibility/block-count guard mirrors blockwise's own
            # causal-skip precondition — without it, odd lengths would
            # degenerate to tiny-block fallbacks slower than quadratic)
            # long causal sequences: the local full-sequence attention is
            # where Ulysses burns its flops — route through the blockwise
            # causal-skip path (ops/blockwise_attention.py) so future KV
            # blocks are never computed (and memory stays O(N))
            from .blockwise_attention import blockwise_attention
            of = blockwise_attention(qf, kf, vf, causal=True, scale=scale,
                                     block_q=blk, block_k=blk)
        else:
            s = jnp.einsum('bqhd,bkhd->bhqk', qf, kf,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                cm = jnp.tril(jnp.ones((n_full, n_full), bool))
                s = jnp.where(cm[None, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            if dropout_p and dropout_key is not None:
                # the caller folds the rank in; local heads draw iid masks
                keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                            p.shape)
                p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
            of = jnp.einsum('bhqk,bkhd->bqhd', p.astype(vf.dtype), vf)
    else:
        of = attn_fn(qf, kf, vf)
    return head2seq(of.astype(q.dtype))


def _sharded(fn, mesh, axis_name, q, k, v, **kw):
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    spec = P(None, axis_name, None, None)
    wrapped = shard_map(
        functools.partial(fn, axis_name=axis_name, **kw), mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec, check_rep=False)
    return wrapped(q, k, v)


def ring_attention_sharded(q, k, v, mesh, axis_name='sp', causal=False):
    """Host-level entry: q/k/v are GLOBAL [B, N, H, D] arrays; shard_map
    splits the sequence over `axis_name` and runs the ring."""
    return _sharded(ring_attention, mesh, axis_name, q, k, v, causal=causal)


def ulysses_attention_sharded(q, k, v, mesh, axis_name='sp', causal=False):
    return _sharded(ulysses_attention, mesh, axis_name, q, k, v,
                    causal=causal)


# -- ring FLASH attention (SURVEY §5.7: 'ring attention as a Pallas kernel
# with ppermute over ICI') --------------------------------------------------
#
# Per ring step the LOCAL block runs the Pallas flash kernel
# (ops/flash_attention._fwd_impl) and the normalized partial outputs merge
# through their LSEs; the backward is a second ring that reuses the Pallas
# dq/dkv kernels with the GLOBAL lse/delta (blockwise-exact, Liu et al.),
# rotating the dk/dv accumulators alongside their k/v blocks so each
# block's grads arrive home after a full loop. Memory stays O(N_local);
# the quadratic [Nq, Nk] matrix never materializes.

def _lse_merge(o1, lse1, o2, lse2, w2):
    """Merge normalized flash outputs (o [B,H,N,D], lse [B,H,N,1]);
    w2 False masks block 2 out entirely."""
    neg = jnp.full_like(lse2, -jnp.inf)
    lse2w = jnp.where(w2, lse2, neg)
    m = jnp.maximum(lse1, lse2w)
    m_safe = jnp.where(jnp.isfinite(m), m, jnp.zeros_like(m))
    a1 = jnp.exp(lse1 - m_safe)
    a2 = jnp.exp(lse2w - m_safe)
    denom = jnp.maximum(a1 + a2, 1e-30)
    o = (o1 * a1 + o2 * a2) / denom
    return o, m_safe + jnp.log(denom)


def ring_flash_attention(q, k, v, axis_name='sp', causal=False, scale=None,
                         dropout_p=0.0, dropout_key=None):
    """Drop-in for ring_attention ([B, N_local, H, D] shards) running the
    Pallas flash kernels per block. Falls back to the jnp ring when the
    kernel cannot run (shape/backend), and routes attention-prob dropout
    to the jnp ring (the Pallas kernels are dropout-free)."""
    from . import flash_attention as fa
    if dropout_p and dropout_key is not None:
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              scale=scale, dropout_p=dropout_p,
                              dropout_key=dropout_key)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, N, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    reason = (None if fa.is_available() else 'flash unavailable on this '
              'backend') or fa._supported(qt, kt, vt)
    if reason is not None:
        if fa.strict_mode():
            raise RuntimeError(
                'PADDLE_TPU_FLASH_STRICT=1 but ring flash attention '
                'cannot run: %s' % reason)
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              scale=scale)

    n_dev = _axis_size(axis_name)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    @jax.custom_vjp
    def _ring(qb, kb, vb):
        o, lse, _, _ = _ring_fwd_impl(qb, kb, vb)
        return o

    def _ring_fwd_impl(qb, kb, vb):
        my = lax.axis_index(axis_name)
        # step 0: the diagonal block (causal inside the kernel)
        o, lse = fa._fwd_impl(qb, kb, vb, causal, scale)
        o = o.astype(jnp.float32)

        def step(carry, r):
            o_c, lse_c, k_c, v_c = carry
            k_n = lax.ppermute(k_c, axis_name, perm)
            v_n = lax.ppermute(v_c, axis_name, perm)
            o_b, lse_b = fa._fwd_impl(qb, k_n, v_n, False, scale)
            src = jnp.mod(my - r, n_dev)
            w = jnp.logical_or(jnp.asarray(not causal), src < my)
            o_c, lse_c = _lse_merge(o_c, lse_c,
                                    o_b.astype(jnp.float32), lse_b, w)
            return (o_c, lse_c, k_n, v_n), None

        (o, lse, k_last, v_last), _ = lax.scan(
            step, (o, lse, kb, vb), jnp.arange(1, n_dev))
        return o.astype(qb.dtype), lse, k_last, v_last

    def _ring_vjp_fwd(qb, kb, vb):
        o, lse, _, _ = _ring_fwd_impl(qb, kb, vb)
        return o, (qb, kb, vb, o, lse)

    def _ring_vjp_bwd(res, do):
        qb, kb, vb, o, lse = res
        my = lax.axis_index(axis_name)
        do = do.astype(qb.dtype)

        # step 0: diagonal block grads
        dq, dk0, dv0 = fa._bwd_impl(qb, kb, vb, o, lse, do, causal, scale)
        dq = dq.astype(jnp.float32)

        def step(carry, r):
            dq_c, k_c, v_c, dk_c, dv_c = carry
            # rotate the kv block AND its grad accumulators together
            k_n = lax.ppermute(k_c, axis_name, perm)
            v_n = lax.ppermute(v_c, axis_name, perm)
            dk_n = lax.ppermute(dk_c, axis_name, perm)
            dv_n = lax.ppermute(dv_c, axis_name, perm)
            dq_b, dk_b, dv_b = fa._bwd_impl(qb, k_n, v_n, o, lse, do,
                                            False, scale)
            src = jnp.mod(my - r, n_dev)
            w = jnp.logical_or(jnp.asarray(not causal),
                               src < my).astype(jnp.float32)
            dq_c = dq_c + dq_b.astype(jnp.float32) * w
            dk_n = dk_n + dk_b.astype(jnp.float32) * w
            dv_n = dv_n + dv_b.astype(jnp.float32) * w
            return (dq_c, k_n, v_n, dk_n, dv_n), None

        (dq, _, _, dk_acc, dv_acc), _ = lax.scan(
            step, (dq, kb, vb, dk0.astype(jnp.float32),
                   dv0.astype(jnp.float32)), jnp.arange(1, n_dev))
        # one final rotation brings each block's accumulators home
        dk_home = lax.ppermute(dk_acc, axis_name, perm)
        dv_home = lax.ppermute(dv_acc, axis_name, perm)
        return (dq.astype(qb.dtype), dk_home.astype(kb.dtype),
                dv_home.astype(vb.dtype))

    _ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)

    return jnp.swapaxes(_ring(qt, kt, vt), 1, 2)


def ring_flash_attention_sharded(q, k, v, mesh, axis_name='sp',
                                 causal=False):
    return _sharded(ring_flash_attention, mesh, axis_name, q, k, v,
                    causal=causal)
