"""Pallas TPU kernels for hot ops (flash attention, ring attention, fused ops)."""
