"""Fused LM-head + softmax cross-entropy, chunked over rows.

The dominant non-matmul cost of LM training at realistic vocab sizes is
the logits tensor: a [batch*seq, vocab] bf16 matmul output that the
straight path (head matmul -> cross_entropy) materializes in HBM, copies
to f32 for the stable logsumexp, and materializes AGAIN as softmax probs
in the backward. On the BERT-base bench config that is ~2 GB of f32
logits + ~1 GB of probs per step — measured at ~13 ms/step of pure HBM
traffic on v5e (docs/PERF_NOTES_r4.md, profile analysis).

This op computes mean softmax-CE of `x @ w (+bias)` against integer
labels WITHOUT ever materializing the full [rows, vocab] logits:

- forward: python-unrolled loop over row chunks; each chunk computes its
  logits tile, reduces it to (logsumexp, picked-label logit) in f32, and
  discards it. Residuals are O(rows), not O(rows*vocab).
- backward (custom_vjp): re-computes each chunk's logits tile, forms
  softmax(logits) - onehot(label) on the fly (an elementwise epilogue
  XLA fuses into the consuming matmuls), and emits dx per chunk and a
  f32-accumulated dw. MXU matmuls use f32 accumulation
  (preferred_element_type) so the chunked dw matches the one-shot matmul.

Cost: one extra logits-tile matmul (the backward recompute) — ~25% more
head flops — traded for removing every [rows, vocab] HBM round-trip.

Reference counterpart: the reference reaches the same end by op fusion
on GPU (paddle/fluid/operators/fused/ family; c_softmax_with_cross_entropy
fuses the vocab-PARALLEL variant, operators/collective/
c_softmax_with_cross_entropy_op.cu) — this is the XLA/TPU-native design:
chunk at the algorithm level, let the compiler fuse the epilogues.
"""
import functools
import os

import jax
import jax.numpy as jnp

__all__ = ['linear_cross_entropy_arrays', 'env_chunk_rows',
           'logits_sharding']

_MAX_CHUNKS = 64

# Vocab-parallel hint (reference: the c_softmax_with_cross_entropy
# vocab-PARALLEL collective op). Under tensor parallelism GSPMD's cost
# model prefers gathering the vocab axis for the CE region over
# vocab-parallel local reductions + a small all-reduce
# (test_hlo_collectives documents the r4 behavior). When a strategy
# enters `logits_sharding(s)` around the step trace, every transient
# logits tile is constrained to `s` ([rows-axes, 'mp']), which forces
# the partitioner onto the vocab-parallel plan. A ContextVar, not a
# module global: concurrent traces (a hinted train step and an
# unhinted eval step on another thread) must not see each other's
# sharding — a wrong-mesh constraint is a trace error at best.
import contextvars

_LOGITS_SHARDING = contextvars.ContextVar('fused_ce_logits_sharding',
                                          default=None)


class logits_sharding:
    """Context manager: constrain fused-CE logits tiles to `sharding`."""

    def __init__(self, sharding):
        self.sharding = sharding

    def __enter__(self):
        self._token = _LOGITS_SHARDING.set(self.sharding)
        return self

    def __exit__(self, *exc):
        _LOGITS_SHARDING.reset(self._token)
        return False


def _maybe_constrain(af):
    s = _LOGITS_SHARDING.get()
    if s is None:
        return af
    return jax.lax.with_sharding_constraint(af, s)


def env_chunk_rows():
    """PADDLE_TPU_FUSED_CE_CHUNK: rows per logits tile (default 4096).

    Bigger tiles = fewer dw accumulation passes (each one is a
    read-modify-write of the full f32 [d, vocab] accumulator) but a
    larger transient logits tile. 4096 rows x 30k vocab bf16 = 250 MB —
    comfortably HBM-resident on any TPU generation.
    """
    raw = os.environ.get('PADDLE_TPU_FUSED_CE_CHUNK')
    if raw is None:
        return 4096
    try:
        val = int(raw)
    except ValueError:
        import warnings
        warnings.warn('PADDLE_TPU_FUSED_CE_CHUNK=%r is not an integer; '
                      'using the default 4096' % (raw,))
        return 4096
    if val < 1:
        raise ValueError(
            'PADDLE_TPU_FUSED_CE_CHUNK must be >= 1, got %d' % val)
    return val


def _chunk_plan(rows, chunk):
    """(chunk, n_chunks, padded_rows) with the unroll bounded."""
    # never a chunk larger than the input: padding rounds rows up to a
    # chunk multiple, and padded rows cost real (masked) matmul flops
    chunk = max(1, min(int(chunk), rows))
    n = -(-rows // chunk)
    if n > _MAX_CHUNKS:  # keep the unrolled program a sane size
        chunk = -(-rows // _MAX_CHUNKS)
        n = -(-rows // chunk)
    return chunk, n, n * chunk


def _pad_rows(x, labels, rows_p, ignore_index):
    rows = x.shape[0]
    if rows_p == rows:
        return x, labels
    pad = rows_p - rows
    x = jnp.pad(x, ((0, pad), (0, 0)))
    labels = jnp.pad(labels, (0, pad), constant_values=ignore_index)
    return x, labels


def _tile_logits(xc, w, bias):
    logits = jnp.matmul(xc, w)
    if bias is not None:
        logits = logits + bias
    return _maybe_constrain(logits.astype(jnp.float32))


def _label_onehot(safe, shape):
    """[rows, vocab] bool mask selecting each row's label column, built
    by iota-compare rather than gather/one_hot: elementwise over the
    vocab axis, so GSPMD keeps it sharded with the logits tile (a
    vocab-axis gather would make the partitioner all-gather the tile).
    Shared by fwd (label-logit pick) and bwd (softmax - onehot)."""
    return jax.lax.broadcasted_iota(jnp.int32, shape, 1) == safe[:, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def linear_cross_entropy_arrays(x, w, labels, bias, ignore_index, chunk):
    """Mean softmax-CE of (x @ w + bias) vs labels over valid rows.

    x: [rows, d] float; w: [d, vocab]; labels: [rows] int;
    bias: [vocab] or None. Rows whose label == ignore_index contribute
    nothing; the mean divides by the valid count (matching
    F.cross_entropy(reduction='mean', ignore_index=...)).
    Returns a scalar in x.dtype.
    """
    loss, _ = _lce_fwd(x, w, labels, bias, ignore_index, chunk)
    return loss


def _lce_fwd(x, w, labels, bias, ignore_index, chunk):
    rows = x.shape[0]
    v = w.shape[1]
    chunk, n, rows_p = _chunk_plan(rows, chunk)
    xp, lp = _pad_rows(x, labels, rows_p, ignore_index)
    # STRIDED chunking (chunk i = rows i, i+n, i+2n, ...): under data
    # parallelism the flattened row axis is dp-sharded contiguously, so
    # contiguous chunks would each live on ONE dp group — every chunk
    # would either run on a fraction of the devices or force a per-chunk
    # redistribution. Strided chunks hit every dp shard evenly. Rows are
    # independent in CE, so order only matters for the final stitch
    # (the [chunk, n] stack below mirrors the reshape here).
    x3 = xp.reshape(chunk, n, -1)
    l2 = lp.reshape(chunk, n)
    lse_parts, picked_parts = [], []
    for i in range(n):
        xc = x3[:, i, :]
        lc = l2[:, i]
        af = _tile_logits(xc, w, bias)
        m = af.max(axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(af - m[:, None]), axis=-1))
        safe = jnp.clip(lc, 0, v - 1).astype(jnp.int32)
        # pick the label logit as a masked SUM, not take_along_axis: a
        # gather along the vocab axis defeats GSPMD when the head weight
        # is mp-sharded, while iota-compare + sum partitions into a
        # local reduce + a tiny all-reduce — the vocab-parallel CE
        # pattern (reference: c_softmax_with_cross_entropy). The
        # elementwise cost fuses into the pass that reads af anyway.
        picked = jnp.sum(jnp.where(_label_onehot(safe, af.shape),
                                   af, 0.0), axis=-1)
        lse_parts.append(lse)
        picked_parts.append(picked)
    lse = jnp.stack(lse_parts, axis=1).reshape(rows_p)
    picked = jnp.stack(picked_parts, axis=1).reshape(rows_p)
    valid = lp != ignore_index
    per_row = jnp.where(valid, lse - picked, 0.0)
    denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    loss = (jnp.sum(per_row) / denom).astype(x.dtype)
    # residuals are O(rows): the logits tiles are recomputed in _lce_bwd
    return loss, (x, w, labels, bias, lse, denom)


def _lce_bwd(ignore_index, chunk, res, g):
    x, w, labels, bias, lse, denom = res
    rows, d = x.shape
    v = w.shape[1]
    chunk, n, rows_p = _chunk_plan(rows, chunk)
    xp, lp = _pad_rows(x, labels, rows_p, ignore_index)
    gg = g.astype(jnp.float32) / denom
    dx_parts = []
    dw = jnp.zeros((d, v), jnp.float32)
    db = jnp.zeros((v,), jnp.float32) if bias is not None else None
    # same strided chunk layout as the forward (see _lce_fwd)
    x3 = xp.reshape(chunk, n, d)
    l2 = lp.reshape(chunk, n)
    lse2 = lse.reshape(chunk, n)
    for i in range(n):
        xc = x3[:, i, :]
        lc = l2[:, i]
        lse_c = lse2[:, i]
        af = _tile_logits(xc, w, bias)
        p = jnp.exp(af - lse_c[:, None])
        valid = lc != ignore_index
        safe = jnp.clip(lc, 0, v - 1).astype(jnp.int32)
        onehot = _label_onehot(safe, p.shape)
        # d(CE)/d(logits) = softmax - onehot, zeroed on ignored rows; the
        # whole epilogue is elementwise so XLA fuses it into both
        # consuming matmuls — p never round-trips HBM at full precision
        p = (p - onehot) * (gg * valid.astype(jnp.float32))[:, None]
        pc = p.astype(w.dtype)
        dx_parts.append(
            jnp.matmul(pc, w.T,
                       preferred_element_type=jnp.float32).astype(x.dtype))
        dw = dw + jnp.matmul(xc.T, pc, preferred_element_type=jnp.float32)
        if db is not None:
            db = db + p.sum(axis=0)
    dx = jnp.stack(dx_parts, axis=1).reshape(rows_p, d)[:rows]
    dlabels = jnp.zeros(labels.shape, jax.dtypes.float0)
    return (dx, dw.astype(w.dtype), dlabels,
            None if bias is None else db.astype(bias.dtype))


linear_cross_entropy_arrays.defvjp(_lce_fwd, _lce_bwd)
