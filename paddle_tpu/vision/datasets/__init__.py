"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: loaders read standard local files (download=False
semantics); `FakeData` provides deterministic synthetic data for tests and
benchmarks (the reference's tests download; ours must not).
"""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io.dataset import Dataset

__all__ = ['MNIST', 'FashionMNIST', 'Cifar10', 'Cifar100', 'FakeData',
           'DatasetFolder', 'ImageFolder', 'Flowers', 'VOC2012']


class FakeData(Dataset):
    """Deterministic synthetic images (size, shape, classes configurable)."""

    def __init__(self, num_samples=1024, image_shape=(1, 28, 28),
                 num_classes=10, mode='train', transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed + (0 if mode == 'train' else 1))
        self._labels = rng.randint(0, num_classes, size=num_samples)
        self._seeds = rng.randint(0, 2 ** 31 - 1, size=num_samples)

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seeds[idx])
        img = rng.standard_normal(self.image_shape).astype(np.float32) * 0.5
        # class-dependent bright square (conv-learnable spatial pattern)
        label = int(self._labels[idx])
        if len(self.image_shape) == 3:
            _, h, w = self.image_shape
            side = max(h // 7, 2)
            cols = max(w // side, 1)
            r = (label // cols) * side % max(h - side, 1)
            c = (label % cols) * side % max(w - side, 1)
            img[:, r:r + side, c:c + side] += 3.0
        else:
            img.reshape(-1)[:self.num_classes] += \
                np.eye(self.num_classes, dtype=np.float32)[label] * 3.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """IDX-format loader (reference: vision/datasets/mnist.py). Point
    image_path/label_path at local idx files."""
    NAME = 'mnist'

    def __init__(self, image_path=None, label_path=None, mode='train',
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        base = os.environ.get('PADDLE_TPU_DATA_HOME',
                              os.path.expanduser('~/.cache/paddle_tpu'))
        prefix = 'train' if mode == 'train' else 't10k'
        self.image_path = image_path or os.path.join(
            base, self.NAME, '%s-images-idx3-ubyte.gz' % prefix)
        self.label_path = label_path or os.path.join(
            base, self.NAME, '%s-labels-idx1-ubyte.gz' % prefix)
        if not os.path.exists(self.image_path):
            raise FileNotFoundError(
                "MNIST idx files not found at %s (zero-egress env: place "
                "files locally or use vision.datasets.FakeData)" %
                self.image_path)
        self._load()

    def _load(self):
        opener = gzip.open if self.image_path.endswith('.gz') else open
        with opener(self.image_path, 'rb') as f:
            magic, n, rows, cols = struct.unpack('>IIII', f.read(16))
            self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                n, rows, cols)
        with opener(self.label_path, 'rb') as f:
            magic, n = struct.unpack('>II', f.read(8))
            self.labels = np.frombuffer(f.read(), dtype=np.uint8).astype(
                np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None, :, :] / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    NAME = 'fashion-mnist'


class Cifar10(Dataset):
    """python-pickle batches loader (reference: vision/datasets/cifar.py)."""

    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend=None):
        self.transform = transform
        base = os.environ.get('PADDLE_TPU_DATA_HOME',
                              os.path.expanduser('~/.cache/paddle_tpu'))
        self.data_file = data_file or os.path.join(base, 'cifar',
                                                   'cifar-10-python.tar.gz')
        if not os.path.exists(self.data_file):
            raise FileNotFoundError(
                "cifar archive not found at %s (zero-egress env: place it "
                "locally or use vision.datasets.FakeData)" % self.data_file)
        names = ['data_batch_%d' % i for i in range(1, 6)] if mode == 'train' \
            else ['test_batch']
        xs, ys = [], []
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                if any(m.name.endswith(n) for n in names):
                    d = pickle.load(tf.extractfile(m), encoding='bytes')
                    xs.append(d[b'data'])
                    ys.extend(d[b'labels' if b'labels' in d else b'fine_labels'])
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.data[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend=None):
        base = os.environ.get('PADDLE_TPU_DATA_HOME',
                              os.path.expanduser('~/.cache/paddle_tpu'))
        data_file = data_file or os.path.join(base, 'cifar',
                                              'cifar-100-python.tar.gz')
        super().__init__(data_file, mode, transform, download, backend)


IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.ppm', '.bmp', '.npy')


def _load_image(path):
    if path.endswith('.npy'):
        return np.load(path)
    try:
        from PIL import Image
        return np.asarray(Image.open(path).convert('RGB'))
    except ImportError as e:
        raise RuntimeError("PIL unavailable; use .npy images") from e


class DatasetFolder(Dataset):
    """class-subdir image tree (reference: vision/datasets/folder.py)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _load_image
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(d, fname),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, dtype=np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.loader = loader or _load_image
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        self.samples = [os.path.join(root, f) for f in sorted(os.listdir(root))
                        if f.lower().endswith(extensions)]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode='train', transform=None, download=True, backend=None):
        raise FileNotFoundError(
            "Flowers requires local archives (zero-egress env); use "
            "DatasetFolder over an extracted copy or FakeData")


class VOC2012(Dataset):
    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend=None):
        raise FileNotFoundError(
            "VOC2012 requires local archives (zero-egress env); use "
            "DatasetFolder over an extracted copy or FakeData")
