"""Detection op tranche (reference: paddle/fluid/operators/detection/ —
matrix_nms_op.cc, multiclass_nms_op.cc, iou_similarity_op.cc,
box_clip_op.cc, sigmoid_focal_loss_op.cc, anchor_generator_op.cc,
bipartite_match_op.cc). TPU-first design: every op is fixed-shape and
mask-based (XLA needs static shapes), so "variable-size" outputs come
back PADDED to keep_top_k with label=-1 rows plus an explicit rois_num —
the reference's multiclass_nms3/rois_num convention generalized to the
whole family (its earlier LoD outputs carry the same information).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['iou_similarity', 'box_clip', 'sigmoid_focal_loss',
           'anchor_generator', 'bipartite_match', 'matrix_nms',
           'multiclass_nms', 'multiclass_nms2', 'multiclass_nms3']


def _unwrap(x):
    from ..framework.core import Tensor
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(a):
    from ..framework.core import Tensor
    return Tensor(a)


def _pairwise_iou(a, b, normalized=True):
    """a [N,4], b [M,4] (x1,y1,x2,y2) -> [N,M]."""
    off = 0.0 if normalized else 1.0
    area = lambda box: jnp.maximum(box[..., 2] - box[..., 0] + off, 0) * \
        jnp.maximum(box[..., 3] - box[..., 1] + off, 0)
    ax = area(a)[:, None]
    bx = area(b)[None, :]
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(ax + bx - inter, 1e-10)


def iou_similarity(x, y, box_normalized=True, name=None):
    """[N,4] x [M,4] -> [N,M] IoU (iou_similarity_op.cc)."""
    return _wrap(_pairwise_iou(_unwrap(x), _unwrap(y),
                               normalized=box_normalized))


def box_clip(input, im_shape, name=None):
    """Clip boxes to image bounds (box_clip_op.cc). input [..., N, 4],
    im_shape [..., 2] = (h, w); boxes clip to [0, w-1] x [0, h-1]."""
    boxes = _unwrap(input)
    im = _unwrap(im_shape).astype(boxes.dtype)
    h = im[..., None, 0:1]
    w = im[..., None, 1:2]
    x1 = jnp.clip(boxes[..., 0:1], 0, w - 1)
    y1 = jnp.clip(boxes[..., 1:2], 0, h - 1)
    x2 = jnp.clip(boxes[..., 2:3], 0, w - 1)
    y2 = jnp.clip(boxes[..., 3:4], 0, h - 1)
    return _wrap(jnp.concatenate([x1, y1, x2, y2], axis=-1))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction='sum', name=None):
    """Focal loss over sigmoid probs (sigmoid_focal_loss_op.cc; modern
    paddle.nn.functional signature — label is one/multi-hot float)."""
    from ..framework.core import run_op

    def fn(x, lab, *rest):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * lab + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * lab + (1 - p) * (1 - lab)
        a_t = alpha * lab + (1 - alpha) * (1 - lab)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        if reduction == 'sum':
            return jnp.sum(loss)
        if reduction == 'mean':
            return jnp.mean(loss)
        return loss

    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return run_op('sigmoid_focal_loss', fn, *args)


def anchor_generator(input, anchor_sizes, aspect_ratios, variances=None,
                     stride=None, offset=0.5, name=None):
    """Per-pixel anchors for an [N,C,H,W] feature map
    (anchor_generator_op.cc). Returns (anchors [H,W,A,4],
    variances [H,W,A,4])."""
    x = _unwrap(input)
    h, w = int(x.shape[-2]), int(x.shape[-1])
    sx, sy = (stride if stride else (16.0, 16.0))
    variances = variances or [0.1, 0.1, 0.2, 0.2]
    whs = []
    for ar in aspect_ratios:
        for size in anchor_sizes:
            aw = size * np.sqrt(1.0 / ar)
            ah = size * np.sqrt(ar)
            whs.append((aw, ah))
    whs = jnp.asarray(whs, jnp.float32)  # [A, 2]
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * sx
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * sy
    cxg, cyg = jnp.meshgrid(cx, cy)          # [H, W]
    centers = jnp.stack([cxg, cyg], axis=-1)  # [H, W, 2]
    half = whs / 2.0
    mins = centers[:, :, None, :] - half[None, None, :, :]
    maxs = centers[:, :, None, :] + half[None, None, :, :]
    anchors = jnp.concatenate([mins, maxs], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return _wrap(anchors), _wrap(var)


def bipartite_match(dist_matrix, match_type='bipartite', dist_threshold=0.5,
                    name=None):
    """Greedy bipartite matching (bipartite_match_op.cc): repeatedly take
    the globally largest entry, pair that row/col, exclude both. Returns
    (match_indices [B, M] int32 row-or--1, match_dist [B, M])."""
    d = _unwrap(dist_matrix)
    if d.ndim == 2:
        d = d[None]
    bsz, n, m = d.shape

    def per_batch(dm):
        idx0 = jnp.full((m,), -1, jnp.int32)
        dist0 = jnp.zeros((m,), dm.dtype)

        def body(_, carry):
            cur, idx, dist = carry
            flat = jnp.argmax(cur)
            i, j = flat // m, flat % m
            best = cur[i, j]
            take = best > 0
            idx = jnp.where(take, idx.at[j].set(i.astype(jnp.int32)), idx)
            dist = jnp.where(take, dist.at[j].set(best), dist)
            cur = jnp.where(take, cur.at[i, :].set(-1.0), cur)
            cur = jnp.where(take, cur.at[:, j].set(-1.0), cur)
            return cur, idx, dist

        _, idx, dist = jax.lax.fori_loop(0, min(n, m), body,
                                         (dm, idx0, dist0))
        if match_type == 'per_prediction':
            # second pass: unmatched cols take their argmax row if the
            # distance clears the threshold
            col_best = jnp.argmax(dm, axis=0).astype(jnp.int32)
            col_dist = jnp.max(dm, axis=0)
            extra = (idx < 0) & (col_dist >= dist_threshold)
            idx = jnp.where(extra, col_best, idx)
            dist = jnp.where(extra, col_dist, dist)
        return idx, dist

    idx, dist = jax.vmap(per_batch)(d)
    return _wrap(idx), _wrap(dist)


# -- NMS family --------------------------------------------------------------

def _matrix_nms_batch(boxes, scores, score_threshold, post_threshold,
                      nms_top_k, keep_top_k, use_gaussian, gaussian_sigma,
                      background_label, normalized):
    """boxes [M,4]; scores [C,M] -> (out [K,6], count, index [K])."""
    C, M = scores.shape
    k = min(nms_top_k, M) if nms_top_k > 0 else M

    cls_ids = jnp.arange(C)
    bg_mask = (cls_ids == background_label)[:, None]  # [C,1]
    s = jnp.where(bg_mask, -1.0, scores)
    s = jnp.where(s > score_threshold, s, -1.0)

    order = jnp.argsort(-s, axis=1)[:, :k]           # [C,k]
    top_s = jnp.take_along_axis(s, order, axis=1)    # [C,k]
    top_b = boxes[order]                             # [C,k,4]

    iou = jax.vmap(lambda bb: _pairwise_iou(bb, bb, normalized))(top_b)
    # tri[j, i] == True iff i < j: row j is the candidate, column i its
    # (higher-scored) potential suppressor
    tri = jnp.tril(jnp.ones((k, k), bool), -1)
    iou_ji = jnp.where(tri[None], iou, 0.0)          # [C, j, i]
    # compensate_i = max_{l<i} iou_li (how suppressed the suppressor is)
    comp = jnp.max(iou_ji, axis=2)                   # [C, k] by row index
    comp_i = comp[:, None, :]                        # broadcast on column i
    if use_gaussian:
        decay = jnp.exp(-(iou_ji ** 2 - comp_i ** 2) / gaussian_sigma)
    else:
        decay = (1.0 - iou_ji) / jnp.maximum(1.0 - comp_i, 1e-10)
    decay = jnp.where(tri[None], decay, 1.0)
    decay = jnp.min(decay, axis=2)                   # min over i<j -> [C,k]
    new_s = jnp.where(top_s > 0, top_s * decay, -1.0)
    new_s = jnp.where(new_s > post_threshold, new_s, -1.0)

    flat_s = new_s.reshape(-1)
    flat_lbl = jnp.broadcast_to(cls_ids[:, None], (C, k)).reshape(-1)
    flat_box = top_b.reshape(-1, 4)
    flat_idx = jnp.broadcast_to(order, (C, k)).reshape(-1)

    K = keep_top_k if keep_top_k > 0 else flat_s.shape[0]
    K = min(K, flat_s.shape[0])
    kept_s, kept_pos = jax.lax.top_k(flat_s, K)
    valid = kept_s > 0
    out = jnp.concatenate([
        jnp.where(valid, flat_lbl[kept_pos], -1)[:, None].astype(boxes.dtype),
        jnp.where(valid, kept_s, -1.0)[:, None],
        jnp.where(valid[:, None], flat_box[kept_pos], -1.0)], axis=1)
    index = jnp.where(valid, flat_idx[kept_pos], -1).astype(jnp.int32)
    return out, jnp.sum(valid.astype(jnp.int32)), index


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (matrix_nms_op.cc; SOLOv2 decay formulation): per class,
    each candidate's score decays by the most-suppressing higher-scored
    box, with the suppressor's own overlap compensated. bboxes [B,M,4],
    scores [B,C,M]. Returns out [B*K, 6] (label, score, x1y1x2y2; padded
    rows label=-1), optional index [B*K], rois_num [B]."""
    boxes = _unwrap(bboxes)
    s = _unwrap(scores)
    fn = functools.partial(
        _matrix_nms_batch, score_threshold=score_threshold,
        post_threshold=post_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, use_gaussian=use_gaussian,
        gaussian_sigma=gaussian_sigma, background_label=background_label,
        normalized=normalized)
    out, counts, index = jax.vmap(fn)(boxes, s)
    out = out.reshape(-1, 6)
    index = index.reshape(-1)
    res = [_wrap(out)]
    if return_index:
        res.append(_wrap(index))
    if return_rois_num:
        res.append(_wrap(counts.astype(jnp.int32)))
    return tuple(res) if len(res) > 1 else res[0]


def _hard_nms_batch(boxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold, normalized, background_label):
    """boxes [M,4], scores [C,M] -> (out [K,6], count, index [K])."""
    C, M = scores.shape
    k = min(nms_top_k, M) if nms_top_k > 0 else M
    cls_ids = jnp.arange(C)
    s = jnp.where((cls_ids == background_label)[:, None], -1.0, scores)
    s = jnp.where(s > score_threshold, s, -1.0)
    order = jnp.argsort(-s, axis=1)[:, :k]
    top_s = jnp.take_along_axis(s, order, axis=1)
    top_b = boxes[order]
    iou = jax.vmap(lambda bb: _pairwise_iou(bb, bb, normalized))(top_b)

    def suppress(iou_c, valid_c):
        def body(i, kept):
            sup = (iou_c[i] > nms_threshold) & kept[i] & \
                (jnp.arange(k) > i)
            return kept & ~sup
        return jax.lax.fori_loop(0, k, body, valid_c)

    kept = jax.vmap(suppress)(iou, top_s > 0)
    new_s = jnp.where(kept, top_s, -1.0)

    flat_s = new_s.reshape(-1)
    flat_lbl = jnp.broadcast_to(cls_ids[:, None], (C, k)).reshape(-1)
    flat_box = top_b.reshape(-1, 4)
    flat_idx = jnp.broadcast_to(order, (C, k)).reshape(-1)
    K = keep_top_k if keep_top_k > 0 else flat_s.shape[0]
    K = min(K, flat_s.shape[0])
    kept_s, kept_pos = jax.lax.top_k(flat_s, K)
    valid = kept_s > 0
    out = jnp.concatenate([
        jnp.where(valid, flat_lbl[kept_pos], -1)[:, None].astype(boxes.dtype),
        jnp.where(valid, kept_s, -1.0)[:, None],
        jnp.where(valid[:, None], flat_box[kept_pos], -1.0)], axis=1)
    index = jnp.where(valid, flat_idx[kept_pos], -1).astype(jnp.int32)
    return out, jnp.sum(valid.astype(jnp.int32)), index


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=1000,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   return_rois_num=True, rois_num=None, name=None):
    """Per-class hard NMS + cross-class keep_top_k (multiclass_nms_op.cc).
    bboxes [B,M,4], scores [B,C,M]. Same padded-output convention as
    matrix_nms."""
    boxes = _unwrap(bboxes)
    s = _unwrap(scores)
    fn = functools.partial(
        _hard_nms_batch, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, normalized=normalized,
        background_label=background_label)
    out, counts, index = jax.vmap(fn)(boxes, s)
    out = out.reshape(-1, 6)
    index = index.reshape(-1)
    res = [_wrap(out)]
    if return_index:
        res.append(_wrap(index))
    if return_rois_num:
        res.append(_wrap(counts.astype(jnp.int32)))
    return tuple(res) if len(res) > 1 else res[0]


def multiclass_nms2(bboxes, scores, **kwargs):
    """multiclass_nms + kept-box index output (multiclass_nms2 op)."""
    kwargs['return_index'] = True
    return multiclass_nms(bboxes, scores, **kwargs)


def multiclass_nms3(bboxes, scores, rois_num=None, **kwargs):
    """rois_num-in/rois_num-out variant (multiclass_nms3 op)."""
    kwargs.setdefault('return_rois_num', True)
    return multiclass_nms(bboxes, scores, rois_num=rois_num, **kwargs)
