"""paddle.vision parity (reference: python/paddle/vision/)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from . import detection  # noqa: F401
from .models import *  # noqa: F401,F403


def set_image_backend(backend):
    pass


def get_image_backend():
    return 'numpy'


def image_load(path, backend=None):
    from .datasets import _load_image
    return _load_image(path)
