"""Vision ops (reference: python/paddle/vision/ops.py — yolo_box, yolo_loss,
nms, roi_align, deform_conv, distribute_fpn_proposals…).

Detection post-processing ops are jnp where shape-static (TPU-jittable) and
numpy where inherently dynamic (host post-processing, same place the
reference runs them in deployment).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, wrap_out
from ..tensor._helpers import ensure_tensor

__all__ = ['read_file', 'decode_jpeg',
           'yolo_box', 'yolo_loss', 'nms', 'roi_align', 'roi_pool',
           'box_coder', 'prior_box', 'deform_conv2d', 'DeformConv2D',
           'distribute_fpn_proposals', 'generate_proposals', 'PSRoIPool',
           'RoIAlign', 'RoIPool']


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (operators/detection/
    yolo_box_op.* parity, fully vectorized for TPU)."""
    x = ensure_tensor(x)
    imgs = ensure_tensor(img_size)._data
    na = len(anchors) // 2
    anchors_arr = jnp.asarray(anchors, jnp.float32).reshape(na, 2)

    def fn(a):
        n, c, h, w = a.shape
        ioup = None
        if iou_aware:
            # reference yolo_box_op iou_aware layout: the first `na`
            # channels are per-anchor IoU predictions, the rest is the
            # standard head
            ioup = jax.nn.sigmoid(a[:, :na].reshape(n, na, h, w))
            a = a[:, na:]
        a = a.reshape(n, na, -1, h, w)
        grid_x = jnp.arange(w, dtype=jnp.float32).reshape(1, 1, 1, w)
        grid_y = jnp.arange(h, dtype=jnp.float32).reshape(1, 1, h, 1)
        bx = (jax.nn.sigmoid(a[:, :, 0]) * scale_x_y -
              0.5 * (scale_x_y - 1.0) + grid_x) / w
        by = (jax.nn.sigmoid(a[:, :, 1]) * scale_x_y -
              0.5 * (scale_x_y - 1.0) + grid_y) / h
        bw = jnp.exp(a[:, :, 2]) * anchors_arr[:, 0].reshape(1, na, 1, 1) / \
            (w * downsample_ratio)
        bh = jnp.exp(a[:, :, 3]) * anchors_arr[:, 1].reshape(1, na, 1, 1) / \
            (h * downsample_ratio)
        conf = jax.nn.sigmoid(a[:, :, 4])
        if ioup is not None:
            # PP-YOLO IoU-aware confidence: obj^(1-f) * iou^f
            conf = conf ** (1.0 - iou_aware_factor) * \
                ioup ** iou_aware_factor
        probs = jax.nn.sigmoid(a[:, :, 5:5 + class_num])
        scores = conf[:, :, None] * probs
        img_h = imgs[:, 0].reshape(n, 1, 1, 1).astype(jnp.float32)
        img_w = imgs[:, 1].reshape(n, 1, 1, 1).astype(jnp.float32)
        x0 = (bx - bw / 2) * img_w
        y0 = (by - bh / 2) * img_h
        x1 = (bx + bw / 2) * img_w
        y1 = (by + bh / 2) * img_h
        if clip_bbox:
            x0 = jnp.clip(x0, 0, img_w - 1)
            y0 = jnp.clip(y0, 0, img_h - 1)
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(n, -1, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        mask = (conf.reshape(n, -1, 1) > conf_thresh).astype(boxes.dtype)
        return boxes * mask, scores * mask
    boxes, scores = run_op('yolo_box', fn, x)
    return boxes, scores


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (operators/detection/yolov3_loss_op.*)."""
    x = ensure_tensor(x)
    gtb = ensure_tensor(gt_box)._data
    gtl = ensure_tensor(gt_label)._data
    na = len(anchor_mask)
    anchors_full = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    mask_anchors = anchors_full[jnp.asarray(anchor_mask)]

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, na, 5 + class_num, h, w)
        input_size = h * downsample_ratio
        # build targets: per gt box, responsible anchor/cell
        bx = gtb[..., 0] * w
        by = gtb[..., 1] * h
        gw = gtb[..., 2] * input_size
        gh = gtb[..., 3] * input_size
        gi = jnp.clip(bx.astype(jnp.int32), 0, w - 1)
        gj = jnp.clip(by.astype(jnp.int32), 0, h - 1)
        # anchor iou on wh
        inter = jnp.minimum(gw[..., None], anchors_full[:, 0]) * \
            jnp.minimum(gh[..., None], anchors_full[:, 1])
        union = gw[..., None] * gh[..., None] + \
            anchors_full[:, 0] * anchors_full[:, 1] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)
        valid = (gtb[..., 2] > 0)
        mask_idx = jnp.asarray(anchor_mask)
        in_mask = (best[..., None] == mask_idx).any(-1) & valid
        local_a = jnp.argmax((best[..., None] == mask_idx).astype(jnp.int32),
                             axis=-1)

        bidx = jnp.arange(n)[:, None] * jnp.ones_like(gi)
        sel = (bidx, local_a, gj, gi)
        tx = bx - jnp.floor(bx)
        ty = by - jnp.floor(by)
        tw = jnp.log(jnp.maximum(gw / jnp.maximum(
            mask_anchors[local_a, 0], 1e-9), 1e-9))
        th = jnp.log(jnp.maximum(gh / jnp.maximum(
            mask_anchors[local_a, 1], 1e-9), 1e-9))
        scale = 2.0 - gtb[..., 2] * gtb[..., 3]

        # scale_x_y (PP-YOLO trick): stretch the sigmoid box center
        px = jax.nn.sigmoid(a[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1.0)
        py = jax.nn.sigmoid(a[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1.0)
        pw = a[:, :, 2]
        ph = a[:, :, 3]
        pobj = a[:, :, 4]
        pcls = a[:, :, 5:]

        m = in_mask.astype(a.dtype)
        if gt_score is not None:
            # mixup/soft scores weight every positive term (reference
            # yolov3_loss GTScore input)
            m = m * ensure_tensor(gt_score)._data.astype(a.dtype)
        loss_xy = jnp.sum(m * scale * ((px[sel] - tx) ** 2 + (py[sel] - ty) ** 2))
        loss_wh = jnp.sum(m * scale * ((pw[sel] - tw) ** 2 + (ph[sel] - th) ** 2))
        obj_target = jnp.zeros((n, na, h, w), a.dtype)
        obj_target = obj_target.at[sel].max(m)
        # ignore_thresh (reference yolov3_loss_op.h CalcObjnessLoss):
        # negatives whose PREDICTED box overlaps any gt above the
        # threshold are excluded from the objectness loss
        grid_x = jnp.arange(w, dtype=a.dtype)[None, None, None, :]
        grid_y = jnp.arange(h, dtype=a.dtype)[None, None, :, None]
        pbx = (grid_x + px) / w                                  # [n,na,h,w]
        pby = (grid_y + py) / h
        pbw = jnp.exp(jnp.clip(pw, -10, 10)) * \
            mask_anchors[:, 0][None, :, None, None] / input_size
        pbh = jnp.exp(jnp.clip(ph, -10, 10)) * \
            mask_anchors[:, 1][None, :, None, None] / input_size
        # corners, normalized coords; gt boxes are (cx, cy, w, h) norm
        p_x0, p_x1 = pbx - pbw / 2, pbx + pbw / 2
        p_y0, p_y1 = pby - pbh / 2, pby + pbh / 2
        g_x0 = (gtb[..., 0] - gtb[..., 2] / 2)                   # [n, G]
        g_x1 = (gtb[..., 0] + gtb[..., 2] / 2)
        g_y0 = (gtb[..., 1] - gtb[..., 3] / 2)
        g_y1 = (gtb[..., 1] + gtb[..., 3] / 2)
        ex = (slice(None), None, None, None)  # broadcast gt over na,h,w
        iw = jnp.maximum(jnp.minimum(p_x1[..., None], g_x1[ex]) -
                         jnp.maximum(p_x0[..., None], g_x0[ex]), 0.0)
        ih = jnp.maximum(jnp.minimum(p_y1[..., None], g_y1[ex]) -
                         jnp.maximum(p_y0[..., None], g_y0[ex]), 0.0)
        inter_pg = iw * ih
        area_p = (pbw * pbh)[..., None]
        area_g = (gtb[..., 2] * gtb[..., 3])[ex]
        iou_pg = inter_pg / jnp.maximum(area_p + area_g - inter_pg, 1e-9)
        iou_pg = jnp.where(valid[ex] > 0, iou_pg, 0.0)
        best_iou = iou_pg.max(-1)                               # [n,na,h,w]
        noobj_keep = (best_iou <= ignore_thresh).astype(a.dtype)
        obj_weight = obj_target + (1.0 - jnp.minimum(obj_target, 1.0)) * \
            noobj_keep
        bce = jnp.maximum(pobj, 0) - pobj * obj_target + \
            jnp.log1p(jnp.exp(-jnp.abs(pobj)))
        loss_obj = jnp.sum(bce * obj_weight)
        smooth = 1.0 / class_num if use_label_smooth else 0.0
        cls_target = jax.nn.one_hot(gtl, class_num, dtype=a.dtype)
        cls_target = cls_target * (1 - smooth) + smooth / 2
        pc = pcls.transpose(0, 1, 3, 4, 2)[sel]
        bce_c = jnp.maximum(pc, 0) - pc * cls_target + \
            jnp.log1p(jnp.exp(-jnp.abs(pc)))
        loss_cls = jnp.sum(m[..., None] * bce_c)
        return (loss_xy + loss_wh + loss_obj + loss_cls) * jnp.ones((n,)) / n
    return run_op('yolo_loss', fn, x)


def _iou_matrix(boxes, offset=0.0):
    # offset=1 reproduces the reference's legacy pixel-inclusive overlap
    # (JaccardOverlap with normalized=false)
    x0, y0, x1, y1 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = (x1 - x0 + offset) * (y1 - y0 + offset)
    ix0 = np.maximum(x0[:, None], x0[None, :])
    iy0 = np.maximum(y0[:, None], y0[None, :])
    ix1 = np.minimum(x1[:, None], x1[None, :])
    iy1 = np.minimum(y1[:, None], y1[None, :])
    iw = np.maximum(ix1 - ix0 + offset, 0)
    ih = np.maximum(iy1 - iy0 + offset, 0)
    inter = iw * ih
    return inter / np.maximum(area[:, None] + area[None, :] - inter, 1e-9)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host NMS (operators/detection/nms_op parity; dynamic output shape
    keeps this off-device, same as deployment practice)."""
    if categories is not None and category_idxs is None:
        raise ValueError('nms: `categories` requires `category_idxs` '
                         '(per-box class ids)')
    b = ensure_tensor(boxes).numpy()
    s = ensure_tensor(scores).numpy() if scores is not None else None
    order = np.argsort(-s) if s is not None else np.arange(len(b))
    if category_idxs is not None:
        cats = ensure_tensor(category_idxs).numpy()
    else:
        cats = np.zeros(len(b), dtype=np.int64)
    iou = _iou_matrix(b)
    keep = []
    suppressed = np.zeros(len(b), dtype=bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        over = (iou[i] > iou_threshold) & (cats == cats[i])
        suppressed |= over
        suppressed[i] = True
    keep = np.asarray(keep, dtype=np.int64)
    if categories is not None:
        # reference: `categories` lists the class ids eligible for output
        keep = keep[np.isin(cats[keep], np.asarray(categories))]
    if top_k is not None:
        keep = keep[:top_k]
    return wrap_out(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear grid sampling (operators/roi_align_op parity)."""
    x = ensure_tensor(x)
    rois = ensure_tensor(boxes)._data
    nums = ensure_tensor(boxes_num)._data
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(feat):
        n, c, h, w = feat.shape
        # batch index per roi
        batch_idx = jnp.repeat(jnp.arange(nums.shape[0]), nums,
                               total_repeat_length=rois.shape[0])
        offset = 0.5 if aligned else 0.0
        x0 = rois[:, 0] * spatial_scale - offset
        y0 = rois[:, 1] * spatial_scale - offset
        x1 = rois[:, 2] * spatial_scale - offset
        y1 = rois[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x1 - x0, 1e-3)
        rh = jnp.maximum(y1 - y0, 1e-3)
        # sampling_ratio (reference roi_align_op.h): s^2 sample points
        # per bin, averaged; <=0 means one centered sample per bin
        # (the adaptive ceil(roi/bin) count is roi-dependent and thus
        # shape-dynamic — the fixed-grid approximation keeps this
        # jittable, biasing only very large rois)
        s = max(1, int(sampling_ratio)) if sampling_ratio and \
            sampling_ratio > 0 else 1
        grid_h = (jnp.arange(ph)[:, None] +
                  (jnp.arange(s) + 0.5)[None, :] / s).reshape(-1) / ph
        grid_w = (jnp.arange(pw)[:, None] +
                  (jnp.arange(s) + 0.5)[None, :] / s).reshape(-1) / pw
        ys = y0[:, None] + grid_h[None, :] * rh[:, None]   # [R, ph*s]
        xs = x0[:, None] + grid_w[None, :] * rw[:, None]   # [R, pw*s]

        def bilinear(fmap, yy, xx):
            y0i = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0i = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1i = jnp.clip(y0i + 1, 0, h - 1)
            x1i = jnp.clip(x0i + 1, 0, w - 1)
            wy = yy - y0i
            wx = xx - x0i
            v00 = fmap[:, y0i, x0i]
            v01 = fmap[:, y0i, x1i]
            v10 = fmap[:, y1i, x0i]
            v11 = fmap[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        def per_roi(bi, ys_r, xs_r):
            fmap = feat[bi]
            yy = jnp.repeat(ys_r, pw * s)          # [ph*s * pw*s]
            xx = jnp.tile(xs_r, ph * s)
            vals = bilinear(fmap, yy, xx)          # [C, ph*s*pw*s]
            vals = vals.reshape(c, ph, s, pw, s)
            return vals.mean(axis=(2, 4))          # average the s^2 samples
        out = jax.vmap(per_roi)(batch_idx, ys, xs)
        return out
    return run_op('roi_align', fn, x)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     aligned=False)


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool(RoIAlign):
    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(RoIAlign):
    pass


def box_coder(prior_box, prior_box_var, target_box, code_type='encode_center_size',
              box_normalized=True, axis=0, name=None):
    pb = ensure_tensor(prior_box)._data
    pbv = ensure_tensor(prior_box_var)._data if not isinstance(
        prior_box_var, (list, tuple)) else jnp.asarray(prior_box_var)
    tb = ensure_tensor(target_box)

    def fn(t):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph_ = pb[:, 3] - pb[:, 1] + norm
        pcx = (pb[:, 0] + pb[:, 2]) / 2
        pcy = (pb[:, 1] + pb[:, 3]) / 2
        if code_type == 'encode_center_size':
            # reference: every target row encodes against EVERY prior ->
            # [N, M, 4] (the M priors ride dim 1)
            tw = (t[:, 2] - t[:, 0] + norm)[:, None]
            th = (t[:, 3] - t[:, 1] + norm)[:, None]
            tcx = ((t[:, 0] + t[:, 2]) / 2)[:, None]
            tcy = ((t[:, 1] + t[:, 3]) / 2)[:, None]
            pbv_e = pbv if pbv.ndim == 2 else pbv[None]
            ox = (tcx - pcx[None]) / pw[None] / pbv_e[..., 0]
            oy = (tcy - pcy[None]) / ph_[None] / pbv_e[..., 1]
            ow = jnp.log(tw / pw[None]) / pbv_e[..., 2]
            oh = jnp.log(th / ph_[None]) / pbv_e[..., 3]
            return jnp.stack([ox, oy, ow, oh], axis=-1)
        # decode: `axis` names the dim of a [N, M, 4] target the priors
        # BROADCAST ALONG (reference box_coder_op): axis=0 -> priors
        # [M, 4] ride dim 1; axis=1 -> priors ride dim 0
        if t.ndim == 3:
            ex = (None, slice(None)) if axis == 0 else (slice(None), None)
            pw_b, ph_b, pcx_b, pcy_b = pw[ex], ph_[ex], pcx[ex], pcy[ex]
            pbv_b = pbv[ex + (slice(None),)] if pbv.ndim == 2 else pbv
        else:
            pw_b, ph_b, pcx_b, pcy_b, pbv_b = pw, ph_, pcx, pcy, pbv
        ox = t[..., 0] * pbv_b[..., 0] * pw_b + pcx_b
        oy = t[..., 1] * pbv_b[..., 1] * ph_b + pcy_b
        ow = jnp.exp(t[..., 2] * pbv_b[..., 2]) * pw_b
        oh = jnp.exp(t[..., 3] * pbv_b[..., 3]) * ph_b
        return jnp.stack([ox - ow / 2, oy - oh / 2,
                          ox + ow / 2 - norm, oy + oh / 2 - norm], axis=-1)
    return run_op('box_coder', fn, tb)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0., 0.), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    feat = ensure_tensor(input)
    img = ensure_tensor(image)
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_h = steps[1] or ih / h
    step_w = steps[0] or iw / w
    ars = [1.0]
    for ar in aspect_ratios:
        if ar != 1.0:
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        sizes = []
        for ar in ars:
            sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            mx = max_sizes[ms_i]
            # reference order: [min&ars..., max] by default; the
            # min_max_aspect_ratios_order flag moves max right after the
            # ar=1 min box (Caffe order)
            if min_max_aspect_ratios_order:
                sizes.insert(1, (np.sqrt(ms * mx), np.sqrt(ms * mx)))
            else:
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
        for (bw, bh) in sizes:
            cy, cx = np.mgrid[0:h, 0:w].astype(np.float32)
            cx = (cx + offset) * step_w
            cy = (cy + offset) * step_h
            boxes.append(np.stack([(cx - bw / 2) / iw, (cy - bh / 2) / ih,
                                   (cx + bw / 2) / iw, (cy + bh / 2) / ih],
                                  axis=-1))
    out = np.stack(boxes, axis=2)  # H, W, num_priors, 4
    if clip:
        out = np.clip(out, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32), out.shape).copy()
    return wrap_out(jnp.asarray(out)), wrap_out(jnp.asarray(var))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 via gather+matmul (operators/deformable_conv_op).
    Bilinear-samples input at offset positions then does a dense matmul —
    MXU-friendly formulation."""
    x = ensure_tensor(x)
    off = ensure_tensor(offset)
    w = ensure_tensor(weight)
    msk = ensure_tensor(mask) if mask is not None else None

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    sh, sw = _pair(stride)
    ph_, pw_ = _pair(padding)
    dh, dw = _pair(dilation)

    def fn(a, o, ww, *mb):
        n, cin, h, wdt = a.shape
        cout, cin_g, kh, kw = ww.shape
        oh = (h + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
        ow = (wdt + 2 * pw_ - dw * (kw - 1) - 1) // sw + 1
        a_p = jnp.pad(a, [(0, 0), (0, 0), (ph_, ph_), (pw_, pw_)])
        hp, wp = a_p.shape[2], a_p.shape[3]
        base_y = (jnp.arange(oh) * sh)[:, None, None] + \
            (jnp.arange(kh) * dh)[None, :, None]
        base_x = (jnp.arange(ow) * sw)[:, None, None] + \
            (jnp.arange(kw) * dw)[None, :, None]
        o = o.reshape(n, deformable_groups, kh * kw, 2, oh, ow)
        oy = o[:, :, :, 0]
        ox = o[:, :, :, 1]
        ky = jnp.arange(kh)[:, None] * jnp.ones((1, kw))
        kx = jnp.ones((kh, 1)) * jnp.arange(kw)[None, :]
        yy = base_y.reshape(oh, 1, kh, 1) + jnp.zeros((1, ow, 1, kw))
        xx = jnp.zeros((oh, 1, kh, 1)) + base_x.reshape(1, ow, 1, kw)
        yy = yy.reshape(1, 1, oh, ow, kh * kw) + \
            oy.transpose(0, 1, 3, 4, 2).reshape(n, deformable_groups, oh, ow,
                                                kh * kw)
        xx = xx.reshape(1, 1, oh, ow, kh * kw) + \
            ox.transpose(0, 1, 3, 4, 2).reshape(n, deformable_groups, oh, ow,
                                                kh * kw)
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy = yy - y0
        wx = xx - x0

        def gather(ai, yi, xi):
            yi_c = jnp.clip(yi.astype(jnp.int32), 0, hp - 1)
            xi_c = jnp.clip(xi.astype(jnp.int32), 0, wp - 1)
            inb = ((yi >= 0) & (yi <= hp - 1) & (xi >= 0) &
                   (xi <= wp - 1)).astype(ai.dtype)
            g = ai[:, :, yi_c, xi_c]
            return g * inb

        cpg = cin // deformable_groups
        outs = []
        for dg in range(deformable_groups):
            ai = a_p[:, dg * cpg:(dg + 1) * cpg]
            vals = 0.
            for (dy, dx, wgt) in [(0, 0, (1 - wy) * (1 - wx)),
                                  (0, 1, (1 - wy) * wx),
                                  (1, 0, wy * (1 - wx)), (1, 1, wy * wx)]:
                yi = y0[:, dg] + dy
                xi = x0[:, dg] + dx
                g = jax.vmap(lambda am, ym, xm: gather(
                    am[None], ym, xm)[0])(ai, yi, xi)
                vals = vals + g * wgt[:, None] if g.ndim == 5 else \
                    vals + g * wgt[:, dg if False else 0]
            outs.append(vals)
        sampled = jnp.concatenate(outs, axis=1)  # n, cin, oh, ow, kh*kw
        if mb and msk is not None:
            mm = mb[-1].reshape(n, deformable_groups, kh * kw, oh, ow)
            mm = jnp.repeat(mm, cpg, axis=1).transpose(0, 1, 3, 4, 2)
            sampled = sampled * mm
        cols = sampled.transpose(0, 2, 3, 1, 4).reshape(
            n, oh, ow, cin * kh * kw)
        wflat = ww.reshape(cout, cin_g * kh * kw)
        if groups == 1:
            out = jnp.einsum('nhwk,ck->nchw', cols, wflat)
        else:
            cols_g = cols.reshape(n, oh, ow, groups, -1)
            wg = wflat.reshape(groups, cout // groups, -1)
            out = jnp.einsum('nhwgk,gck->ngchw', cols_g, wg).reshape(
                n, cout, oh, ow)
        if mb and bias is not None:
            out = out + mb[0].reshape(1, -1, 1, 1)
        return out

    args = [x, off, w]
    if bias is not None:
        args.append(ensure_tensor(bias))
    if msk is not None:
        args.append(msk)
    return run_op('deform_conv2d', fn, *args)


class DeformConv2D:
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from .. import nn
        self._layer = nn.Conv2D(in_channels, out_channels, kernel_size,
                                stride, padding, dilation, groups,
                                weight_attr=weight_attr, bias_attr=bias_attr)
        self.args = (stride, padding, dilation, deformable_groups, groups)

    def __call__(self, x, offset, mask=None):
        s, p, d, dg, g = self.args
        return deform_conv2d(x, offset, self._layer.weight, self._layer.bias,
                             s, p, d, dg, g, mask)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    rois = ensure_tensor(fpn_rois).numpy()
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum((rois[:, 2] - rois[:, 0] + off) *
                               (rois[:, 3] - rois[:, 1] + off), 0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    for l in range(min_level, max_level + 1):
        sel = np.flatnonzero(lvl == l)
        outs.append(wrap_out(jnp.asarray(rois[sel])))
        idxs.append(sel)
    order = np.concatenate(idxs) if idxs else np.zeros(0, np.int64)
    restore = np.argsort(order)
    if rois_num is not None:
        # batched input: per-level outputs report PER-IMAGE counts
        # (reference rois_num_per_level), images delimited by rois_num
        rn = ensure_tensor(rois_num).numpy().astype(np.int64).reshape(-1)
        img_of = np.repeat(np.arange(len(rn)), rn)
        out_num = [wrap_out(jnp.asarray(np.bincount(
            img_of[i], minlength=len(rn)).astype(np.int32)))
            for i in idxs]
    else:
        out_num = [wrap_out(jnp.asarray(np.asarray([len(i)], np.int32)))
                   for i in idxs]
    return outs, wrap_out(jnp.asarray(restore.reshape(-1, 1))), out_num


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    s = ensure_tensor(scores).numpy()
    d = ensure_tensor(bbox_deltas).numpy()
    a = ensure_tensor(anchors).numpy().reshape(-1, 4)
    v = ensure_tensor(variances).numpy().reshape(-1, 4)
    imgs = ensure_tensor(img_size).numpy()
    off = 1.0 if pixel_offset else 0.0
    n = s.shape[0]
    all_rois, all_scores, nums = [], [], []
    for b in range(n):
        sb = s[b].transpose(1, 2, 0).reshape(-1)
        db = d[b].transpose(1, 2, 0).reshape(-1, 4)
        order = np.argsort(-sb)[:pre_nms_top_n]
        sb, db, ab, vb = sb[order], db[order], a[order % len(a)], v[order % len(v)]
        aw = ab[:, 2] - ab[:, 0] + off
        ah = ab[:, 3] - ab[:, 1] + off
        acx = ab[:, 0] + aw / 2
        acy = ab[:, 1] + ah / 2
        cx = db[:, 0] * vb[:, 0] * aw + acx
        cy = db[:, 1] * vb[:, 1] * ah + acy
        bw = np.exp(np.minimum(db[:, 2] * vb[:, 2], 10)) * aw
        bh = np.exp(np.minimum(db[:, 3] * vb[:, 3], 10)) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2],
                         axis=-1)
        # clip to the image (reference proposal_op: boxes never exceed
        # [0, W-offset] x [0, H-offset])
        img_h, img_w = float(imgs[b][0]), float(imgs[b][1])
        boxes[:, 0] = np.clip(boxes[:, 0], 0, img_w - off)
        boxes[:, 1] = np.clip(boxes[:, 1], 0, img_h - off)
        boxes[:, 2] = np.clip(boxes[:, 2], 0, img_w - off)
        boxes[:, 3] = np.clip(boxes[:, 3], 0, img_h - off)
        bw_c = boxes[:, 2] - boxes[:, 0] + off
        bh_c = boxes[:, 3] - boxes[:, 1] + off
        eff_min = max(float(min_size), 1.0)  # reference FilterBoxes clamp
        keep_mask = (bw_c >= eff_min) & (bh_c >= eff_min)
        boxes, sb = boxes[keep_mask], sb[keep_mask]
        iou = _iou_matrix(boxes, offset=off)
        keep = []
        supp = np.zeros(len(boxes), bool)
        adaptive = nms_thresh
        for i in range(len(boxes)):
            if supp[i]:
                continue
            keep.append(i)
            if len(keep) >= post_nms_top_n:
                break
            supp |= iou[i] > adaptive
            supp[i] = True
            if eta < 1.0 and adaptive > 0.5:
                # reference adaptive NMS: threshold decays by eta
                adaptive *= eta
        all_rois.append(boxes[keep])
        all_scores.append(sb[keep])
        nums.append(len(keep))
    rois = wrap_out(jnp.asarray(np.concatenate(all_rois)))
    rscores = wrap_out(jnp.asarray(np.concatenate(all_scores)))
    if return_rois_num:
        return rois, rscores, wrap_out(jnp.asarray(np.asarray(nums, np.int32)))
    return rois, rscores


def read_file(filename, name=None):
    """File bytes as a uint8 tensor (reference read_file_op)."""
    from ..framework.core import Tensor
    with open(filename, 'rb') as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode='unchanged', name=None):
    """JPEG bytes tensor -> image tensor [C, H, W] uint8 (reference
    decode_jpeg op, nvjpeg-backed there; PIL-backed host decode here)."""
    import io as _io
    from PIL import Image
    from ..framework.core import Tensor
    data = bytes(np.asarray(x._data if hasattr(x, '_data') else x,
                            np.uint8).tobytes())
    img = Image.open(_io.BytesIO(data))
    if mode == 'gray':
        img = img.convert('L')
    elif mode == 'rgb':
        img = img.convert('RGB')
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
