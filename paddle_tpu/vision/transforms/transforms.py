"""Transform classes (reference: python/paddle/vision/transforms/transforms.py)."""
import random
import numbers

import numpy as np

from . import functional as F

__all__ = ['BaseTransform', 'Compose', 'ToTensor', 'Normalize', 'Resize',
           'RandomCrop', 'CenterCrop', 'RandomHorizontalFlip',
           'RandomVerticalFlip', 'RandomResizedCrop', 'Pad', 'Transpose',
           'RandomRotation', 'ColorJitter', 'Grayscale', 'BrightnessTransform',
           'ContrastTransform', 'SaturationTransform', 'HueTransform']


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format='CHW', keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format='CHW', to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation='bilinear', keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode='constant', keys=None):
        super().__init__(keys)
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        if h == th and w == tw:
            return arr
        top = random.randint(0, max(h - th, 0))
        left = random.randint(0, max(w - tw, 0))
        return F.crop(arr, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.hflip(img)
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.vflip(img)
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation='bilinear', keys=None):
        super().__init__(keys)
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            aspect = random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target_area * aspect)))
            th = int(round(np.sqrt(target_area / aspect)))
            if 0 < tw <= w and 0 < th <= h:
                top = random.randint(0, h - th)
                left = random.randint(0, w - tw)
                patch = F.crop(arr, top, left, th, tw)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(arr, min(h, w)), self.size,
                        self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode='constant', keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation='nearest', expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kwargs = dict(interpolation=interpolation, expand=expand,
                           center=center, fill=fill)

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, **self.kwargs)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = list(range(len(self.transforms)))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i](img)
        return np.asarray(img)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)
