"""Transform functionals over numpy HWC images (reference:
python/paddle/vision/transforms/functional*.py; CHW/HWC both supported)."""
import numbers

import numpy as np

from ...framework.core import Tensor

__all__ = ['to_tensor', 'normalize', 'resize', 'crop', 'center_crop', 'hflip',
           'vflip', 'pad', 'rotate', 'adjust_brightness', 'adjust_contrast',
           'adjust_saturation', 'adjust_hue', 'to_grayscale']


def _np_img(img):
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


def to_tensor(pic, data_format='CHW'):
    img = _np_img(pic)
    if img.ndim == 2:
        img = img[:, :, None]
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    else:
        img = img.astype(np.float32)
    if data_format == 'CHW':
        img = np.transpose(img, (2, 0, 1))
    return Tensor(img)


def normalize(img, mean, std, data_format='CHW', to_rgb=False):
    arr = _np_img(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if isinstance(img, Tensor) or arr.ndim == 3:
        if data_format == 'CHW':
            mean = mean.reshape(-1, 1, 1)
            std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


_INTERP = {'nearest': 'nearest', 'bilinear': 'linear', 'linear': 'linear',
           'bicubic': 'cubic', 'cubic': 'cubic', 'lanczos': 'lanczos3',
           'area': 'linear', 'box': 'linear'}


def _interp_resize(img, size, interpolation='bilinear'):
    """Resize of an HWC numpy image via jax.image (method honored)."""
    import jax
    import jax.numpy as jnp
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    out_shape = (oh, ow) + img.shape[2:]
    out = jax.image.resize(jnp.asarray(img.astype(np.float32)), out_shape,
                           method=_INTERP.get(interpolation, 'linear'))
    res = np.asarray(out)
    if img.dtype == np.uint8:
        res = np.clip(res, 0, 255).astype(np.uint8)
    return res


def resize(img, size, interpolation='bilinear'):
    arr = _np_img(img)
    return _interp_resize(arr, size, interpolation)


def crop(img, top, left, height, width):
    arr = _np_img(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _np_img(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _np_img(img)[:, ::-1]


def vflip(img):
    return _np_img(img)[::-1]


def pad(img, padding, fill=0, padding_mode='constant'):
    arr = _np_img(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    pads = [(pt, pb), (pl, pr)] + [(0, 0)] * (arr.ndim - 2)
    mode = {'constant': 'constant', 'edge': 'edge', 'reflect': 'reflect',
            'symmetric': 'symmetric'}[padding_mode]
    if mode == 'constant':
        return np.pad(arr, pads, mode=mode, constant_values=fill)
    return np.pad(arr, pads, mode=mode)


def rotate(img, angle, interpolation='nearest', expand=False, center=None,
           fill=0):
    arr = _np_img(img)
    k = int(round(angle / 90.0)) % 4
    exact90 = abs(angle - 90 * round(angle / 90.0)) < 1e-6
    # rot90 shortcut changes the canvas shape, which is only correct
    # when expanding (or the image is square and the shapes coincide)
    if exact90 and center is None and \
            (expand or k % 2 == 0 or arr.shape[0] == arr.shape[1]):
        return np.rot90(arr, k).copy()
    h, w = arr.shape[:2]
    theta = np.deg2rad(angle)
    if expand:
        # reference (PIL) expand=True: output canvas is the rotated
        # bounding box; rotation is about the image center (center arg
        # only shifts the pivot for expand=False, matching PIL)
        oh = int(abs(h * np.cos(theta)) + abs(w * np.sin(theta)) + 0.5)
        ow = int(abs(h * np.sin(theta)) + abs(w * np.cos(theta)) + 0.5)
        cy_in, cx_in = (h - 1) / 2.0, (w - 1) / 2.0
        cy_out, cx_out = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow = h, w
        cy_in, cx_in = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
            else (center[1], center[0])
        cy_out, cx_out = cy_in, cx_in
    # inverse-map nearest sampling: output pixel -> source pixel
    yy, xx = np.mgrid[0:oh, 0:ow].astype(np.float32)
    ys = (yy - cy_out) * np.cos(theta) - (xx - cx_out) * np.sin(theta) \
        + cy_in
    xs = (yy - cy_out) * np.sin(theta) + (xx - cx_out) * np.cos(theta) \
        + cx_in
    yi = np.clip(np.round(ys).astype(np.int64), 0, h - 1)
    xi = np.clip(np.round(xs).astype(np.int64), 0, w - 1)
    out = arr[yi, xi]
    outside = (ys < 0) | (ys > h - 1) | (xs < 0) | (xs > w - 1)
    out[outside] = fill
    return out


def adjust_brightness(img, brightness_factor):
    arr = _np_img(img).astype(np.float32)
    out = arr * brightness_factor
    return _clip_like(out, img)


def adjust_contrast(img, contrast_factor):
    arr = _np_img(img).astype(np.float32)
    mean = arr.mean()
    out = (arr - mean) * contrast_factor + mean
    return _clip_like(out, img)


def adjust_saturation(img, saturation_factor):
    arr = _np_img(img).astype(np.float32)
    gray = arr.mean(axis=-1, keepdims=True)
    out = (arr - gray) * saturation_factor + gray
    return _clip_like(out, img)


def adjust_hue(img, hue_factor):
    arr = _np_img(img).astype(np.float32) / 255.0
    # RGB->HSV hue rotation
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = arr[..., :3].max(-1)
    minc = arr[..., :3].min(-1)
    v = maxc
    delta = maxc - minc + 1e-8
    s = delta / (maxc + 1e-8)
    rc = (maxc - r) / delta
    gc = (maxc - g) / delta
    bc = (maxc - b) / delta
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    conds = [i == k for k in range(6)]
    r2 = np.select(conds, [v, q, p, p, t, v])
    g2 = np.select(conds, [t, v, v, q, p, p])
    b2 = np.select(conds, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1) * 255.0
    return _clip_like(out, img)


def to_grayscale(img, num_output_channels=1):
    arr = _np_img(img).astype(np.float32)
    gray = (0.2989 * arr[..., 0] + 0.587 * arr[..., 1] + 0.114 * arr[..., 2])
    out = np.stack([gray] * num_output_channels, axis=-1)
    return _clip_like(out, img)


def _clip_like(out, ref):
    arr = _np_img(ref)
    if arr.dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(arr.dtype)
