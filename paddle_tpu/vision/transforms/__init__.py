from .transforms import *  # noqa: F401,F403
from . import functional  # noqa: F401

from .functional import (to_tensor, normalize, resize, crop,  # noqa: F401
                         center_crop, hflip, vflip, pad, rotate,
                         adjust_brightness, adjust_contrast,
                         adjust_saturation, adjust_hue, to_grayscale)
