from .transforms import *  # noqa: F401,F403
from . import functional  # noqa: F401
