"""PP-YOLOv2-family detector (BASELINE config 4; reference: the PP-YOLOv2
model served through AnalysisPredictor — backbone + FPN-style neck + YOLOv3
heads + yolo_box decode + matrix_nms, the op pipeline of
operators/detection/{yolo_box_op.cc, matrix_nms_op.cc}).

Scaled-down but structurally faithful: CSP-style residual backbone with 3
feature levels, top-down neck, per-level heads, and a jittable static-shape
post-process (decode + matrix NMS with padded outputs + rois_num).
"""
import numpy as np

from ... import nn
from ...nn import functional as F
from ...tensor import manipulation as M
from .. import ops as vops
from .. import detection as det

__all__ = ['PPYOLOv2', 'ppyolov2']


class ConvBNLayer(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=k // 2, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)

    def forward(self, x):
        return F.mish(self.bn(self.conv(x)))


class CSPBlock(nn.Layer):
    """Cross-stage-partial residual stage (CSPResNet flavor)."""

    def __init__(self, cin, cout, n=1, downsample=True):
        super().__init__()
        self.down = ConvBNLayer(cin, cout, 3, stride=2 if downsample else 1)
        self.split1 = ConvBNLayer(cout, cout // 2, 1)
        self.split2 = ConvBNLayer(cout, cout // 2, 1)
        self.blocks = nn.LayerList([
            ConvBNLayer(cout // 2, cout // 2, 3) for _ in range(n)])
        self.merge = ConvBNLayer(cout, cout, 1)

    def forward(self, x):
        x = self.down(x)
        a = self.split1(x)
        b = self.split2(x)
        for blk in self.blocks:
            b = b + blk(b)
        return self.merge(M.concat([a, b], axis=1))


class YOLOHead(nn.Layer):
    def __init__(self, cin, num_anchors, num_classes):
        super().__init__()
        self.tip = ConvBNLayer(cin, cin * 2, 3)
        self.pred = nn.Conv2D(cin * 2, num_anchors * (5 + num_classes), 1)

    def forward(self, x):
        return self.pred(self.tip(x))


class PPYOLOv2(nn.Layer):
    """Forward returns the per-level raw head maps (training mode) or
    decoded (boxes, scores) ready for NMS (set `self.eval()`)."""

    ANCHORS = [[10, 13, 16, 30, 33, 23],
               [30, 61, 62, 45, 59, 119],
               [116, 90, 156, 198, 373, 326]]
    DOWNSAMPLES = [8, 16, 32]

    def __init__(self, num_classes=80, width=32, img_size=320):
        super().__init__()
        self.num_classes = num_classes
        self.img_size = img_size
        w = width
        self.stem = ConvBNLayer(3, w, 3)
        self.c2 = CSPBlock(w, w * 2, n=1)        # /2
        self.c3 = CSPBlock(w * 2, w * 4, n=2)    # /4
        self.c4 = CSPBlock(w * 4, w * 8, n=2)    # /8  -> P3
        self.c5 = CSPBlock(w * 8, w * 16, n=2)   # /16 -> P4
        self.c6 = CSPBlock(w * 16, w * 16, n=1)  # /32 -> P5
        # top-down neck (PAN-lite)
        self.lat5 = ConvBNLayer(w * 16, w * 8, 1)
        self.lat4 = ConvBNLayer(w * 16 + w * 8, w * 4, 1)
        self.lat3 = ConvBNLayer(w * 8 + w * 4, w * 2, 1)
        self.head3 = YOLOHead(w * 2, 3, num_classes)
        self.head4 = YOLOHead(w * 4, 3, num_classes)
        self.head5 = YOLOHead(w * 8, 3, num_classes)

    def backbone_neck(self, x):
        x = self.stem(x)
        x = self.c2(x)
        x = self.c3(x)
        p3 = self.c4(x)
        p4 = self.c5(p3)
        p5 = self.c6(p4)
        f5 = self.lat5(p5)
        up5 = F.interpolate(f5, scale_factor=2, mode='nearest')
        f4 = self.lat4(M.concat([p4, up5], axis=1))
        up4 = F.interpolate(f4, scale_factor=2, mode='nearest')
        f3 = self.lat3(M.concat([p3, up4], axis=1))
        return f3, f4, f5

    def forward(self, x):
        f3, f4, f5 = self.backbone_neck(x)
        outs = [self.head3(f3), self.head4(f4), self.head5(f5)]
        if self.training:
            return outs
        return self.decode(outs, x.shape[0])

    def decode(self, outs, batch):
        """yolo_box per level -> concatenated (boxes [B,M,4],
        scores [B,C,M])."""
        import jax.numpy as jnp
        from ...framework.core import Tensor
        img = Tensor(jnp.broadcast_to(
            jnp.asarray([self.img_size, self.img_size], jnp.int32),
            (batch, 2)))
        all_boxes, all_scores = [], []
        for out, anchors, ds in zip(outs, self.ANCHORS, self.DOWNSAMPLES):
            boxes, scores = vops.yolo_box(
                out, img, anchors=anchors, class_num=self.num_classes,
                conf_thresh=0.005, downsample_ratio=ds)
            all_boxes.append(boxes)                       # [B, m, 4]
            all_scores.append(M.transpose(scores, [0, 2, 1]))  # [B, C, m]
        return (M.concat(all_boxes, axis=1),
                M.concat(all_scores, axis=2))

    def postprocess(self, boxes, scores, score_threshold=0.01,
                    post_threshold=0.01, keep_top_k=100):
        """matrix_nms over decoded boxes (the PP-YOLOv2 configuration).
        Returns (out [B*K, 6] padded, rois_num [B])."""
        return det.matrix_nms(
            boxes, scores, score_threshold=score_threshold,
            post_threshold=post_threshold, nms_top_k=400,
            keep_top_k=keep_top_k, use_gaussian=True,
            background_label=-1)


def ppyolov2(num_classes=80, **kwargs):
    return PPYOLOv2(num_classes=num_classes, **kwargs)
