"""Shared model-factory helpers."""


def _no_pretrained(arch, pretrained):
    if pretrained:
        raise ValueError(
            '%s: pretrained=True is not available in this environment '
            '(no weight download); construct the model and load a local '
            'checkpoint via set_state_dict(paddle.load(path)) instead'
            % arch)
