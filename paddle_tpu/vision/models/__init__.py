from .lenet import LeNet  # noqa: F401
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,  # noqa: F401
                     resnet152, wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import (MobileNetV1, MobileNetV2, mobilenet_v1,  # noqa: F401
                        mobilenet_v2)
from .yolo import PPYOLOv2, ppyolov2  # noqa: F401
