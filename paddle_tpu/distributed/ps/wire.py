"""PS wire codec (reference: distributed/service/sendrecv.proto — the
brpc+protobuf frames). A closed, typed binary format replaces pickle on
the socket path: unpickling attacker bytes is code execution by design,
and a cross-host parameter server must not offer that. Only these types
exist on the wire: None, bool, int, float, str, bytes, ndarray,
list/tuple, dict — decode can NEVER instantiate arbitrary objects.

Arrays ship as dtype + shape + raw buffer (zero-copy out of numpy), which
is also faster than pickling for the pull/push payloads that dominate.
"""
import struct

import numpy as np

__all__ = ['encode', 'decode']

_ALLOWED_DTYPES = {'float32', 'float64', 'float16', 'int8', 'int16',
                   'int32', 'int64', 'uint8', 'uint32', 'uint64', 'bool'}


def _enc(obj, out):
    if obj is None:
        out.append(b'N')
    elif obj is True:
        out.append(b'T')
    elif obj is False:
        out.append(b'F')
    elif isinstance(obj, int):
        out.append(b'i' + struct.pack('>q', obj))
    elif isinstance(obj, float):
        out.append(b'f' + struct.pack('>d', obj))
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(b's' + struct.pack('>I', len(b)) + b)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(b'b' + struct.pack('>I', len(obj)) + bytes(obj))
    elif isinstance(obj, np.ndarray):
        dt = str(obj.dtype)
        if dt not in _ALLOWED_DTYPES:
            raise TypeError('dtype %s not allowed on the PS wire' % dt)
        a = np.ascontiguousarray(obj)
        dtb = dt.encode()
        out.append(b'a' + bytes([len(dtb)]) + dtb +
                   bytes([a.ndim]) + struct.pack('>%dq' % a.ndim, *a.shape))
        out.append(a.tobytes())
    elif isinstance(obj, np.generic):
        _enc(obj.item(), out)
    elif isinstance(obj, (list, tuple)):
        tag = b'l' if isinstance(obj, list) else b't'
        out.append(tag + struct.pack('>I', len(obj)))
        for v in obj:
            _enc(v, out)
    elif isinstance(obj, dict):
        out.append(b'd' + struct.pack('>I', len(obj)))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise TypeError('PS wire dict keys must be str, got %r' % k)
            _enc(k, out)
            _enc(v, out)
    else:
        raise TypeError('type %s not allowed on the PS wire' % type(obj))


def encode(obj):
    out = []
    _enc(obj, out)
    return b''.join(out)


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def take(self, n):
        if self.pos + n > len(self.buf):
            raise ValueError('truncated PS wire message')
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b


def _dec(r):
    tag = r.take(1)
    if tag == b'N':
        return None
    if tag == b'T':
        return True
    if tag == b'F':
        return False
    if tag == b'i':
        return struct.unpack('>q', r.take(8))[0]
    if tag == b'f':
        return struct.unpack('>d', r.take(8))[0]
    if tag == b's':
        n = struct.unpack('>I', r.take(4))[0]
        return r.take(n).decode()
    if tag == b'b':
        n = struct.unpack('>I', r.take(4))[0]
        return bytes(r.take(n))
    if tag == b'a':
        dtn = r.take(1)[0]
        dt = r.take(dtn).decode()
        if dt not in _ALLOWED_DTYPES:
            raise ValueError('dtype %s not allowed on the PS wire' % dt)
        ndim = r.take(1)[0]
        shape = struct.unpack('>%dq' % ndim, r.take(8 * ndim)) if ndim \
            else ()
        count = 1
        for s in shape:
            if s < 0:
                raise ValueError('negative dim on the PS wire')
            count *= s
        raw = r.take(count * np.dtype(dt).itemsize)
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
    if tag in (b'l', b't'):
        n = struct.unpack('>I', r.take(4))[0]
        items = [_dec(r) for _ in range(n)]
        return items if tag == b'l' else tuple(items)
    if tag == b'd':
        n = struct.unpack('>I', r.take(4))[0]
        out = {}
        for _ in range(n):
            k = _dec(r)
            if not isinstance(k, str):
                raise ValueError('non-str dict key on the PS wire')
            out[k] = _dec(r)
        return out
    raise ValueError('unknown PS wire tag %r' % tag)


def decode(buf):
    r = _Reader(buf)
    obj = _dec(r)
    if r.pos != len(buf):
        raise ValueError('trailing bytes in PS wire message')
    return obj
