"""Heterogeneous embedding: host-resident table + device-resident dense
half (reference: framework/fleet/heter_ps/heter_comm.h:50,
ps_gpu_wrapper.h:50, trainer.h:180 HeterXpuTrainer — the CPU<->accelerator
split that backs the "100 billion features" capability).

TPU-native shape: the table lives in the embedding service (host RAM,
optionally SSD-tiered via tables.SsdSparseTable) and NEVER enters the XLA
program; the jitted step exchanges only the batch's rows per step:

  forward : jax.pure_callback pulls rows for the ids        (host -> TPU)
  backward: io_callback pushes the rows' gradients back     (TPU -> host)

so device memory is O(batch x dim) regardless of table size — the same
activations/grads-over-the-wire contract as the reference's HeterWorker,
with XLA's host-callback machinery instead of a brpc channel. The server
applies its optimizer to pushed grads, so the layer exposes no trainable
row Parameters to the device optimizer — only a scalar `push_token`
Parameter that anchors the layer into the backward pass (ids are
integers; without a float input on the grad path, reverse-mode AD would
never traverse the lookup and the push would not fire).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, Parameter, run_op
from ... import nn

__all__ = ['HeterEmbedding', 'PassCachedEmbedding']


class HeterEmbedding(nn.Layer):
    """Embedding lookup whose table lives host-side in the embedding
    service. Drop-in for nn.Embedding in jitted training steps."""

    def __init__(self, client, table_id, embedding_dim, communicator=None,
                 name=None):
        super().__init__()
        self.client = client
        self.table_id = int(table_id)
        self.dim = int(embedding_dim)
        self.comm = communicator
        from ...nn import initializer as init_mod
        self.push_token = self.create_parameter(
            shape=[1], default_initializer=init_mod.Constant(0.0))

    def _pull_host(self, idsf):
        ids = np.asarray(idsf).view(np.int32).reshape(-1).astype(np.int64)
        rows = self.client.pull(self.table_id, ids)
        return rows.astype(np.float32)

    def _push_host(self, idsf, grads):
        ids = np.asarray(idsf).view(np.int32).reshape(-1).astype(np.int64)
        grads = np.asarray(grads).reshape(len(ids), self.dim)
        if self.comm is not None:
            self.comm.push_sparse_grad(self.table_id, ids, grads)
        else:
            self.client.push(self.table_id, ids, grads)

    def forward(self, ids):
        dim = self.dim
        pull = self._pull_host
        push = self._push_host
        try:
            from jax.experimental import io_callback
        except ImportError:  # older layouts
            from jax.experimental.io_callback import io_callback

        @jax.custom_vjp
        def lookup(idsf, token):
            flat_n = int(np.prod(idsf.shape))
            out = jax.pure_callback(
                pull,
                jax.ShapeDtypeStruct((flat_n, dim), jnp.float32),
                idsf)
            return out.reshape(idsf.shape + (dim,))

        def fwd(idsf, token):
            return lookup(idsf, token), idsf

        def bwd(idsf, g):
            io_callback(push, None, idsf, g.astype(jnp.float32),
                        ordered=True)
            return (jnp.zeros(idsf.shape, jnp.float32),
                    jnp.zeros((1,), jnp.float32))

        lookup.defvjp(fwd, bwd)

        ids_t = ids if isinstance(ids, Tensor) else Tensor(ids)
        # ids ride BITCAST to float32 (exact — a value cast would corrupt
        # ids >= 2^24) so the custom bwd's cotangent types line up; the
        # host side views the bits back as int32. In-process ids are
        # int32 anyway (jax x64 disabled); the service keys are int64.
        idsf = Tensor(jax.lax.bitcast_convert_type(
            ids_t._data.astype(jnp.int32), jnp.float32))
        return run_op('heter_embedding', lookup, idsf, self.push_token)

class PassCachedEmbedding(nn.Layer):
    """PSGPU/HeterPs analog (reference: framework/fleet/ps_gpu_wrapper.h:50
    BuildPull/EndPass, heter_ps/heter_comm.h:50): per training PASS, the
    pass's unique ids' rows are pulled ONCE into an HBM-resident table that
    trains at device speed as an ordinary Parameter (the device optimizer
    updates it inside the jitted step — the on-accelerator optimizer of
    heter_ps/optimizer.cuh.h); end_pass() pushes the accumulated deltas
    back to the host service. Data feeding remaps global ids to pass-local
    slots host-side (lookup_slots), mirroring the reference's pass build
    converting keys to local indices.

    Use when the working set per pass fits HBM but the full table does not
    — the complement of HeterEmbedding's per-step exchange."""

    def __init__(self, client, table_id, embedding_dim, name=None):
        super().__init__()
        self.client = client
        self.table_id = int(table_id)
        self.dim = int(embedding_dim)
        self.table = None          # device Parameter during a pass
        self._ids = None
        self._slot_of = None
        self._base = None

    def begin_pass(self, ids):
        """Pull the pass working set into HBM."""
        ids = np.unique(np.asarray(ids).reshape(-1).astype(np.int64))
        rows = self.client.pull(self.table_id, ids)
        self._ids = ids
        self._slot_of = {int(i): s for s, i in enumerate(ids)}
        self._base = rows.copy()
        self.table = Parameter(rows.astype(np.float32))
        # re-register so named_parameters picks the fresh table up
        self._parameters['table'] = self.table
        return len(ids)

    def lookup_slots(self, ids):
        """Global ids -> pass-local slot ids (host-side feed remap)."""
        ids = np.asarray(ids)
        flat = ids.reshape(-1)
        out = np.empty(flat.shape, np.int32)
        for i, v in enumerate(flat):
            try:
                out[i] = self._slot_of[int(v)]
            except KeyError:
                raise KeyError('id %d not in the current pass working set '
                               '(call begin_pass with every id the pass '
                               'will touch)' % int(v))
        return out.reshape(ids.shape)

    def forward(self, slot_ids):
        """slot_ids from lookup_slots -> rows [..., dim]."""
        if self.table is None:
            raise RuntimeError('begin_pass() before training')
        t = slot_ids if isinstance(slot_ids, Tensor) else Tensor(slot_ids)

        def fn(table, s):
            return table[s]
        return run_op('pass_cached_embedding', fn, self.table, t)

    def end_pass(self):
        """Push the pass's training deltas back to the host table."""
        if self.table is None:
            return 0
        new = np.asarray(self.table.numpy(), np.float32)
        delta = new - self._base
        touched = np.abs(delta).sum(axis=1) > 0
        if touched.any():
            self.client.push_delta(self.table_id, self._ids[touched],
                                   delta[touched])
        n = int(touched.sum())
        self.table = None
        self._parameters.pop('table', None)
        self._ids = self._slot_of = self._base = None
        return n
