"""Host-side sharded embedding service (reference:
distributed/table/common_sparse_table.cc + service/brpc_ps_*.cc +
framework/fleet/fleet_wrapper.h pull/push).

Capability: 100B-feature sparse embeddings that cannot live in HBM. Design
(SURVEY.md §7.1 PS row): key-sharded hash tables on host(s); workers
pull rows for the batch's unique ids, device computes dense grads, workers
push grads back and the server applies the optimizer server-side (same
division of labor as the reference's DownpourWorker + CommonSparseTable).

Transport: in-process for single-host; TCP socket protocol with a typed
binary codec (wire.py — no pickle on the socket, closed type set) for
multi-host — brpc+protobuf's role, without the dependency. Server-side optimizer
appliers mirror table/depends/sparse.h (sgd/adagrad/adam).
"""
import os
import socketserver
import struct
import threading

import numpy as np

from ...monitor import default_registry as _monitor_registry
from ...monitor import tracing as _tracing
from ..resilience import Deadline, ResilientChannel, call_once

__all__ = ['EmbeddingTable', 'EmbeddingServer', 'EmbeddingClient',
           'CountFilterEntry', 'ProbabilityEntry']

# per-op RPC counters (label set is the closed op vocabulary — bounded
# cardinality; see docs/observability.md). Registered through the
# single-source schema table (monitor/telemetry.py CLIENT_OP_FAMILIES)
# so the committed metrics baseline and this module cannot drift.
from ...monitor.telemetry import record_client_op_schema \
    as _record_client_op_schema

_CLIENT_FAMS = _record_client_op_schema(_monitor_registry())
_M_PS_CALLS = _CLIENT_FAMS['ps_client_calls_total']
_M_PS_ERRORS = _CLIENT_FAMS['ps_client_call_errors_total']

# Retry semantics of every op _Handler dispatches, declared where the
# server registers them and enforced against client send sites by
# graftlint's idempotency checker (tools/graftlint). Vocabulary:
# idempotent (safe to resend), accumulating (grad-style accumulation —
# clients must send idempotent=False), conditional (depends on the
# payload — clients must compute the kwarg), non_idempotent (never
# blind-resent).
OP_SEMANTICS = {
    'pull': 'idempotent',            # pure read
    'push': 'accumulating',          # optimizer apply accumulates
    'push_delta': 'accumulating',    # delta merge accumulates
    'pull_dense': 'idempotent',      # pure read
    'push_dense': 'accumulating',    # grad apply accumulates
    'set_dense': 'idempotent',       # last-writer set of the same value
    'barrier': 'non_idempotent',     # a resend double-arrives a worker
    'tensor': 'conditional',         # set/get resend safely; increment not
    'save': 'idempotent',            # rewrites the same shard file
    'load': 'idempotent',            # reloads the same shard file
    'stop': 'non_idempotent',        # second delivery hits a dead server
}


class _SparseOptimizer:
    """Server-side appliers (reference: table/depends/sparse.h)."""

    def __init__(self, name='sgd', lr=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        self.name = name
        self.lr = lr
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def slot_count(self):
        return {'sgd': 0, 'adagrad': 1, 'adam': 2}[self.name]

    def apply(self, rows, slots, grads):
        if self.name == 'sgd':
            rows -= self.lr * grads
            return rows, slots
        if self.name == 'adagrad':
            g2 = slots[0] + grads * grads
            rows -= self.lr * grads / (np.sqrt(g2) + self.epsilon)
            return rows, [g2]
        m = self.beta1 * slots[0] + (1 - self.beta1) * grads
        v = self.beta2 * slots[1] + (1 - self.beta2) * grads * grads
        rows -= self.lr * m / (np.sqrt(v) + self.epsilon)
        return rows, [m, v]


class CountFilterEntry:
    """Feature admission: materialize a row only after its id was seen
    `count` times (reference distributed/common/ entry_attr count_filter —
    keeps one-off ids from bloating 100B-feature tables)."""

    tracks_count = True

    def __init__(self, count=1):
        if count < 1:
            raise ValueError('count must be >= 1')
        self.count = int(count)

    def accept(self, seen_count, rng):
        return seen_count >= self.count


class ProbabilityEntry:
    """Feature admission with probability p (entry_attr probability) —
    memoryless, so no per-id sighting state is kept."""

    tracks_count = False

    def __init__(self, probability=1.0):
        if not 0.0 < probability <= 1.0:
            raise ValueError('probability must be in (0, 1]')
        self.probability = float(probability)

    def accept(self, seen_count, rng):
        return rng.rand() < self.probability


class EmbeddingTable:
    """One shard: id -> row. On-demand init (common_sparse_table semantics)
    with optional entry-admission policy; thread-safe; save/load to
    directory of npz chunks."""

    def __init__(self, dim, initializer='uniform', init_scale=0.01,
                 optimizer='sgd', lr=0.01, seed=0, entry=None):
        self.dim = dim
        self._rows = {}
        self._slots = {}
        self._lock = threading.Lock()
        self._rng = np.random.RandomState(seed)
        self._init_scale = init_scale
        self._initializer = initializer
        self._opt = _SparseOptimizer(optimizer, lr)
        self._entry = entry
        self._seen = {}

    def _new_row(self):
        if self._initializer == 'zeros':
            return np.zeros(self.dim, np.float32)
        return self._rng.uniform(-self._init_scale, self._init_scale,
                                 self.dim).astype(np.float32)

    def pull(self, ids):
        out = np.empty((len(ids), self.dim), np.float32)
        with self._lock:
            for i, key in enumerate(ids):
                row = self._rows.get(key)
                if row is None:
                    if self._entry is not None:
                        seen = 1
                        if getattr(self._entry, 'tracks_count', False):
                            seen = self._seen.get(key, 0) + 1
                        if not self._entry.accept(seen, self._rng):
                            # not admitted yet: serve zeros; count-based
                            # policies remember the sighting, memoryless
                            # ones keep nothing
                            if getattr(self._entry, 'tracks_count', False):
                                self._seen[key] = seen
                            out[i] = 0.0
                            continue
                        self._seen.pop(key, None)
                    row = self._new_row()
                    self._rows[key] = row
                    nslots = self._opt.slot_count()
                    if nslots:
                        self._slots[key] = [np.zeros(self.dim, np.float32)
                                            for _ in range(nslots)]
                out[i] = row
        return out

    def push(self, ids, grads):
        with self._lock:
            for key, g in zip(ids, grads):
                row = self._rows.get(key)
                if row is None:
                    continue
                slots = self._slots.get(key, [])
                new_row, new_slots = self._opt.apply(row.copy(), list(slots), g)
                self._rows[key] = new_row
                if new_slots:
                    self._slots[key] = new_slots

    def push_delta(self, ids, deltas):
        """Apply raw parameter deltas (geo-SGD sends / PSGPU end-pass
        flush): rows += delta, bypassing the server optimizer."""
        with self._lock:
            for key, d in zip(ids, deltas):
                row = self._rows.get(key)
                if row is None:
                    continue
                self._rows[key] = row + np.asarray(d, row.dtype)

    def __len__(self):
        return len(self._rows)

    def save(self, path):
        os.makedirs(path, exist_ok=True)
        with self._lock:
            keys = np.asarray(list(self._rows.keys()), np.int64)
            vals = np.stack(list(self._rows.values())) if self._rows else \
                np.zeros((0, self.dim), np.float32)
            seen_keys = np.asarray(list(self._seen.keys()), np.int64)
            seen_vals = np.asarray(list(self._seen.values()), np.int64)
        np.savez(os.path.join(path, 'shard.npz'), keys=keys, vals=vals,
                 seen_keys=seen_keys, seen_vals=seen_vals)

    def load(self, path):
        data = np.load(os.path.join(path, 'shard.npz'))
        with self._lock:
            self._rows = {int(k): v for k, v in zip(data['keys'],
                                                    data['vals'])}
            if 'seen_keys' in data:
                self._seen = {int(k): int(v) for k, v in
                              zip(data['seen_keys'], data['seen_vals'])}

    def shrink(self, threshold=0):
        pass


# -- socket RPC (multi-host path) ------------------------------------------

def _send_msg(sock, obj):
    # typed wire codec, NOT pickle: unpickling peer bytes would be
    # remote code execution by design (see wire.py)
    from . import wire
    payload = wire.encode(obj)
    sock.sendall(struct.pack('>Q', len(payload)) + payload)


def _recv_msg(sock):
    hdr = b''
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError('peer closed')
        hdr += chunk
    n = struct.unpack('>Q', hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError('peer closed')
        buf.extend(chunk)
    from . import wire
    return wire.decode(bytes(buf))


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        # registry lets chaos.kill_server sever established connections,
        # not just the listener — a killed pod drops both
        self.server.live_connections.add(self.request)

    def finish(self):
        self.server.live_connections.discard(self.request)

    def handle(self):
        server = self.server.embedding_server
        while True:
            try:
                msg = _recv_msg(self.request)
            except (ConnectionError, OSError):
                return
            # continues the client's rpc.attempt span when the message
            # carries trace context; always strips the metadata key
            span = _tracing.default_tracer().server_span(msg, 'ps.server')
            try:
                op = msg['op']
                if op == 'pull':
                    out = server.table(msg['table']).pull(msg['ids'])
                    _send_msg(self.request, out)
                elif op == 'push':
                    server.table(msg['table']).push(msg['ids'],
                                                    msg['grads'])
                    _send_msg(self.request, b'ok')
                elif op == 'push_delta':
                    server.table(msg['table']).push_delta(msg['ids'],
                                                          msg['deltas'])
                    _send_msg(self.request, b'ok')
                elif op == 'pull_dense':
                    _send_msg(self.request,
                              server.table(msg['table']).pull())
                elif op == 'push_dense':
                    server.table(msg['table']).push(msg['grad'])
                    _send_msg(self.request, b'ok')
                elif op == 'set_dense':
                    server.table(msg['table']).set(msg['value'])
                    _send_msg(self.request, b'ok')
                elif op == 'barrier':
                    server.table(msg['table']).barrier(
                        msg.get('worker_id'), msg.get('timeout', 60.0))
                    _send_msg(self.request, b'ok')
                elif op == 'tensor':
                    if msg['method'] not in ('set', 'get', 'increment'):
                        raise ValueError('bad tensor method %r'
                                         % msg['method'])
                    tt = server.table(msg['table'])
                    method = getattr(tt, msg['method'])
                    _send_msg(self.request, method(*msg.get('args', ())))
                elif op == 'save':
                    server.table(msg['table']).save(msg['path'])
                    _send_msg(self.request, b'ok')
                elif op == 'load':
                    server.table(msg['table']).load(msg['path'])
                    _send_msg(self.request, b'ok')
                elif op == 'stop':
                    _send_msg(self.request, b'ok')
                    self.server.shutdown()
                    return
                else:
                    _send_msg(self.request, {'error': 'unknown op %r' % op})
            except Exception as e:  # report instead of killing the server
                span.set_error(e)
                try:
                    _send_msg(self.request, {'error': repr(e)})
                except OSError:
                    return
            finally:
                span.finish()


class _PsTCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    # restart-on-the-same-endpoint is the recovery path: rebinding right
    # after a kill must not wait out TIME_WAIT
    allow_reuse_address = True


class EmbeddingServer:
    """One PS shard process (BrpcPsServer parity, socket transport)."""

    def __init__(self, host='127.0.0.1', port=0):
        self._tables = {}
        self._srv = _PsTCPServer((host, port), _Handler,
                                 bind_and_activate=True)
        self._srv.embedding_server = self
        self._srv.live_connections = set()
        self.port = self._srv.server_address[1]
        self.endpoint = '%s:%d' % (host, self.port)
        self._thread = None

    def create_table(self, table_id, dim, table_class=None, backend=None,
                     **kwargs):
        if backend == 'native':
            if table_class is not None:
                raise ValueError('pass either table_class or '
                                 "backend='native', not both")
            from ...native.embedding_table import NativeEmbeddingTable
            cls = NativeEmbeddingTable
        else:
            cls = table_class or EmbeddingTable
        self._tables[table_id] = cls(dim, **kwargs)
        return self._tables[table_id]

    def create_dense_table(self, table_id, shape, **kwargs):
        from .tables import DenseTable
        self._tables[table_id] = DenseTable(shape, **kwargs)
        return self._tables[table_id]

    def create_barrier_table(self, table_id, trigger_count):
        from .tables import BarrierTable
        self._tables[table_id] = BarrierTable(trigger_count)
        return self._tables[table_id]

    def create_tensor_table(self, table_id):
        from .tables import TensorTable
        self._tables[table_id] = TensorTable()
        return self._tables[table_id]

    def table(self, table_id):
        return self._tables[table_id]

    def start(self, block=False):
        if block:
            self._srv.serve_forever()
        else:
            self._thread = threading.Thread(target=self._srv.serve_forever,
                                            daemon=True)
            self._thread.start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    # ---- fleet telemetry ------------------------------------------

    def metrics_server(self, **kwargs):
        """A MetricsServer over this process's registry — start it in a
        PS shard process and add `.url` to a FleetCollector as an HTTP
        target; the shard's ps_server_* families then show up in the
        federated view with the shard's instance label."""
        from ...monitor.server import MetricsServer
        return MetricsServer(registry=_monitor_registry(), **kwargs)

    def fleet_register(self, collector, instance=None):
        """Register this shard on an in-process FleetCollector (same
        process, no HTTP hop). Server metrics live on the PROCESS
        registry, so register each process once — two in-proc shards
        share one registry and registering both would double-count."""
        return collector.add_target(instance or 'ps-%d' % self.port,
                                    registry=_monitor_registry())


class EmbeddingClient:
    """Key-sharded client over N servers (BrpcPsClient parity): shard by
    id % nshards, batch per-shard, parallel requests.

    Remote transport is a ResilientChannel per shard (socket timeouts,
    reconnect + retry for idempotent ops, per-endpoint circuit breaker).
    Reads (pull/pull_dense/tensor-get) and overwrites (set_dense) retry
    transparently; grad applications (push/push_delta/push_dense) are NOT
    idempotent — the server may have applied an unacked op, and resending
    would double-apply — so they run single-attempt and surface a
    RetryableError for the communicator's own error path. `op_deadline`
    (seconds) bounds each public op across all shards and retries.
    """

    def __init__(self, endpoints=None, servers=None, retry_policy=None,
                 call_timeout=None, op_deadline=None):
        self._local = servers  # in-proc mode: list of EmbeddingServer
        self._channels = None
        self._endpoints = endpoints
        self._op_deadline = op_deadline
        if endpoints and not servers:
            kw = {} if call_timeout is None else \
                {'call_timeout': call_timeout}
            self._channels = [ResilientChannel(ep,
                                               retry_policy=retry_policy,
                                               **kw)
                              for ep in endpoints]
        self._n = len(servers or endpoints)

    def _shard(self, ids):
        ids = np.asarray(ids, np.int64)
        shard_idx = ids % self._n
        return ids, shard_idx

    def _deadline(self):
        return None if self._op_deadline is None \
            else Deadline(self._op_deadline)

    def _call(self, s, msg, idempotent=True, deadline=None):
        """Remote call to server s with error propagation."""
        op = str(msg.get('op', '?'))
        _M_PS_CALLS.labels(op).inc()
        try:
            out = self._channels[s].call(msg, idempotent=idempotent,
                                         deadline=deadline)
        except Exception:
            _M_PS_ERRORS.labels(op).inc()
            raise
        if isinstance(out, dict) and 'error' in out:
            _M_PS_ERRORS.labels(op).inc()
            raise RuntimeError(out['error'])
        return out

    def _call_fresh(self, s, msg, timeout=None):
        """Blocking RPC (e.g. barrier) over a NEW ephemeral connection so
        the persistent per-server channel stays free for fast ops."""
        op = str(msg.get('op', '?'))
        _M_PS_CALLS.labels(op).inc()
        kw = {} if timeout is None else {'timeout': timeout}
        try:
            out = call_once(self._endpoints[s], msg, **kw)
        except Exception:
            _M_PS_ERRORS.labels(op).inc()
            raise
        if isinstance(out, dict) and 'error' in out:
            _M_PS_ERRORS.labels(op).inc()
            raise RuntimeError(out['error'])
        return out

    def pull(self, table_id, ids):
        ids, shard_idx = self._shard(ids)
        dl = self._deadline()
        out = np.empty((len(ids), self._dim(table_id)), np.float32)
        for s in range(self._n):
            mask = shard_idx == s
            if not mask.any():
                continue
            sub = ids[mask]
            if self._local is not None:
                rows = self._local[s].table(table_id).pull(sub.tolist())
            else:
                rows = self._call(s, {'op': 'pull', 'table': table_id,
                                      'ids': sub.tolist()}, deadline=dl)
            out[mask] = rows
        return out

    def push(self, table_id, ids, grads):
        ids, shard_idx = self._shard(ids)
        grads = np.asarray(grads, np.float32)
        dl = self._deadline()
        for s in range(self._n):
            mask = shard_idx == s
            if not mask.any():
                continue
            if self._local is not None:
                self._local[s].table(table_id).push(ids[mask].tolist(),
                                                    grads[mask])
            else:
                # grad application is not idempotent: no blind resend
                self._call(s, {'op': 'push', 'table': table_id,
                               'ids': ids[mask].tolist(),
                               'grads': grads[mask]}, idempotent=False,
                           deadline=dl)

    def _dim(self, table_id):
        if self._local is not None:
            return self._local[0].table(table_id).dim
        # remote: pull a probe row
        row = self._call(0, {'op': 'pull', 'table': table_id, 'ids': [0]})
        return row.shape[1]

    def push_delta(self, table_id, ids, deltas):
        """Geo-SGD path: add parameter deltas on the server."""
        ids, shard_idx = self._shard(ids)
        deltas = np.asarray(deltas, np.float32)
        dl = self._deadline()
        for s in range(self._n):
            mask = shard_idx == s
            if not mask.any():
                continue
            if self._local is not None:
                self._local[s].table(table_id).push_delta(
                    ids[mask].tolist(), deltas[mask])
            else:
                self._call(s, {'op': 'push_delta', 'table': table_id,
                               'ids': ids[mask].tolist(),
                               'deltas': deltas[mask]}, idempotent=False,
                           deadline=dl)

    # -- dense / barrier / tensor tables (placed by table_id % n) -----------
    def _owner(self, table_id):
        return int(table_id) % self._n

    def pull_dense(self, table_id):
        s = self._owner(table_id)
        if self._local is not None:
            return self._local[s].table(table_id).pull()
        return self._call(s, {'op': 'pull_dense', 'table': table_id},
                          deadline=self._deadline())

    def push_dense(self, table_id, grad):
        s = self._owner(table_id)
        if self._local is not None:
            return self._local[s].table(table_id).push(grad)
        # grad application is not idempotent: no blind resend
        self._call(s, {'op': 'push_dense', 'table': table_id,
                       'grad': np.asarray(grad, np.float32)},
                   idempotent=False, deadline=self._deadline())

    def set_dense(self, table_id, value):
        s = self._owner(table_id)
        if self._local is not None:
            return self._local[s].table(table_id).set(value)
        # overwrite semantics: a resend re-writes the same value
        self._call(s, {'op': 'set_dense', 'table': table_id,
                       'value': np.asarray(value, np.float32)},
                   deadline=self._deadline())

    def barrier(self, table_id, worker_id=None, timeout=60.0):
        s = self._owner(table_id)
        if self._local is not None:
            return self._local[s].table(table_id).barrier(worker_id,
                                                          timeout)
        # ephemeral connection: a blocking barrier must not pin the shared
        # per-server channel (other threads' pulls/pushes keep flowing).
        # Transport timeout = barrier timeout + slack, so a wedged server
        # surfaces as a socket timeout instead of a hang.
        self._call_fresh(s, {'op': 'barrier', 'table': table_id,
                             'worker_id': worker_id, 'timeout': timeout},
                         timeout=timeout + 10.0)

    def tensor(self, table_id, method, *args):
        s = self._owner(table_id)
        if self._local is not None:
            return getattr(self._local[s].table(table_id), method)(*args)
        # set/get re-send safely; increment would double-count
        return self._call(s, {'op': 'tensor', 'table': table_id,
                              'method': method, 'args': args},
                          idempotent=(method != 'increment'),
                          deadline=self._deadline())

    def save(self, table_id, path):
        dl = self._deadline()
        for s in range(self._n):
            p = os.path.join(path, 'shard_%d' % s)
            if self._local is not None:
                self._local[s].table(table_id).save(p)
            else:
                self._call(s, {'op': 'save', 'table': table_id, 'path': p},
                           deadline=dl)
