"""Host-side sharded embedding service (reference:
distributed/table/common_sparse_table.cc + service/brpc_ps_*.cc +
framework/fleet/fleet_wrapper.h pull/push).

Capability: 100B-feature sparse embeddings that cannot live in HBM. Design
(SURVEY.md §7.1 PS row): key-sharded hash tables on host(s); workers
pull rows for the batch's unique ids, device computes dense grads, workers
push grads back and the server applies the optimizer server-side (same
division of labor as the reference's DownpourWorker + CommonSparseTable).

Transport: in-process for single-host; TCP socket protocol with a typed
binary codec (wire.py — no pickle on the socket, closed type set) for
multi-host — brpc+protobuf's role, without the dependency. Server-side optimizer
appliers mirror table/depends/sparse.h (sgd/adagrad/adam).
"""
import os
import socketserver
import struct
import threading

import numpy as np

from ...monitor import default_registry as _monitor_registry
from ...monitor import tracing as _tracing
from ..resilience import Deadline, ResilientChannel, call_once

__all__ = ['EmbeddingTable', 'EmbeddingServer', 'EmbeddingClient',
           'CountFilterEntry', 'ProbabilityEntry']

# per-op RPC counters (label set is the closed op vocabulary — bounded
# cardinality; see docs/observability.md). Registered through the
# single-source schema table (monitor/telemetry.py CLIENT_OP_FAMILIES)
# so the committed metrics baseline and this module cannot drift.
from ...monitor.telemetry import record_client_op_schema \
    as _record_client_op_schema

_CLIENT_FAMS = _record_client_op_schema(_monitor_registry())
_M_PS_CALLS = _CLIENT_FAMS['ps_client_calls_total']
_M_PS_ERRORS = _CLIENT_FAMS['ps_client_call_errors_total']

# Retry semantics of every op _Handler dispatches, declared where the
# server registers them and enforced against client send sites by
# graftlint's idempotency checker (tools/graftlint). Vocabulary:
# idempotent (safe to resend), accumulating (grad-style accumulation —
# clients must send idempotent=False), conditional (depends on the
# payload — clients must compute the kwarg), non_idempotent (never
# blind-resent).
OP_SEMANTICS = {
    'pull': 'idempotent',            # pure read
    # the accumulating writes are conditional: journaled sends carry a
    # (client, seq) pair the server dedups on its high-water mark, so
    # they retry safely; unjournaled sends must stay single-attempt
    'push': 'conditional',           # idempotent iff journaled
    'push_delta': 'conditional',     # idempotent iff journaled
    'pull_dense': 'idempotent',      # pure read
    'push_dense': 'conditional',     # idempotent iff journaled
    'set_dense': 'idempotent',       # last-writer set of the same value
    'barrier': 'non_idempotent',     # a resend double-arrives a worker
    'tensor': 'conditional',         # set/get resend safely; increment not
    'save': 'idempotent',            # rewrites the same shard file
    'load': 'idempotent',            # reloads the same shard file
    'ping': 'idempotent',            # liveness probe, pure read
    'snapshot': 'idempotent',        # rewrites the same snapshot file
    'restore': 'idempotent',         # reloads the same snapshot file
    'stop': 'non_idempotent',        # second delivery hits a dead server
}


class _SparseOptimizer:
    """Server-side appliers (reference: table/depends/sparse.h)."""

    def __init__(self, name='sgd', lr=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        self.name = name
        self.lr = lr
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def slot_count(self):
        return {'sgd': 0, 'adagrad': 1, 'adam': 2}[self.name]

    def apply(self, rows, slots, grads):
        if self.name == 'sgd':
            rows -= self.lr * grads
            return rows, slots
        if self.name == 'adagrad':
            g2 = slots[0] + grads * grads
            rows -= self.lr * grads / (np.sqrt(g2) + self.epsilon)
            return rows, [g2]
        m = self.beta1 * slots[0] + (1 - self.beta1) * grads
        v = self.beta2 * slots[1] + (1 - self.beta2) * grads * grads
        rows -= self.lr * m / (np.sqrt(v) + self.epsilon)
        return rows, [m, v]


class CountFilterEntry:
    """Feature admission: materialize a row only after its id was seen
    `count` times (reference distributed/common/ entry_attr count_filter —
    keeps one-off ids from bloating 100B-feature tables)."""

    tracks_count = True

    def __init__(self, count=1):
        if count < 1:
            raise ValueError('count must be >= 1')
        self.count = int(count)

    def accept(self, seen_count, rng):
        return seen_count >= self.count


class ProbabilityEntry:
    """Feature admission with probability p (entry_attr probability) —
    memoryless, so no per-id sighting state is kept."""

    tracks_count = False

    def __init__(self, probability=1.0):
        if not 0.0 < probability <= 1.0:
            raise ValueError('probability must be in (0, 1]')
        self.probability = float(probability)

    def accept(self, seen_count, rng):
        return rng.rand() < self.probability


class EmbeddingTable:
    """One shard: id -> row. On-demand init (common_sparse_table semantics)
    with optional entry-admission policy; thread-safe; save/load to
    directory of npz chunks."""

    def __init__(self, dim, initializer='uniform', init_scale=0.01,
                 optimizer='sgd', lr=0.01, seed=0, entry=None):
        self.dim = dim
        self._rows = {}
        self._slots = {}
        self._lock = threading.Lock()
        self._rng = np.random.RandomState(seed)
        self._init_scale = init_scale
        self._initializer = initializer
        self._opt = _SparseOptimizer(optimizer, lr)
        self._entry = entry
        self._seen = {}

    def _new_row(self):
        if self._initializer == 'zeros':
            return np.zeros(self.dim, np.float32)
        return self._rng.uniform(-self._init_scale, self._init_scale,
                                 self.dim).astype(np.float32)

    def pull(self, ids):
        out = np.empty((len(ids), self.dim), np.float32)
        with self._lock:
            for i, key in enumerate(ids):
                row = self._rows.get(key)
                if row is None:
                    if self._entry is not None:
                        seen = 1
                        if getattr(self._entry, 'tracks_count', False):
                            seen = self._seen.get(key, 0) + 1
                        if not self._entry.accept(seen, self._rng):
                            # not admitted yet: serve zeros; count-based
                            # policies remember the sighting, memoryless
                            # ones keep nothing
                            if getattr(self._entry, 'tracks_count', False):
                                self._seen[key] = seen
                            out[i] = 0.0
                            continue
                        self._seen.pop(key, None)
                    row = self._new_row()
                    self._rows[key] = row
                    nslots = self._opt.slot_count()
                    if nslots:
                        self._slots[key] = [np.zeros(self.dim, np.float32)
                                            for _ in range(nslots)]
                out[i] = row
        return out

    def push(self, ids, grads):
        with self._lock:
            for key, g in zip(ids, grads):
                row = self._rows.get(key)
                if row is None:
                    continue
                slots = self._slots.get(key, [])
                new_row, new_slots = self._opt.apply(row.copy(), list(slots), g)
                self._rows[key] = new_row
                if new_slots:
                    self._slots[key] = new_slots

    def push_delta(self, ids, deltas):
        """Apply raw parameter deltas (geo-SGD sends / PSGPU end-pass
        flush): rows += delta, bypassing the server optimizer."""
        with self._lock:
            for key, d in zip(ids, deltas):
                row = self._rows.get(key)
                if row is None:
                    continue
                self._rows[key] = row + np.asarray(d, row.dtype)

    def __len__(self):
        return len(self._rows)

    def save(self, path):
        os.makedirs(path, exist_ok=True)
        with self._lock:
            keys = np.asarray(list(self._rows.keys()), np.int64)
            vals = np.stack(list(self._rows.values())) if self._rows else \
                np.zeros((0, self.dim), np.float32)
            seen_keys = np.asarray(list(self._seen.keys()), np.int64)
            seen_vals = np.asarray(list(self._seen.values()), np.int64)
        np.savez(os.path.join(path, 'shard.npz'), keys=keys, vals=vals,
                 seen_keys=seen_keys, seen_vals=seen_vals)

    def load(self, path):
        data = np.load(os.path.join(path, 'shard.npz'))
        with self._lock:
            self._rows = {int(k): v for k, v in zip(data['keys'],
                                                    data['vals'])}
            if 'seen_keys' in data:
                self._seen = {int(k): int(v) for k, v in
                              zip(data['seen_keys'], data['seen_vals'])}

    def shrink(self, threshold=0):
        pass

    def state_dict(self):
        """Full shard state for a supervisor snapshot: rows, optimizer
        slots, admission sightings AND the row-init RNG state — a
        restored shard must mint the same rows for ids it has not seen,
        or a resumed run diverges from the uninterrupted one. (For
        SsdSparseTable subclasses this covers the in-memory hot set.)"""
        with self._lock:
            return {
                'rows': {int(k): v.copy() for k, v in self._rows.items()},
                'slots': {int(k): [s.copy() for s in v]
                          for k, v in self._slots.items()},
                'seen': dict(self._seen),
                'rng': self._rng.get_state(),
            }

    def set_state_dict(self, state):
        with self._lock:
            self._rows = {int(k): np.asarray(v, np.float32)
                          for k, v in state['rows'].items()}
            self._slots = {int(k): [np.asarray(s, np.float32) for s in v]
                           for k, v in state['slots'].items()}
            self._seen = {int(k): int(v)
                          for k, v in state.get('seen', {}).items()}
            if state.get('rng') is not None:
                self._rng.set_state(state['rng'])


# -- socket RPC (multi-host path) ------------------------------------------

def _send_msg(sock, obj):
    # typed wire codec, NOT pickle: unpickling peer bytes would be
    # remote code execution by design (see wire.py)
    from . import wire
    payload = wire.encode(obj)
    sock.sendall(struct.pack('>Q', len(payload)) + payload)


def _recv_msg(sock):
    hdr = b''
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError('peer closed')
        hdr += chunk
    n = struct.unpack('>Q', hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError('peer closed')
        buf.extend(chunk)
    from . import wire
    return wire.decode(bytes(buf))


def _apply_table_write(server, op, msg):
    """Apply one accumulating write message to its table (shared by the
    direct dispatch path and the journaled exactly-once path)."""
    if op == 'push':
        server.table(msg['table']).push(msg['ids'], msg['grads'])
    elif op == 'push_delta':
        server.table(msg['table']).push_delta(msg['ids'], msg['deltas'])
    else:
        server.table(msg['table']).push(msg['grad'])


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        # registry lets chaos.kill_server sever established connections,
        # not just the listener — a killed pod drops both
        self.server.live_connections.add(self.request)

    def finish(self):
        self.server.live_connections.discard(self.request)

    def handle(self):
        server = self.server.embedding_server
        while True:
            try:
                msg = _recv_msg(self.request)
            except (ConnectionError, OSError):
                return
            # continues the client's rpc.attempt span when the message
            # carries trace context; always strips the metadata key
            span = _tracing.default_tracer().server_span(msg, 'ps.server')
            try:
                op = msg['op']
                if op == 'pull':
                    out = server.table(msg['table']).pull(msg['ids'])
                    _send_msg(self.request, out)
                elif op in ('push', 'push_delta', 'push_dense'):
                    cid = msg.get('client')
                    if cid is not None:
                        # journaled write: dedup on the per-client seq
                        # high-water mark so a retried/replayed push
                        # applies exactly once
                        applied = server.journal_apply(
                            cid, msg['seq'],
                            lambda: _apply_table_write(server, op, msg))
                        _send_msg(self.request,
                                  {'ok': True, 'applied': applied})
                    else:
                        _apply_table_write(server, op, msg)
                        _send_msg(self.request, b'ok')
                elif op == 'pull_dense':
                    _send_msg(self.request,
                              server.table(msg['table']).pull())
                elif op == 'set_dense':
                    server.table(msg['table']).set(msg['value'])
                    _send_msg(self.request, b'ok')
                elif op == 'barrier':
                    server.table(msg['table']).barrier(
                        msg.get('worker_id'), msg.get('timeout', 60.0))
                    _send_msg(self.request, b'ok')
                elif op == 'tensor':
                    if msg['method'] not in ('set', 'get', 'increment'):
                        raise ValueError('bad tensor method %r'
                                         % msg['method'])
                    tt = server.table(msg['table'])
                    method = getattr(tt, msg['method'])
                    _send_msg(self.request, method(*msg.get('args', ())))
                elif op == 'save':
                    server.table(msg['table']).save(msg['path'])
                    _send_msg(self.request, b'ok')
                elif op == 'load':
                    server.table(msg['table']).load(msg['path'])
                    _send_msg(self.request, b'ok')
                elif op == 'ping':
                    _send_msg(self.request, {'ok': True,
                                             'port': server.port})
                elif op == 'snapshot':
                    server.snapshot(msg['path'])
                    _send_msg(self.request, b'ok')
                elif op == 'restore':
                    server.restore(msg['path'])
                    _send_msg(self.request, b'ok')
                elif op == 'stop':
                    _send_msg(self.request, b'ok')
                    self.server.shutdown()
                    return
                else:
                    _send_msg(self.request, {'error': 'unknown op %r' % op})
            except Exception as e:  # report instead of killing the server
                span.set_error(e)
                try:
                    _send_msg(self.request, {'error': repr(e)})
                except OSError:
                    return
            finally:
                span.finish()


class _PsTCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    # restart-on-the-same-endpoint is the recovery path: rebinding right
    # after a kill must not wait out TIME_WAIT
    allow_reuse_address = True


class EmbeddingServer:
    """One PS shard process (BrpcPsServer parity, socket transport)."""

    def __init__(self, host='127.0.0.1', port=0):
        self._tables = {}
        self._srv = _PsTCPServer((host, port), _Handler,
                                 bind_and_activate=True)
        self._srv.embedding_server = self
        self._srv.live_connections = set()
        self.port = self._srv.server_address[1]
        self.endpoint = '%s:%d' % (host, self.port)
        self._thread = None
        # exactly-once dedup state: client_id -> last applied seq.
        # Included in snapshot()/restore() so a replayed journal after a
        # restore is judged against the restored state, not a blank one.
        self._journal = {}
        self._journal_lock = threading.Lock()

    def create_table(self, table_id, dim, table_class=None, backend=None,
                     **kwargs):
        if backend == 'native':
            if table_class is not None:
                raise ValueError('pass either table_class or '
                                 "backend='native', not both")
            from ...native.embedding_table import NativeEmbeddingTable
            cls = NativeEmbeddingTable
        else:
            cls = table_class or EmbeddingTable
        self._tables[table_id] = cls(dim, **kwargs)
        return self._tables[table_id]

    def create_dense_table(self, table_id, shape, **kwargs):
        from .tables import DenseTable
        self._tables[table_id] = DenseTable(shape, **kwargs)
        return self._tables[table_id]

    def create_barrier_table(self, table_id, trigger_count):
        from .tables import BarrierTable
        self._tables[table_id] = BarrierTable(trigger_count)
        return self._tables[table_id]

    def create_tensor_table(self, table_id):
        from .tables import TensorTable
        self._tables[table_id] = TensorTable()
        return self._tables[table_id]

    def table(self, table_id):
        return self._tables[table_id]

    def journal_apply(self, client_id, seq, apply_fn):
        """Apply a journaled write exactly once. The mark-and-apply runs
        under one lock so a duplicate arriving on a second connection
        (client reconnected and resent before the first handler thread
        finished) can never double-apply. Returns False on a dedup hit."""
        seq = int(seq)
        with self._journal_lock:
            if seq <= self._journal.get(client_id, -1):
                return False
            apply_fn()
            self._journal[client_id] = seq
            return True

    def state_dict(self):
        """Snapshot every table that supports it (BarrierTable holds
        only transient arrival counts and is deliberately skipped) plus
        the exactly-once journal marks. Caller holds _journal_lock."""
        tables = {}
        for tid, table in self._tables.items():
            state_fn = getattr(table, 'state_dict', None)
            if state_fn is not None:
                tables[tid] = state_fn()
        return {'tables': tables, 'journal': dict(self._journal)}

    def snapshot(self, path):
        """Write the full shard state atomically (io_save: temp + rename
        + CRC manifest). Held under the journal lock so journaled pushes
        serialize against the snapshot — the journal marks in the file
        exactly vouch for the table state next to them."""
        from ...framework import io_save
        with self._journal_lock:
            state = self.state_dict()
        io_save.save(state, path)

    def restore(self, path):
        """Load a snapshot() file into the (already created) tables."""
        from ...framework import io_save
        state = io_save.load(path)
        for tid, table_state in state['tables'].items():
            self._tables[tid].set_state_dict(table_state)
        with self._journal_lock:
            self._journal = {str(k): int(v)
                             for k, v in state['journal'].items()}

    def start(self, block=False):
        if block:
            self._srv.serve_forever()
        else:
            self._thread = threading.Thread(target=self._srv.serve_forever,
                                            daemon=True)
            self._thread.start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    # ---- fleet telemetry ------------------------------------------

    def metrics_server(self, **kwargs):
        """A MetricsServer over this process's registry — start it in a
        PS shard process and add `.url` to a FleetCollector as an HTTP
        target; the shard's ps_server_* families then show up in the
        federated view with the shard's instance label."""
        from ...monitor.server import MetricsServer
        return MetricsServer(registry=_monitor_registry(), **kwargs)

    def fleet_register(self, collector, instance=None):
        """Register this shard on an in-process FleetCollector (same
        process, no HTTP hop). Server metrics live on the PROCESS
        registry, so register each process once — two in-proc shards
        share one registry and registering both would double-count."""
        return collector.add_target(instance or 'ps-%d' % self.port,
                                    registry=_monitor_registry())


class EmbeddingClient:
    """Key-sharded client over N servers (BrpcPsClient parity): shard by
    id % nshards, batch per-shard, parallel requests.

    Remote transport is a ResilientChannel per shard (socket timeouts,
    reconnect + retry for idempotent ops, per-endpoint circuit breaker).
    Reads (pull/pull_dense/tensor-get) and overwrites (set_dense) retry
    transparently. Grad applications (push/push_delta/push_dense) are
    conditional: without a journal the server may have applied an unacked
    op and a resend would double-apply, so they run single-attempt and
    surface a RetryableError; with `journal=` (a supervisor.PushJournal)
    every write carries a (client, seq) pair the server dedups on, so
    they retry — and replay after a shard restore — exactly once.
    `op_deadline` (seconds) bounds each public op across all shards and
    retries.
    """

    def __init__(self, endpoints=None, servers=None, retry_policy=None,
                 call_timeout=None, op_deadline=None, journal=None):
        self._local = servers  # in-proc mode: list of EmbeddingServer
        self._channels = None
        self._endpoints = endpoints
        self._op_deadline = op_deadline
        self._journal = journal if servers is None else None
        if endpoints and not servers:
            kw = {} if call_timeout is None else \
                {'call_timeout': call_timeout}
            self._channels = [ResilientChannel(ep,
                                               retry_policy=retry_policy,
                                               **kw)
                              for ep in endpoints]
        self._n = len(servers or endpoints)

    def _shard(self, ids):
        ids = np.asarray(ids, np.int64)
        shard_idx = ids % self._n
        return ids, shard_idx

    def _deadline(self):
        return None if self._op_deadline is None \
            else Deadline(self._op_deadline)

    def _call(self, s, msg, idempotent=True, deadline=None):
        """Remote call to server s with error propagation."""
        op = str(msg.get('op', '?'))
        _M_PS_CALLS.labels(op).inc()
        try:
            out = self._channels[s].call(msg, idempotent=idempotent,
                                         deadline=deadline)
        except Exception:
            _M_PS_ERRORS.labels(op).inc()
            raise
        if isinstance(out, dict) and 'error' in out:
            _M_PS_ERRORS.labels(op).inc()
            raise RuntimeError(out['error'])
        return out

    def _call_fresh(self, s, msg, timeout=None):
        """Blocking RPC (e.g. barrier) over a NEW ephemeral connection so
        the persistent per-server channel stays free for fast ops."""
        op = str(msg.get('op', '?'))
        _M_PS_CALLS.labels(op).inc()
        kw = {} if timeout is None else {'timeout': timeout}
        try:
            out = call_once(self._endpoints[s], msg, **kw)
        except Exception:
            _M_PS_ERRORS.labels(op).inc()
            raise
        if isinstance(out, dict) and 'error' in out:
            _M_PS_ERRORS.labels(op).inc()
            raise RuntimeError(out['error'])
        return out

    def pull(self, table_id, ids):
        ids, shard_idx = self._shard(ids)
        dl = self._deadline()
        out = np.empty((len(ids), self._dim(table_id)), np.float32)
        for s in range(self._n):
            mask = shard_idx == s
            if not mask.any():
                continue
            sub = ids[mask]
            if self._local is not None:
                rows = self._local[s].table(table_id).pull(sub.tolist())
            else:
                rows = self._call(s, {'op': 'pull', 'table': table_id,
                                      'ids': sub.tolist()}, deadline=dl)
            out[mask] = rows
        return out

    @property
    def journal(self):
        """The PushJournal backing exactly-once sends (None when
        unjournaled) — ShardSupervisor trims it at snapshot barriers."""
        return self._journal

    def _record(self, kind, table_id, ids, data):
        """Journal one write before sending; returns its seq (or None
        when unjournaled). Entries are retained until the journal is
        trimmed at a snapshot barrier, so they can replay after a shard
        restore."""
        if self._journal is None:
            return None
        return self._journal.record({'kind': kind, 'table': table_id,
                                     'ids': ids, 'data': data})

    def _note_applied(self, out, seq):
        """Count a server-side dedup hit (retried/replayed journaled
        write the server had already applied)."""
        if seq is not None and isinstance(out, dict) \
                and not out.get('applied', True):
            self._journal.note_dedup()

    def push(self, table_id, ids, grads):
        ids, shard_idx = self._shard(ids)
        grads = np.asarray(grads, np.float32)
        dl = self._deadline()
        seq = self._record('push', table_id, ids.tolist(), grads)
        for s in range(self._n):
            mask = shard_idx == s
            if not mask.any():
                continue
            if self._local is not None:
                self._local[s].table(table_id).push(ids[mask].tolist(),
                                                    grads[mask])
            else:
                # unjournaled grad application is not idempotent: no
                # blind resend; journaled sends dedup server-side
                msg = {'op': 'push', 'table': table_id,
                       'ids': ids[mask].tolist(), 'grads': grads[mask]}
                if seq is not None:
                    msg['client'] = self._journal.client_id
                    msg['seq'] = seq
                out = self._call(s, msg, idempotent=seq is not None,
                                 deadline=dl)
                self._note_applied(out, seq)

    def _dim(self, table_id):
        if self._local is not None:
            return self._local[0].table(table_id).dim
        # remote: pull a probe row
        row = self._call(0, {'op': 'pull', 'table': table_id, 'ids': [0]})
        return row.shape[1]

    def push_delta(self, table_id, ids, deltas):
        """Geo-SGD path: add parameter deltas on the server."""
        ids, shard_idx = self._shard(ids)
        deltas = np.asarray(deltas, np.float32)
        dl = self._deadline()
        seq = self._record('push_delta', table_id, ids.tolist(), deltas)
        for s in range(self._n):
            mask = shard_idx == s
            if not mask.any():
                continue
            if self._local is not None:
                self._local[s].table(table_id).push_delta(
                    ids[mask].tolist(), deltas[mask])
            else:
                msg = {'op': 'push_delta', 'table': table_id,
                       'ids': ids[mask].tolist(), 'deltas': deltas[mask]}
                if seq is not None:
                    msg['client'] = self._journal.client_id
                    msg['seq'] = seq
                out = self._call(s, msg, idempotent=seq is not None,
                                 deadline=dl)
                self._note_applied(out, seq)

    def replay_journal(self):
        """Resend every retained journal entry (oldest first) after a
        shard restart/restore. The servers' journal marks decide per
        entry: writes lost with the crash re-apply, survivors dedup —
        the sum is exactly-once relative to the restored state. Returns
        (entries_replayed, dedup_hits counted during the replay)."""
        if self._journal is None:
            return 0, 0
        before = self._journal.dedup_hits
        replayed = 0
        for seq, entry in self._journal.entries():
            self._replay_entry(seq, entry)
            replayed += 1
            self._journal.note_replay()
        return replayed, self._journal.dedup_hits - before

    def _replay_entry(self, seq, entry):
        kind, table_id = entry['kind'], entry['table']
        data = np.asarray(entry['data'], np.float32)
        dl = self._deadline()
        if kind == 'push_dense':
            msg = {'op': 'push_dense', 'table': table_id, 'grad': data,
                   'client': self._journal.client_id, 'seq': seq}
            out = self._call(self._owner(table_id), msg,
                             idempotent=seq is not None, deadline=dl)
            self._note_applied(out, seq)
            return
        key = 'grads' if kind == 'push' else 'deltas'
        ids, shard_idx = self._shard(entry['ids'])
        for s in range(self._n):
            mask = shard_idx == s
            if not mask.any():
                continue
            msg = {'op': kind, 'table': table_id,
                   'ids': ids[mask].tolist(), key: data[mask],
                   'client': self._journal.client_id, 'seq': seq}
            out = self._call(s, msg, idempotent=seq is not None,
                             deadline=dl)
            self._note_applied(out, seq)

    # -- dense / barrier / tensor tables (placed by table_id % n) -----------
    def _owner(self, table_id):
        return int(table_id) % self._n

    def pull_dense(self, table_id):
        s = self._owner(table_id)
        if self._local is not None:
            return self._local[s].table(table_id).pull()
        return self._call(s, {'op': 'pull_dense', 'table': table_id},
                          deadline=self._deadline())

    def push_dense(self, table_id, grad):
        s = self._owner(table_id)
        if self._local is not None:
            return self._local[s].table(table_id).push(grad)
        grad = np.asarray(grad, np.float32)
        seq = self._record('push_dense', table_id, None, grad)
        # unjournaled grad application is not idempotent: no blind resend
        msg = {'op': 'push_dense', 'table': table_id, 'grad': grad}
        if seq is not None:
            msg['client'] = self._journal.client_id
            msg['seq'] = seq
        out = self._call(s, msg, idempotent=seq is not None,
                         deadline=self._deadline())
        self._note_applied(out, seq)

    def set_dense(self, table_id, value):
        s = self._owner(table_id)
        if self._local is not None:
            return self._local[s].table(table_id).set(value)
        # overwrite semantics: a resend re-writes the same value
        self._call(s, {'op': 'set_dense', 'table': table_id,
                       'value': np.asarray(value, np.float32)},
                   deadline=self._deadline())

    def barrier(self, table_id, worker_id=None, timeout=60.0):
        s = self._owner(table_id)
        if self._local is not None:
            return self._local[s].table(table_id).barrier(worker_id,
                                                          timeout)
        # ephemeral connection: a blocking barrier must not pin the shared
        # per-server channel (other threads' pulls/pushes keep flowing).
        # Transport timeout = barrier timeout + slack, so a wedged server
        # surfaces as a socket timeout instead of a hang.
        self._call_fresh(s, {'op': 'barrier', 'table': table_id,
                             'worker_id': worker_id, 'timeout': timeout},
                         timeout=timeout + 10.0)

    def tensor(self, table_id, method, *args):
        s = self._owner(table_id)
        if self._local is not None:
            return getattr(self._local[s].table(table_id), method)(*args)
        # set/get re-send safely; increment would double-count
        return self._call(s, {'op': 'tensor', 'table': table_id,
                              'method': method, 'args': args},
                          idempotent=(method != 'increment'),
                          deadline=self._deadline())

    def save(self, table_id, path):
        dl = self._deadline()
        for s in range(self._n):
            p = os.path.join(path, 'shard_%d' % s)
            if self._local is not None:
                self._local[s].table(table_id).save(p)
            else:
                self._call(s, {'op': 'save', 'table': table_id, 'path': p},
                           deadline=dl)
