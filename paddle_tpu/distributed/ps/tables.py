"""Parameter-server table zoo beyond the sparse embedding table.

Parity (SURVEY.md §2.1 "PS tables", reference distributed/table/):
  CommonDenseTable   -> DenseTable   (dense params, server-side optimizer)
  BarrierTable       -> BarrierTable (worker sync point)
  TensorTable        -> TensorTable  (named server-side dense tensors)
  SparseGeoTable     -> GeoSparseTable (geo-SGD delta aggregation)
  SsdSparseTable     -> SsdSparseTable (sqlite-backed overflow tier —
                        rocksdb's role, stdlib-only)
"""
import os
import sqlite3
import threading

import numpy as np

from .embedding_service import EmbeddingTable, _SparseOptimizer

__all__ = ['DenseTable', 'BarrierTable', 'TensorTable', 'GeoSparseTable',
           'SsdSparseTable']


class DenseTable:
    """Dense parameter block with the optimizer applied server-side
    (reference table/common_dense_table.cc + depends/dense.h)."""

    def __init__(self, shape, optimizer='sgd', lr=0.01, init='zeros',
                 seed=0):
        rng = np.random.RandomState(seed)
        if init == 'zeros':
            self._value = np.zeros(shape, np.float32)
        else:
            self._value = rng.uniform(-0.01, 0.01, shape).astype(np.float32)
        self._opt = _SparseOptimizer(optimizer, lr)
        self._slots = [np.zeros(shape, np.float32)
                       for _ in range(self._opt.slot_count())]
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self._value.copy()

    def push(self, grad):
        grad = np.asarray(grad, np.float32)
        with self._lock:
            new_v, new_slots = self._opt.apply(self._value.copy(),
                                               list(self._slots), grad)
            self._value = new_v
            self._slots = new_slots if new_slots else self._slots

    def set(self, value):
        with self._lock:
            self._value = np.asarray(value, np.float32).copy()

    def save(self, path):
        os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
        with self._lock:
            np.savez(path, value=self._value,
                     slots=np.stack(self._slots) if self._slots else
                     np.zeros((0,) + self._value.shape, np.float32))

    def load(self, path):
        data = np.load(path if path.endswith('.npz') else path + '.npz')
        with self._lock:
            self._value = data['value']
            self._slots = [s for s in data['slots']]

    def state_dict(self):
        with self._lock:
            return {'value': self._value.copy(),
                    'slots': [s.copy() for s in self._slots]}

    def set_state_dict(self, state):
        with self._lock:
            self._value = np.asarray(state['value'], np.float32).copy()
            self._slots = [np.asarray(s, np.float32).copy()
                           for s in state['slots']]


class TensorTable:
    """Named server-side dense tensors (reference table/tensor_table.cc —
    which runs a program server-side; here: plain set/get/increment, the
    part PS users actually depend on: global counters & stats)."""

    def __init__(self):
        self._tensors = {}
        self._lock = threading.Lock()

    def set(self, name, value):
        with self._lock:
            self._tensors[name] = np.asarray(value, np.float32).copy()

    def get(self, name):
        with self._lock:
            v = self._tensors.get(name)
            return None if v is None else v.copy()

    def increment(self, name, delta):
        with self._lock:
            cur = self._tensors.get(name)
            delta = np.asarray(delta, np.float32)
            self._tensors[name] = delta.copy() if cur is None \
                else cur + delta
            return self._tensors[name].copy()

    def state_dict(self):
        with self._lock:
            return {'tensors': {k: v.copy()
                                for k, v in self._tensors.items()}}

    def set_state_dict(self, state):
        with self._lock:
            self._tensors = {str(k): np.asarray(v, np.float32).copy()
                             for k, v in state['tensors'].items()}


class BarrierTable:
    """Counting barrier across `trigger_count` workers (reference
    table/barrier_table.cc). Reusable: each full round bumps a
    generation."""

    def __init__(self, trigger_count):
        self.trigger = int(trigger_count)
        self._count = 0
        self._gen = 0
        self._cv = threading.Condition()

    def barrier(self, worker_id=None, timeout=60.0):
        with self._cv:
            gen = self._gen
            self._count += 1
            if self._count >= self.trigger:
                self._count = 0
                self._gen += 1
                self._cv.notify_all()
                return True
            ok = self._cv.wait_for(lambda: self._gen != gen,
                                   timeout=timeout)
            if not ok:
                # withdraw this arrival — leaving it counted would let a
                # later round release with fewer live workers than trigger
                if self._gen == gen and self._count > 0:
                    self._count -= 1
                raise TimeoutError('barrier timed out (%d/%d arrived)'
                                   % (self._count, self.trigger))
            return True


class GeoSparseTable(EmbeddingTable):
    """Geo-SGD sparse table (reference table/sparse_geo_table.cc):
    workers train local replicas and push parameter DELTAS, which the
    server adds — no server-side optimizer on the delta path."""

    def push_delta(self, ids, deltas):
        with self._lock:
            for key, d in zip(ids, deltas):
                row = self._rows.get(key)
                if row is None:
                    row = self._new_row()
                    nslots = self._opt.slot_count()
                    if nslots:  # mirror pull(): a later grad push on this
                        # key must find initialized optimizer slots
                        self._slots[key] = [np.zeros(self.dim, np.float32)
                                            for _ in range(nslots)]
                self._rows[key] = row + d

    def pull_geo(self, ids):
        return self.pull(ids)


class SsdSparseTable(EmbeddingTable):
    """Sparse table with a bounded in-memory hot set and an sqlite-backed
    cold tier (reference table/ssd_sparse_table.cc over rocksdb). Rows are
    promoted on access and demoted in insertion order when the hot set
    exceeds `max_mem_rows`."""

    def __init__(self, dim, max_mem_rows=100000, db_path=None, **kwargs):
        super().__init__(dim, **kwargs)
        self.max_mem_rows = int(max_mem_rows)
        self._db_path = db_path or ':memory:'
        self._db = sqlite3.connect(self._db_path, check_same_thread=False)
        self._db.execute('CREATE TABLE IF NOT EXISTS rows '
                         '(id INTEGER PRIMARY KEY, val BLOB, slots BLOB)')
        self._db_lock = threading.Lock()

    def _demote_if_needed(self):
        # caller holds self._lock
        while len(self._rows) > self.max_mem_rows:
            key, row = next(iter(self._rows.items()))
            slots = self._slots.pop(key, [])
            del self._rows[key]
            blob = row.astype(np.float32).tobytes()
            sblob = np.concatenate([s.ravel() for s in slots]).astype(
                np.float32).tobytes() if slots else b''
            with self._db_lock:
                self._db.execute(
                    'INSERT OR REPLACE INTO rows VALUES (?,?,?)',
                    (int(key), blob, sblob))

    def _promote(self, key):
        # caller holds self._lock; returns row or None
        with self._db_lock:
            cur = self._db.execute(
                'SELECT val, slots FROM rows WHERE id=?', (int(key),))
            hit = cur.fetchone()
            if hit is None:
                return None
            self._db.execute('DELETE FROM rows WHERE id=?', (int(key),))
        row = np.frombuffer(hit[0], np.float32).copy()
        self._rows[key] = row
        nslots = self._opt.slot_count()
        if nslots:
            if hit[1]:
                flat = np.frombuffer(hit[1], np.float32).copy()
                self._slots[key] = [flat[i * self.dim:(i + 1) * self.dim]
                                    for i in range(nslots)]
            else:
                self._slots[key] = [np.zeros(self.dim, np.float32)
                                    for _ in range(nslots)]
        return row

    def pull(self, ids):
        out = np.empty((len(ids), self.dim), np.float32)
        with self._lock:
            for i, key in enumerate(ids):
                row = self._rows.get(key)
                if row is None:
                    row = self._promote(key)
                if row is None:
                    row = self._new_row()
                    self._rows[key] = row
                    nslots = self._opt.slot_count()
                    if nslots:
                        self._slots[key] = [np.zeros(self.dim, np.float32)
                                            for _ in range(nslots)]
                out[i] = row
            self._demote_if_needed()
        return out

    def push(self, ids, grads):
        with self._lock:
            for key, g in zip(ids, grads):
                if key not in self._rows and self._promote(key) is None:
                    continue
                row = self._rows[key]
                slots = self._slots.get(key, [])
                new_row, new_slots = self._opt.apply(row.copy(),
                                                     list(slots), g)
                self._rows[key] = new_row
                if new_slots:
                    self._slots[key] = new_slots
            self._demote_if_needed()

    def save(self, path):
        """Persist BOTH tiers, values AND optimizer slots (dropping slots
        across a checkpoint would reset adagrad/adam state — and break a
        later push on a loaded row)."""
        os.makedirs(path, exist_ok=True)
        nslots = self._opt.slot_count()
        empty = np.zeros(nslots * self.dim, np.float32)
        with self._lock:
            keys = list(self._rows.keys())
            vals = [v.copy() for v in self._rows.values()]
            slots = []
            for k in keys:
                s = self._slots.get(k)
                slots.append(np.concatenate([x.ravel() for x in s])
                             if s else empty.copy())
            with self._db_lock:
                for kid, blob, sblob in self._db.execute(
                        'SELECT id, val, slots FROM rows'):
                    keys.append(int(kid))
                    vals.append(np.frombuffer(blob, np.float32))
                    slots.append(np.frombuffer(sblob, np.float32)
                                 if sblob else empty.copy())
        np.savez(os.path.join(path, 'shard.npz'),
                 keys=np.asarray(keys, np.int64),
                 vals=np.stack(vals) if vals else
                 np.zeros((0, self.dim), np.float32),
                 slots=np.stack(slots) if slots else
                 np.zeros((0, nslots * self.dim), np.float32))

    def load(self, path):
        data = np.load(os.path.join(path, 'shard.npz'))
        nslots = self._opt.slot_count()
        with self._lock:
            with self._db_lock:
                self._db.execute('DELETE FROM rows')
            self._rows = {int(k): v.copy()
                          for k, v in zip(data['keys'], data['vals'])}
            self._slots = {}
            if nslots:
                saved = data['slots'] if 'slots' in data else None
                for i, k in enumerate(data['keys']):
                    if saved is not None and saved.shape[0] > i and \
                            saved.shape[1] == nslots * self.dim:
                        flat = saved[i].copy()
                    else:  # legacy checkpoint without slots: re-init zeros
                        flat = np.zeros(nslots * self.dim, np.float32)
                    self._slots[int(k)] = [
                        flat[j * self.dim:(j + 1) * self.dim]
                        for j in range(nslots)]
            self._demote_if_needed()

    def mem_rows(self):
        with self._lock:
            return len(self._rows)

    def disk_rows(self):
        with self._db_lock:
            return self._db.execute('SELECT COUNT(*) FROM rows'
                                    ).fetchone()[0]

    def __len__(self):
        return self.mem_rows() + self.disk_rows()
