"""Parameter-server capability (reference: paddle/fluid/distributed/ brpc PS
+ tables). TPU-native analog: host-resident sharded embedding service —
see embedding_service.py (in-proc + grpc-less socket RPC) and runtime.py
(fleet wiring)."""
from . import runtime  # noqa: F401
from .embedding_service import (EmbeddingTable, EmbeddingServer,  # noqa: F401
                                EmbeddingClient)
from .tables import (DenseTable, BarrierTable, TensorTable,  # noqa: F401
                     GeoSparseTable, SsdSparseTable)
from .communicator import (Communicator, AsyncCommunicator,  # noqa: F401
                           HalfAsyncCommunicator, SyncCommunicator,
                           GeoCommunicator)
from .dataset import MultiSlotDataset  # noqa: F401
from .trainer import DownpourTrainer, AsyncExecutor  # noqa: F401
from .heter import HeterEmbedding, PassCachedEmbedding  # noqa: F401
