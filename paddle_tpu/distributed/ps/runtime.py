"""PS runtime wiring for fleet (reference: fleet/runtime/the_one_ps.py:434
TheOnePSRuntime: _init_worker builds Communicator, _init_server hosts
tables)."""
import os

from .embedding_service import EmbeddingServer, EmbeddingClient

_PS = {'servers': [], 'client': None, 'server': None}


def init_server(fleet_state, *args, **kwargs):
    srv = EmbeddingServer(
        host='0.0.0.0',
        port=int(os.environ.get('PADDLE_PORT', '0') or 0))
    _PS['server'] = srv
    return srv


def run_server(fleet_state):
    if _PS['server'] is None:
        init_server(fleet_state)
    _PS['server'].start(block=True)


def init_worker(fleet_state):
    eps = os.environ.get('PADDLE_PSERVERS_IP_PORT_LIST', '')
    if eps:
        _PS['client'] = EmbeddingClient(endpoints=eps.split(','))
    return _PS['client']


def stop_worker(fleet_state):
    if _PS['client'] is not None:
        _PS['client'] = None


def get_client():
    return _PS['client']


def local_cluster(num_servers=2, dim=8, table_id=0, **table_kwargs):
    """Same-process PS cluster for tests (reference pattern:
    distributed/test/brpc_service_dense_sgd_test.cc spins server+client in
    one process)."""
    servers = [EmbeddingServer() for _ in range(num_servers)]
    for s in servers:
        s.create_table(table_id, dim, **table_kwargs)
        s.start(block=False)
    client = EmbeddingClient(servers=servers)
    return servers, client
