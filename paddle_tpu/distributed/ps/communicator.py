"""Worker-side communicator: batches gradient sends to the PS on
background threads.

Parity: distributed/service/communicator.h — AsyncCommunicator (:348,
queue + merge + background send), HalfAsyncCommunicator (:423, async sends
with a drain barrier), SyncCommunicator (:468, send inline each step),
GeoCommunicator (:497, push parameter DELTAS every k local updates).
"""
import queue
import threading

import numpy as np

__all__ = ['Communicator', 'AsyncCommunicator', 'HalfAsyncCommunicator',
           'SyncCommunicator', 'GeoCommunicator']


def _merge_by_id(ids, grads):
    """Sum duplicate-id gradients (communicator merge_sparse_grad)."""
    ids = np.asarray(ids, np.int64)
    grads = np.asarray(grads, np.float32)
    uniq, inv = np.unique(ids, return_inverse=True)
    merged = np.zeros((len(uniq), grads.shape[1]), np.float32)
    np.add.at(merged, inv, grads)
    return uniq, merged


class Communicator:
    """mode: 'async' | 'half_async' | 'sync' | 'geo'."""

    def __init__(self, client, mode='async', send_queue_size=20,
                 merge_size=2, geo_need_push_nums=100):
        assert mode in ('async', 'half_async', 'sync', 'geo')
        self.client = client
        self.mode = mode
        self.merge_size = max(int(merge_size), 1)
        self.geo_need_push_nums = int(geo_need_push_nums)
        self._queue = queue.Queue(maxsize=send_queue_size)
        self._thread = None
        self._running = False
        self._pending = 0
        self._pending_cv = threading.Condition()
        self._send_error = None  # first error from the send loop
        # geo state: local deltas accumulated per table
        self._geo_acc = {}
        self._geo_count = 0
        self._geo_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        # sync sends inline; geo accumulates and flushes from the pushing
        # thread — neither has work for a background send loop
        if self.mode in ('sync', 'geo') or self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()

    def stop(self):
        try:
            self.flush()
        finally:
            # shut the thread down even when flush surfaces a deferred
            # send error — stop() must leave no live background thread
            self._running = False
            if self._thread is not None:
                self._queue.put(None)   # wake the loop
                self._thread.join(timeout=5)
                self._thread = None

    is_running = property(lambda self: self._running)

    # -- send path -----------------------------------------------------------
    def push_sparse_grad(self, table_id, ids, grads):
        if self.mode == 'sync':
            uniq, merged = _merge_by_id(ids, grads)
            self.client.push(table_id, uniq, merged)
            return
        if self.mode == 'geo':
            raise RuntimeError('geo mode pushes deltas: use '
                               'push_sparse_param(table_id, ids, deltas)')
        with self._pending_cv:
            self._pending += 1
        self._queue.put((table_id, np.asarray(ids, np.int64),
                         np.asarray(grads, np.float32)))

    def push_sparse_param(self, table_id, ids, deltas):
        """Geo mode: accumulate local param deltas; every
        geo_need_push_nums accumulated rows, push the merged deltas."""
        if self.mode != 'geo':
            # mirror geo mode's hard error for the converse misuse: deltas
            # are NOT gradients — the server would lr-scale and sign-flip
            raise RuntimeError('push_sparse_param pushes parameter deltas '
                               'and is geo-mode only; use push_sparse_grad '
                               'for %r communicators' % self.mode)
        with self._geo_lock:
            acc = self._geo_acc.setdefault(table_id, {})
            for key, d in zip(np.asarray(ids, np.int64),
                              np.asarray(deltas, np.float32)):
                k = int(key)
                acc[k] = acc.get(k, 0) + d
            self._geo_count += len(ids)
            if self._geo_count >= self.geo_need_push_nums:
                self._geo_flush_locked()

    def _geo_flush_locked(self):
        for table_id, acc in self._geo_acc.items():
            if not acc:
                continue
            ids = np.asarray(list(acc.keys()), np.int64)
            deltas = np.stack(list(acc.values()))
            self.client.push_delta(table_id, ids, deltas)
        self._geo_acc = {}
        self._geo_count = 0

    def _send_loop(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            batch = [item]
            # opportunistically merge up to merge_size queued sends
            while len(batch) < self.merge_size:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._queue.put(None)
                    break
                batch.append(nxt)
            by_table = {}
            for table_id, ids, grads in batch:
                by_table.setdefault(table_id, ([], []))
                by_table[table_id][0].append(ids)
                by_table[table_id][1].append(grads)
            try:
                for table_id, (id_list, g_list) in by_table.items():
                    uniq, merged = _merge_by_id(np.concatenate(id_list),
                                                np.concatenate(g_list))
                    self.client.push(table_id, uniq, merged)
            except Exception as e:  # keep the loop alive on transient RPC
                if self._send_error is None:  # errors; surface via flush()
                    self._send_error = e
            finally:
                with self._pending_cv:
                    self._pending -= len(batch)
                    self._pending_cv.notify_all()

    def flush(self, timeout=30.0):
        """Drain in-flight sends (the half-async barrier; async callers can
        use it too before save/eval)."""
        if self.mode == 'geo':
            with self._geo_lock:
                self._geo_flush_locked()
            return
        with self._pending_cv:
            ok = self._pending_cv.wait_for(lambda: self._pending == 0,
                                           timeout=timeout)
        if self._send_error is not None:
            err, self._send_error = self._send_error, None
            raise RuntimeError('communicator send loop failed; gradients '
                               'were dropped') from err
        if not ok:
            raise TimeoutError('communicator flush timed out '
                               '(%d sends pending)' % self._pending)

    barrier = flush


def AsyncCommunicator(client, **kw):
    return Communicator(client, mode='async', **kw)


def HalfAsyncCommunicator(client, **kw):
    return Communicator(client, mode='half_async', **kw)


def SyncCommunicator(client, **kw):
    return Communicator(client, mode='sync', **kw)


def GeoCommunicator(client, **kw):
    return Communicator(client, mode='geo', **kw)
