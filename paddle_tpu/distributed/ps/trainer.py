"""Dataset-driven PS trainer (reference: framework/executor.cc:152
Executor::RunFromDataset -> trainer.h:102 MultiTrainer ->
device_worker.h:244 HogwildWorker / :275 DownpourWorker TrainFiles).

TPU-native division of labor: worker threads drain the dataset channel;
per batch they PULL the unique sparse ids' rows from the embedding
service, run the dense half as ONE jitted fwd+bwd program (the device
part — XLA replaces the per-op Hogwild loop), PUSH sparse grads through
the communicator (async/half_async/sync/geo), and update the shared dense
params Hogwild-style (lock-free, as HogwildWorker does). The model is a
pooled-embedding CTR net: per-slot mean-pooled embeddings -> MLP ->
sigmoid logloss (the reference's ctr_dnn fleet example shape).
"""
import threading

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['DownpourTrainer', 'AsyncExecutor']


class DownpourTrainer:
    """CTR trainer over sparse PS slots + local dense MLP.

    client: EmbeddingClient (rows live host-side, maybe SSD-backed)
    communicator: ps.communicator.Communicator (push mode semantics)
    slots: sparse slot names (each has a table on the PS)
    tables: {slot_name: table_id}
    """

    def __init__(self, client, communicator, slots, tables, emb_dim,
                 hidden=32, lr=0.05, n_threads=2, seed=0,
                 label_slot='label'):
        self.client = client
        self.comm = communicator
        self.slots = list(slots)
        self.tables = dict(tables)
        self.emb_dim = emb_dim
        self.lr = lr
        self.n_threads = max(int(n_threads), 1)
        self.label_slot = label_slot
        rng = np.random.RandomState(seed)
        d_in = emb_dim * len(self.slots)
        # shared Hogwild dense params (numpy: lock-free in-place updates)
        self.dense = {
            'w1': rng.randn(d_in, hidden).astype(np.float32) * 0.1,
            'b1': np.zeros(hidden, np.float32),
            'w2': rng.randn(hidden, 1).astype(np.float32) * 0.1,
            'b2': np.zeros(1, np.float32),
        }
        self._step = jax.jit(self._make_step())
        self._losses = []
        self._loss_lock = threading.Lock()

    def _make_step(self):
        n_slots = len(self.slots)
        dim = self.emb_dim

        def step(dense, pooled, labels):
            """pooled: [B, n_slots, dim]; returns loss, d_pooled, d_dense."""
            def loss_fn(dense, pooled):
                x = pooled.reshape(pooled.shape[0], n_slots * dim)
                h = jnp.tanh(x @ dense['w1'] + dense['b1'])
                logit = (h @ dense['w2'] + dense['b2'])[:, 0]
                # sigmoid cross-entropy (logloss)
                return jnp.mean(jnp.maximum(logit, 0) - logit * labels +
                                jnp.log1p(jnp.exp(-jnp.abs(logit))))
            loss, (d_dense, d_pooled) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(dense, pooled)
            return loss, d_pooled, d_dense
        return step

    def _train_one_batch(self, batch):
        bsz = batch['__size__']
        pooled = np.zeros((bsz, len(self.slots), self.emb_dim), np.float32)
        slot_ctx = []
        for s, name in enumerate(self.slots):
            ids, offs = batch[name]
            uniq, inv = np.unique(ids, return_inverse=True)
            rows = self.client.pull(self.tables[name], uniq)  # [U, dim]
            # mean-pool per instance
            for i in range(bsz):
                lo, hi = offs[i], offs[i + 1]
                if hi > lo:
                    pooled[i, s] = rows[inv[lo:hi]].mean(axis=0)
            slot_ctx.append((ids, offs))

        labels = batch[self.label_slot]
        loss, d_pooled, d_dense = self._step(
            {k: jnp.asarray(v) for k, v in self.dense.items()},
            jnp.asarray(pooled), jnp.asarray(labels))
        d_pooled = np.asarray(d_pooled)

        # sparse push: distribute each instance's pooled grad to its ids
        for s, name in enumerate(self.slots):
            pos_ids, offs = slot_ctx[s]
            n_pos = offs[-1]
            if n_pos == 0:
                continue
            pos_grads = np.zeros((n_pos, self.emb_dim), np.float32)
            for i in range(len(offs) - 1):
                lo, hi = offs[i], offs[i + 1]
                if hi > lo:
                    pos_grads[lo:hi] = d_pooled[i, s] / (hi - lo)
            if self.comm.mode == 'geo':
                self.comm.push_sparse_param(self.tables[name], pos_ids,
                                            -self.lr * pos_grads)
            else:
                self.comm.push_sparse_grad(self.tables[name], pos_ids,
                                           pos_grads)

        # Hogwild dense update (lock-free, HogwildWorker semantics)
        for k, g in d_dense.items():
            self.dense[k] -= self.lr * np.asarray(g)
        return float(loss)

    def train_from_dataset(self, dataset, epochs=1, debug=False):
        """The Executor::RunFromDataset analog: drain the dataset channel
        with n_threads workers; returns per-batch losses (in completion
        order)."""
        channel = dataset.start_channel(epochs=epochs)
        self._losses = []

        def worker():
            while True:
                item = channel.get()
                if item is None:
                    channel.put(None)  # wake siblings
                    return
                loss = self._train_one_batch(item)
                with self._loss_lock:
                    self._losses.append(loss)

        threads = [threading.Thread(target=worker)
                   for _ in range(self.n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.comm.flush()
        return list(self._losses)


class AsyncExecutor:
    """Legacy async-executor API (reference: framework/async_executor.cc,
    deprecated there in favor of the TrainerBase runtime). Kept as a thin
    facade over DownpourTrainer so old run-from-dataset scripts port:
    construct, then run(trainer, dataset) or run_from_files(...)."""

    def __init__(self, place=None, run_mode=''):
        self.place = place

    def run(self, trainer, dataset, debug=False, epochs=1):
        """trainer: a DownpourTrainer (the modern runtime)."""
        return trainer.train_from_dataset(dataset, epochs=epochs,
                                          debug=debug)

    def run_from_files(self, trainer, filelist, slots, batch_size=32,
                       epochs=1, shuffle_seed=None):
        from .dataset import MultiSlotDataset
        ds = MultiSlotDataset()
        ds.set_use_var(slots)
        ds.set_filelist(filelist)
        ds.set_batch_size(batch_size)
        ds.load_into_memory()
        if shuffle_seed is not None:
            ds.local_shuffle(seed=shuffle_seed)
        return self.run(trainer, ds, epochs=epochs)
