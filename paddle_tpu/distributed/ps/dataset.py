"""Dataset for PS training (reference: framework/data_set.h:43 DatasetImpl,
data_feed.h:208 MultiSlotDataFeed, python paddle.distributed.fleet Dataset).

File-sharded MultiSlot ingestion: files are parsed (native datafeed.cc
parser when available) by loader threads into an in-memory instance pool,
then batches flow through a bounded channel (framework/channel.h analog)
that trainer worker threads drain — the RunFromDataset feeding model.
"""
import glob as _glob
import queue
import threading

import numpy as np

from ...native.datafeed import parse_multislot

__all__ = ['MultiSlotDataset', 'BoxPSDataset']


class MultiSlotDataset:
    """use_var order defines the slot layout: [(name, 'int64'|'float'), ...]
    with by convention the LAST float slot being the label (the reference
    encodes this in trainer_desc; here it is explicit via label_slot)."""

    def __init__(self):
        self._filelist = []
        self._batch_size = 32
        self._n_load_threads = 1
        self._slots = []
        self._pool = []
        self._lock = threading.Lock()
        self._channel = None
        self._drop_last = False

    # -- reference Dataset API ------------------------------------------------
    def set_filelist(self, files):
        out = []
        for f in files:
            hits = sorted(_glob.glob(f))
            out.extend(hits if hits else [f])
        self._filelist = out

    def set_batch_size(self, b):
        self._batch_size = int(b)

    def set_thread(self, n):
        self._n_load_threads = max(int(n), 1)

    def set_use_var(self, slots):
        """slots: [(name, 'int64'|'float'), ...]."""
        self._slots = [(n, 'float' if t.startswith('float') else 'int64')
                       for n, t in slots]

    def load_into_memory(self):
        """Parse every file into the instance pool (InMemoryDataFeed)."""
        types = [t if t == 'float' else 'int' for _, t in self._slots]
        files = list(self._filelist)
        idx = {'i': 0}

        def loader():
            while True:
                with self._lock:
                    if idx['i'] >= len(files):
                        return
                    fn = files[idx['i']]
                    idx['i'] += 1
                with open(fn) as f:
                    text = f.read()
                slots, n_inst = parse_multislot(text, types)
                insts = []
                for i in range(n_inst):
                    inst = []
                    for (vals, offs) in slots:
                        inst.append(vals[offs[i]:offs[i + 1]])
                    insts.append(inst)
                with self._lock:
                    self._pool.extend(insts)

        threads = [threading.Thread(target=loader)
                   for _ in range(self._n_load_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def local_shuffle(self, seed=0):
        rng = np.random.RandomState(seed)
        with self._lock:
            rng.shuffle(self._pool)

    global_shuffle = local_shuffle  # single-node analog

    def get_memory_data_size(self):
        return len(self._pool)

    # -- channel --------------------------------------------------------------
    def start_channel(self, epochs=1):
        """Fill a bounded channel with batches; returns the channel.
        A batch is {slot_name: (values, offsets)} CSR per sparse slot and
        a dense np array per float slot, plus '__size__'."""
        self._channel = queue.Queue(maxsize=64)

        def feeder():
            for _ in range(epochs):
                b = self._batch_size
                n = len(self._pool)
                end = (n // b) * b if self._drop_last else n
                for lo in range(0, end, b):
                    chunk = self._pool[lo:lo + b]
                    if not chunk:
                        continue
                    self._channel.put(self._make_batch(chunk))
            self._channel.put(None)

        threading.Thread(target=feeder, daemon=True).start()
        return self._channel

    def _make_batch(self, chunk):
        batch = {'__size__': len(chunk)}
        for s, (name, t) in enumerate(self._slots):
            vals = [inst[s] for inst in chunk]
            if t == 'float':
                batch[name] = np.asarray(
                    [v[0] if len(v) else 0.0 for v in vals], np.float32)
            else:
                flat = np.concatenate(vals) if vals else \
                    np.zeros(0, np.int64)
                offs = np.zeros(len(vals) + 1, np.int64)
                np.cumsum([len(v) for v in vals], out=offs[1:])
                batch[name] = (flat.astype(np.int64), offs)
        return batch


class BoxPSDataset(MultiSlotDataset):
    """BoxPS-style pass-oriented dataset (reference framework/fleet/
    box_wrapper.h BeginPass/EndPass): begin_pass()/end_pass() bracket a
    training pass — pair with ps.heter.PassCachedEmbedding, whose
    begin_pass pulls the pass working set into HBM and end_pass flushes
    deltas. wait_preload_done/preload_into_memory map onto the in-memory
    loader."""

    def begin_pass(self):
        return True

    def end_pass(self, need_save_delta=False):
        return True

    def preload_into_memory(self):
        self.load_into_memory()

    def wait_preload_done(self):
        return True
