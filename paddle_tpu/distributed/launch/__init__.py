"""paddle.distributed.launch (reference: fleet/launch.py:243 +
launch_utils.py TrainerProc supervision).

TPU-native: one process per HOST (not per chip — single-controller SPMD
drives all local chips), env parity (PADDLE_TRAINER_ID/ENDPOINTS) kept so
reference launch scripts work. Supervision: any child exit != 0 tears down
the pod and propagates logs; elastic restarts come from elastic.py.
"""
from .main import launch, main  # noqa: F401
