"""Launcher implementation."""
import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ['launch', 'main']


def _parse_args(argv=None):
    p = argparse.ArgumentParser('paddle_tpu.distributed.launch')
    p.add_argument('--ips', '--cluster_node_ips', dest='ips',
                   default='127.0.0.1', help='comma-separated host ips')
    p.add_argument('--host', '--node_ip', dest='host', default=None)
    p.add_argument('--nproc_per_node', type=int, default=1,
                   help='processes per host (1 drives all local TPU chips)')
    p.add_argument('--start_port', type=int, default=6170)
    p.add_argument('--log_dir', default=None)
    p.add_argument('--run_mode', default='collective',
                   choices=['collective', 'ps'])
    p.add_argument('--servers', default='')
    p.add_argument('--workers', default='')
    p.add_argument('--elastic_server', default=None,
                   help='etcd-style kv endpoint for elastic membership')
    p.add_argument('--job_id', default='default')
    p.add_argument('--np', type=int, default=None,
                   help='elastic: target node count')
    p.add_argument('training_script')
    p.add_argument('training_script_args', nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class TrainerProc:
    def __init__(self, proc, rank, log_f):
        self.proc = proc
        self.rank = rank
        self.log_f = log_f


def _spawn_local(args, hosts, my_host):
    procs = []
    n_hosts = len(hosts)
    endpoints = ','.join('%s:%d' % (h, args.start_port) for h in hosts)
    my_rank = hosts.index(my_host)
    for local in range(args.nproc_per_node):
        rank = my_rank * args.nproc_per_node + local
        env = dict(os.environ)
        env.update({
            'PADDLE_TRAINER_ID': str(rank),
            'PADDLE_CURRENT_ENDPOINT': '%s:%d' % (my_host, args.start_port),
            'PADDLE_TRAINERS_NUM': str(n_hosts * args.nproc_per_node),
            'PADDLE_TRAINER_ENDPOINTS': endpoints,
            'FLAGS_selected_tpus': str(local),
            'TRAINING_ROLE': 'TRAINER',
        })
        if os.environ.get('PADDLE_TRAINER_TRACE_DIR'):
            # per-rank trace dirs; profiler.merge_traces builds the
            # cluster timeline from them (CrossStackProfiler analog)
            env['PADDLE_TRAINER_TRACE_DIR'] = os.path.join(
                os.environ['PADDLE_TRAINER_TRACE_DIR'], 'rank_%d' % rank)
        log_f = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            log_f = open(os.path.join(args.log_dir,
                                      'workerlog.%d' % rank), 'w')
        cmd = [sys.executable, '-u', args.training_script] + \
            args.training_script_args
        proc = subprocess.Popen(cmd, env=env, stdout=log_f or None,
                                stderr=subprocess.STDOUT if log_f else None)
        procs.append(TrainerProc(proc, rank, log_f))
    return procs


def _watch(procs):
    """Supervision loop (launch_utils.py TrainerProc watch): first non-zero
    exit kills the pod."""
    try:
        while True:
            alive = False
            for tp in procs:
                ret = tp.proc.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    for other in procs:
                        if other.proc.poll() is None:
                            other.proc.send_signal(signal.SIGTERM)
                    return ret
            if not alive:
                return 0
            time.sleep(0.5)
    except KeyboardInterrupt:
        for tp in procs:
            if tp.proc.poll() is None:
                tp.proc.send_signal(signal.SIGTERM)
        return 130
    finally:
        for tp in procs:
            if tp.log_f:
                tp.log_f.close()


ELASTIC_EXIT_CODE = 101  # reference: fleet/elastic.py:26


def launch(argv=None):
    args = _parse_args(argv)
    hosts = args.ips.split(',')
    my_host = args.host or hosts[0]

    if args.elastic_server:
        from ..fleet.elastic import ElasticManager
        mgr = ElasticManager(args.elastic_server, args.job_id,
                             np=args.np or len(hosts), host=my_host)
        while True:
            mgr.register()
            procs = _spawn_local(args, mgr.hosts(), my_host)
            ret = _watch(procs)
            if ret == ELASTIC_EXIT_CODE or mgr.membership_changed():
                # scale event: relaunch with new world (reference
                # launch.py:79-83 behavior)
                mgr.wait_for_stable()
                continue
            mgr.unregister()
            return ret

    procs = _spawn_local(args, hosts, my_host)
    return _watch(procs)


def main():
    sys.exit(launch())


if __name__ == '__main__':
    main()
