"""1F1B pipeline schedule (reference: framework/section_worker.cc:104-143
micro-batch loop RunForward/RunBackward/RunUpdate;
fleet/meta_parallel/pipeline_parallel.py:109 train_batch).

TPU-native 1F1B: the schedule is ONE lax.scan inside a shard_map over the
'pp' mesh axis, where every tick each stage runs (a) the forward of the
incoming microbatch and (b) the backward of the microbatch whose cotangent
just arrived — forwards and backwards interleave exactly as in the
reference's steady state, so the stash of saved stage inputs is a circular
buffer of size O(pp), NOT O(n_micro) (the GPipe scan in pipeline.py keeps
O(n_micro + pp)). Backward recomputes the stage from its stashed input
(recompute is inherent to the schedule, as in SectionWorker).

Because micro-level loss must live INSIDE the pipelined region (a backward
can only start once ITS loss exists — with loss outside, reverse-mode AD
degenerates to GPipe), the model provides a 3-way decomposition via
`pp_decompose()`: pre (embedding...), blocks (homogeneous stack), post
(head + loss). Tied weights (e.g. wte reused by the head) are ONE param
entry used by both pre and post; their per-rank grads sum in the vjp and
the psum over pp adds the rank-0 (embedding) and last-rank (head)
contributions — the SharedLayerDesc tied-grad rule for free.

The whole schedule runs in the PRIMAL computation and emits grads; a
custom_vjp hands those precomputed grads to the outer jax.grad, scaled by
the incoming loss cotangent. Timeline (rank r, microbatch i):
  forward  at tick r + i
  backward at tick 2(pp-1) - r + i
  => in-flight stash span = 2(pp-1-r), total ticks = n_micro + 2(pp-1).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework import functional as func_mod
from ..framework import random as rng_mod
from ..framework.core import Tensor
from .pipeline import _cpu_mesh
from .shard_map_compat import shard_map
from .auto_parallel import tuner as ap_tuner

__all__ = ['one_f_one_b_loss', 'supports_1f1b']


def supports_1f1b(model):
    return hasattr(model, 'pp_decompose')


def one_f_one_b_loss(model, params, inputs, labels, state, loss_fn=None):
    """Scalar loss array; d(loss)/d(params) flows through a custom_vjp
    whose backward returns the grads the interleaved schedule computed.

    params: {name: array} covering every model parameter (the arrays may
    be outer-jit tracers). inputs/labels: int arrays [B, ...]. loss_fn
    (logits Tensor, labels Tensor) -> scalar Tensor is forwarded to
    pp_decompose so the user's objective is honored inside the last stage.
    """
    mesh = state['mesh']
    axis = state['axis']
    pp = state['n_stages']
    n_micro = state['n_micro']
    # dropout under 1F1B: a per-step base key crosses the shard_map
    # boundary and every mask key is a pure function of (base key,
    # microbatch index, stage, layer) — so masks differ per microbatch
    # and per step, and the backward's stage RECOMPUTE (jax.vjp of
    # tick_fn at the backward tick) rederives bit-identical masks from
    # the same indices. Reference capability: parallel_layers/random.py.
    # Always threaded: a "does the model draw RNG?" heuristic would
    # silently bake one mask per trace for any dropout form it missed.
    base_key = rng_mod.next_key()
    import inspect
    takes_loss = True
    try:
        sig = inspect.signature(model.pp_decompose)
        takes_loss = bool(sig.parameters)
    except (TypeError, ValueError):
        pass
    if takes_loss:
        pre_fn, blocks, post_fn = model.pp_decompose(loss_fn)
    else:
        if loss_fn is not None:
            import warnings
            warnings.warn(
                '%s.pp_decompose() takes no loss_fn — the 1F1B schedule '
                'uses the loss baked into its post stage, NOT the loss_fn '
                'passed to the train step' % type(model).__name__)
        pre_fn, blocks, post_fn = model.pp_decompose()
    blocks = list(blocks)
    n_layers = len(blocks)
    # uneven layer counts pad to pp*ceil(n/pp) with zero ghost layers
    # masked to identity (see pipeline.pipeline_blocks; grads to ghosts
    # are discarded — unstack_grads reads only the real entries)
    per = -(-n_layers // pp)
    n_pad = pp * per - n_layers
    template = blocks[0]
    block_pnames = {}  # stacked name -> [per-layer full names]
    tmpl_names = [n for n, _ in template.named_parameters()]
    blk_maps = [dict(b.named_parameters()) for b in blocks]
    full_names = {n: [None] * len(blocks) for n in tmpl_names}
    pmap_all = dict(model.named_parameters())
    rev = {id(p): n for n, p in pmap_all.items()}
    for li, bm in enumerate(blk_maps):
        for n in tmpl_names:
            full_names[n][li] = rev[id(bm[n])]
    block_param_names = {fn2 for ns in full_names.values() for fn2 in ns}
    outer_names = [n for n in params if n not in block_param_names]

    cpu = _cpu_mesh(mesh)

    b = inputs.shape[0]
    if b % n_micro:
        raise ValueError('batch %d %% n_micro %d != 0' % (b, n_micro))
    mb = b // n_micro
    micro_ids = inputs.reshape((n_micro, mb) + inputs.shape[1:])
    micro_lbl = labels.reshape((n_micro, mb) + labels.shape[1:])
    # auto_parallel planner: pin the Auto-axis shardings at the region
    # boundaries (microbatch stream + stacked stage params) so GSPMD has
    # nothing to guess inside the while body — see planner.py for the
    # root cause of the MULTICHIP r05 cfg5 involuntary-reshard warnings.
    # Resolved through the tuner so a PADDLE_TPU_PLAN_DIR artifact
    # (tuned, content-addressed) overrides the analytic specs.
    plan = ap_tuner.resolve_plan(mesh, axis)
    if plan is not None:
        micro_ids = plan.constrain_micro(micro_ids)
        micro_lbl = plan.constrain_micro(micro_lbl)

    # probe shapes eagerly (abstract eval only) to size the rotating bufs;
    # the key scope keeps any dropout draw inside the probe from leaking
    # an abstract tracer into the live generator
    def _probe(ids):
        with rng_mod.key_scope(jax.random.PRNGKey(0)):
            return _call_pre(model, pre_fn, params, ids)
    x_shape_dtype = jax.eval_shape(_probe, micro_ids[0])

    def stacked_of(pdict):
        out = {}
        for n in tmpl_names:
            arrs = [pdict[fn2] for fn2 in full_names[n]]
            a = jnp.stack(arrs)
            if n_pad:
                a = jnp.concatenate(
                    [a, jnp.zeros((n_pad,) + a.shape[1:], a.dtype)])
            out[n] = a.reshape((pp, per) + a.shape[1:])
        return out

    def unstack_grads(stacked_grads):
        out = {}
        for n, a in stacked_grads.items():
            flat = a.reshape((pp * per,) + a.shape[2:])
            for li, fn2 in enumerate(full_names[n]):
                out[fn2] = flat[li]
        return out

    # the base key rides as an EXPLICIT custom_vjp argument (a closed-over
    # tracer inside a custom_vjp body raises UnexpectedTracerError); its
    # cotangent is float0 (integer-typed input)
    @jax.custom_vjp
    def pp_loss(p, key_in):
        loss, _ = _run(p, key_in)
        return loss

    def _fwd(p, key_in):
        return _run(p, key_in)

    def _bwd(grads, g):
        key_ct = np.zeros((2,), jax.dtypes.float0)
        return (jax.tree_util.tree_map(lambda a: a * g, grads), key_ct)

    pp_loss.defvjp(_fwd, lambda res, g: _bwd(res, g))

    def _run(p, key_in):
        stacked = stacked_of(p)
        if plan is not None:
            stacked = plan.constrain_stacked(stacked)
        outer = {n: p[n] for n in outer_names}
        pdtypes = {n: a.dtype for n, a in outer.items()}
        if cpu:
            # f32 across the boundary: replicated operands' grad psums over
            # pp abort XLA:CPU's AllReducePromotion in bf16 (see pipeline.py)
            outer_in = {n: a.astype(jnp.float32) for n, a in outer.items()}
        else:
            outer_in = outer

        wire = jnp.float32 if cpu else jnp.dtype(x_shape_dtype.dtype)

        def body(stacked_local, outer_p, ids_all, lbl_all, key_b):
            if cpu:
                outer_p = {n: a.astype(pdtypes[n])
                           for n, a in outer_p.items()}
            local = {n: a[0] for n, a in stacked_local.items()}
            r = lax.axis_index(axis)
            last = pp - 1
            T = n_micro + 2 * (pp - 1)
            S = 2 * pp
            x_shape = (mb,) + tuple(x_shape_dtype.shape[1:])
            x_dtype = jnp.dtype(x_shape_dtype.dtype)

            def tick_fn(x_in, outer_params, local_blocks, i_mb):
                """One stage application: (y, mb_loss). pre and post run
                under lax.cond on the pp rank: only stage 0 pays the
                embedding lookup and only the last stage pays the
                vocab-size head matmul + loss (branching on the rank is
                SPMD-safe here — all devices sharing a pp coordinate take
                the same branch, so any auto-axis collectives inside a
                branch stay consistent)."""
                ids_mb = ids_all[i_mb]
                lbl_mb = lbl_all[i_mb]
                key_mb = jax.random.fold_in(key_b, i_mb)
                with rng_mod.key_scope(jax.random.fold_in(key_mb, 0)):
                    x0 = lax.cond(
                        r == 0,
                        lambda xi: _call_pre(model, pre_fn, outer_params,
                                             ids_mb).astype(x_dtype),
                        lambda xi: xi,
                        x_in.astype(x_dtype))

                def layer(c, xs):
                    lp, lk, j = xs
                    with rng_mod.key_scope(lk):
                        out, _ = func_mod.functional_call(
                            template, lp, {},
                            args=(Tensor(c, stop_gradient=False),))
                    if n_pad:
                        # ghost (padding) layers act as identity
                        out = jnp.where(r * per + j < n_layers, out, c)
                    return out, None
                # decorrelate by GLOBAL layer index r*per + j
                lkeys = jax.vmap(lambda j: jax.random.fold_in(
                    key_mb, 1 + r * per + j))(jnp.arange(per))
                y, _ = lax.scan(layer, x0,
                                (local_blocks, lkeys, jnp.arange(per)))
                with rng_mod.key_scope(jax.random.fold_in(key_mb,
                                                          99991)):
                    mb_loss = lax.cond(
                        r == last,
                        lambda yy: _call_post(model, post_fn, outer_params,
                                              yy,
                                              lbl_mb).astype(jnp.float32),
                        lambda yy: jnp.zeros((), jnp.float32),
                        y)
                return y, mb_loss

            zero_outer = {n: jnp.zeros(a.shape, jnp.float32)
                          for n, a in outer_p.items()}
            zero_blocks = {n: jnp.zeros(a.shape, jnp.float32)
                           for n, a in local.items()}
            carry0 = dict(
                fwd_buf=jnp.zeros(x_shape, wire),
                bwd_buf=jnp.zeros(x_shape, jnp.float32),
                stash=jnp.zeros((S,) + x_shape, wire),
                g_outer=zero_outer,
                g_blocks=zero_blocks,
                loss=jnp.zeros((), jnp.float32),
            )
            fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
            bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]

            def tick(carry, t):
                i_f = t - r
                f_valid = jnp.logical_and(i_f >= 0, i_f < n_micro)
                i_f_c = jnp.clip(i_f, 0, n_micro - 1)
                x_in = carry['fwd_buf'].astype(x_dtype)

                y, mb_loss = tick_fn(x_in, outer_p, local, i_f_c)
                loss = carry['loss'] + jnp.where(f_valid, mb_loss, 0.0)
                stash = carry['stash'].at[i_f_c % S].set(
                    jnp.where(f_valid, carry['fwd_buf'],
                              carry['stash'][i_f_c % S]))

                i_b = t - (2 * (pp - 1) - r)
                b_valid = jnp.logical_and(i_b >= 0, i_b < n_micro)
                i_b_c = jnp.clip(i_b, 0, n_micro - 1)
                x_st = stash[i_b_c % S].astype(x_dtype)

                _, vjp_fn = jax.vjp(
                    lambda x, op, lb: tick_fn(x, op, lb, i_b_c),
                    x_st, outer_p, local)
                cot_y = jnp.where(r == last,
                                  jnp.zeros(x_shape, x_dtype),
                                  carry['bwd_buf'].astype(x_dtype))
                cot_loss = jnp.where(r == last, 1.0 / n_micro, 0.0)
                cot_loss = jnp.where(b_valid, cot_loss, 0.0)
                cot_y = jnp.where(b_valid, cot_y,
                                  jnp.zeros(x_shape, x_dtype))
                dx, d_outer, d_blocks = vjp_fn(
                    (cot_y, cot_loss.astype(jnp.float32)))

                bmask = b_valid.astype(jnp.float32)
                g_outer = jax.tree_util.tree_map(
                    lambda acc, d2: acc + d2.astype(jnp.float32) * bmask,
                    carry['g_outer'], d_outer)
                g_blocks = jax.tree_util.tree_map(
                    lambda acc, d2: acc + d2.astype(jnp.float32) * bmask,
                    carry['g_blocks'], d_blocks)

                fwd_buf = lax.ppermute(y.astype(wire), axis, fwd_perm)
                bwd_buf = lax.ppermute(
                    (dx.astype(jnp.float32) * bmask), axis, bwd_perm)
                return dict(fwd_buf=fwd_buf, bwd_buf=bwd_buf, stash=stash,
                            g_outer=g_outer, g_blocks=g_blocks,
                            loss=loss), None

            carry, _ = lax.scan(tick, carry0, jnp.arange(T))
            loss = lax.psum(carry['loss'], axis) / n_micro
            g_outer = {n: lax.psum(a, axis)
                       for n, a in carry['g_outer'].items()}
            g_blocks = {n: a[None] for n, a in carry['g_blocks'].items()}
            return loss, g_outer, g_blocks

        in_specs = ({n: P(axis) for n in stacked},
                    {n: P() for n in outer_in}, P(), P(), P())
        out_specs = (P(), {n: P() for n in outer_in},
                     {n: P(axis) for n in stacked})
        fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, axis_names={axis},
                       check_vma=False)
        loss, g_outer, g_blocks = fn(stacked, outer_in, micro_ids,
                                     micro_lbl, key_in)
        if plan is not None:
            # grads leave pp-sharded like the stacked params entered;
            # the optimizer's ZeRO slice of a replicated-over-auto grad
            # is a plain dynamic-slice (efficient), unlike a guessed
            # tiled->tiled transition
            g_blocks = plan.constrain_stacked(g_blocks)
        grads = {}
        for n, a in g_outer.items():
            grads[n] = a.astype(params[n].dtype)
        for n, a in unstack_grads(g_blocks).items():
            grads[n] = a.astype(params[n].dtype)
        # params not touched by the pipeline (none normally) get zeros
        for n in params:
            if n not in grads:
                grads[n] = jnp.zeros_like(params[n])
        return loss, grads

    return pp_loss(params, base_key)


def _call_pre(model, pre_fn, pdict, ids_arr):
    """Run pre_fn with pdict bound into the live layers; returns array."""
    saved = _bind(model, pdict)
    try:
        out = pre_fn(Tensor(ids_arr))
        return out._data if isinstance(out, Tensor) else out
    finally:
        _restore(saved)


def _call_post(model, post_fn, pdict, x_arr, lbl_arr):
    saved = _bind(model, pdict)
    try:
        out = post_fn(Tensor(x_arr, stop_gradient=False), Tensor(lbl_arr))
        return out._data if isinstance(out, Tensor) else out
    finally:
        _restore(saved)


def _bind(model, pdict):
    pmap = dict(model.named_parameters())
    saved = []
    for n, arr in pdict.items():
        t = pmap.get(n)
        if t is None:
            continue
        saved.append((t, t._data))
        t._data = arr
    return saved


def _restore(saved):
    for t, arr in saved:
        t._data = arr
