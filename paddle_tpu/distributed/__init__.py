"""paddle.distributed parity surface (reference: python/paddle/distributed/)."""
from .env import (init_parallel_env, get_rank, get_world_size,  # noqa: F401
                  ParallelEnv, is_initialized)
from .parallel import DataParallel  # noqa: F401
from .collective import (ReduceOp, new_group, all_reduce, all_gather,  # noqa: F401
                         broadcast, reduce, scatter, alltoall, send, recv,
                         barrier, wait, split, get_group)
from .topology import (HybridCommunicateGroup, Group,  # noqa: F401
                       get_hybrid_communicate_group, default_mesh)
from . import fleet  # noqa: F401
from . import cloud_utils  # noqa: F401
from .fleet import utils  # noqa: F401
from . import meta_parallel  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import strategy  # noqa: F401
from . import checkpoint  # noqa: F401
from . import supervisor  # noqa: F401
from .supervisor import (TrainingSupervisor, ShardSupervisor,  # noqa: F401
                         ShardSpec, PushJournal, PreemptionWatcher,
                         ResumeCursor, Preempted, SupervisorAbort)

from .ps.dataset import MultiSlotDataset as QueueDataset  # noqa: F401
from .ps.dataset import MultiSlotDataset as InMemoryDataset  # noqa: F401
from .ps.dataset import BoxPSDataset  # noqa: F401
from .ps.embedding_service import (CountFilterEntry,  # noqa: F401
                                   ProbabilityEntry)
