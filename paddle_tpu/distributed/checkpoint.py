"""Distributed (sharded, async) checkpointing via orbax (SURVEY.md §5.4:
one sharded-checkpoint layer replaces io.py save ops + pickle paths + PS
table save).
"""
import os

import numpy as np
import jax

__all__ = ['save_checkpoint', 'load_checkpoint', 'AsyncCheckpointer']


def _to_arrays(state_dict):
    from ..framework.core import Tensor
    out = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            out[k] = v._data
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        else:
            out[k] = v
    return out


class AsyncCheckpointer:
    """Async sharded checkpoints (gang-scheduled ICI jobs need non-blocking
    saves — SURVEY.md §5.3 TPU equivalent)."""

    def __init__(self):
        try:
            import orbax.checkpoint as ocp
            self._ocp = ocp
            self._ckpt = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        except Exception:
            self._ocp = None
            self._ckpt = None

    def save(self, path, state_dict, force=True):
        state = _to_arrays(state_dict)
        path = os.path.abspath(path)
        if self._ckpt is not None:
            self._ckpt.save(path, state, force=force)
        else:
            from ..framework.io_save import save as _save
            _save(state, path + '.fallback.pdparams')

    def restore(self, path):
        path = os.path.abspath(path)
        if self._ckpt is not None:
            return self._ckpt.restore(path)
        from ..framework.io_save import load as _load
        return _load(path + '.fallback.pdparams')

    def wait_until_finished(self):
        if self._ckpt is not None:
            self._ckpt.wait_until_finished()


_CKPT = None


def _checkpointer():
    global _CKPT
    if _CKPT is None:
        _CKPT = AsyncCheckpointer()
    return _CKPT


def save_checkpoint(state_dict, path, asynchronous=True):
    ck = _checkpointer()
    ck.save(path, state_dict)
    if not asynchronous:
        ck.wait_until_finished()


def load_checkpoint(path):
    return _checkpointer().restore(path)
