"""Distributed (sharded, async) checkpointing via orbax (SURVEY.md §5.4:
one sharded-checkpoint layer replaces io.py save ops + pickle paths + PS
table save).

Fault tolerance: the non-orbax fallback rides framework.io_save, which
writes atomically (temp + fsync + rename) with a CRC32 manifest sidecar;
``CheckpointManager`` keeps N step-numbered snapshots and its
``restore_latest`` skips corrupt/partial ones, falling back to the newest
snapshot whose bytes still match its manifest — a pod preempted mid-save
costs one checkpoint interval, never the job. (Orbax's own save path is
already atomic: it writes to a temp dir and renames on commit.)
"""
import os
import re

import numpy as np
import jax

from ..framework.io_save import CheckpointCorruptError, verify_checkpoint

__all__ = ['save_checkpoint', 'load_checkpoint', 'AsyncCheckpointer',
           'CheckpointManager']


def _to_arrays(state_dict):
    from ..framework.core import Tensor
    out = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            out[k] = v._data
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        else:
            out[k] = v
    return out


class AsyncCheckpointer:
    """Async sharded checkpoints (gang-scheduled ICI jobs need non-blocking
    saves — SURVEY.md §5.3 TPU equivalent)."""

    def __init__(self):
        try:
            import orbax.checkpoint as ocp
            self._ocp = ocp
            self._ckpt = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        except Exception:
            self._ocp = None
            self._ckpt = None
        self._thread = None
        self._error = None

    def save(self, path, state_dict, force=True):
        state = _to_arrays(state_dict)
        path = os.path.abspath(path)
        if self._ckpt is not None:
            self._ckpt.save(path, state, force=force)
        else:
            # the fallback must match orbax's contract: save() returns
            # immediately and wait_until_finished() blocks — a blocking
            # fallback would stall the train step it is meant to overlap
            import threading
            from ..framework.io_save import save as _save
            self.wait_until_finished()

            def _write():
                try:
                    _save(state, path + '.fallback.pdparams')
                except Exception as e:
                    self._error = e
            self._thread = threading.Thread(target=_write, daemon=True)
            self._thread.start()

    def restore(self, path):
        path = os.path.abspath(path)
        if self._ckpt is not None:
            return self._ckpt.restore(path)
        self.wait_until_finished()
        from ..framework.io_save import load as _load
        return _load(path + '.fallback.pdparams')

    def wait_until_finished(self):
        if self._ckpt is not None:
            self._ckpt.wait_until_finished()
            return
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        err, self._error = self._error, None
        if err is not None:
            raise err


_CKPT = None


def _checkpointer():
    global _CKPT
    if _CKPT is None:
        _CKPT = AsyncCheckpointer()
    return _CKPT


def save_checkpoint(state_dict, path, asynchronous=True):
    ck = _checkpointer()
    ck.save(path, state_dict)
    if not asynchronous:
        ck.wait_until_finished()


def load_checkpoint(path):
    return _checkpointer().restore(path)


class CheckpointManager:
    """Step-numbered snapshots with integrity-checked restore.

    save(step, state) writes `step_<n>.ckpt` (atomic + manifest via
    io_save) and prunes beyond keep_last; restore_latest() walks the
    snapshots newest-first and returns the first one that passes its
    manifest check AND unpickles — a truncated latest snapshot (preempted
    writer) silently falls back to the previous epoch's state instead of
    killing the restart.
    """

    _STEP_RE = re.compile(r'^step_(\d+)\.ckpt$')

    def __init__(self, directory, keep_last=3):
        self.dir = directory
        self.keep_last = int(keep_last)
        if self.keep_last < 1:
            # keep_last=0 used to slice steps()[:-0] == [] and prune
            # NOTHING — the opposite of what the caller asked for.
            # There is no sane reading of "keep zero snapshots" for a
            # manager whose job is restoring the newest one: refuse.
            raise ValueError('keep_last must be >= 1 (got %d): the '
                             'current snapshot is always kept'
                             % self.keep_last)
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, step):
        return os.path.join(self.dir, 'step_%d.ckpt' % step)

    def steps(self):
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            m = self._STEP_RE.match(n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def save(self, step, state_dict):
        from ..framework import io_save
        io_save.save(state_dict, self._path(int(step)))
        for old in self.steps()[:-self.keep_last]:
            for p in (self._path(old),
                      io_save.manifest_path(self._path(old))):
                try:
                    os.remove(p)
                except OSError:
                    pass

    def restore_latest(self):
        """(step, state) from the newest valid snapshot, or (None, None).
        Corrupt/partial snapshots are skipped, not deleted — forensics
        beat tidiness when a job is recovering from preemption."""
        from ..framework import io_save
        for step in reversed(self.steps()):
            path = self._path(step)
            # require_manifest: manager snapshots are always written
            # through io_save.save, so a data file with no manifest is a
            # writer that died between rename and manifest — torn, skip
            if not verify_checkpoint(path, require_manifest=True):
                continue
            try:
                return step, io_save.load(path)
            except Exception:
                # anything unloadable (torn pickle, missing file between
                # verify and load) means "try the next-older snapshot"
                continue
        return None, None
