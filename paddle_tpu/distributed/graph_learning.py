"""GNN training bridge over the distributed graph engine.

The reference fork's marquee feature is a brpc-sharded graph store feeding
GNN trainers (reference: paddle/fluid/distributed/table/
common_graph_table.cc + graph_py_service.cc; consumed there by PGL-style
samplers). This module is the TPU-native consumption path: sample fixed
fan-out neighborhoods through GraphPyClient, pad to static shapes (XLA
wants static), and aggregate with a GraphSAGE layer whose batch is one
fused device program.
"""
import numpy as np

from .. import nn
from ..framework.core import Tensor

__all__ = ['neighbor_sample', 'gather_features', 'GraphSageLayer',
           'sample_and_gather']


def neighbor_sample(client, etype, ids, fanout):
    """Fixed fan-out neighbor sample with self-fallback padding.

    Returns int64 [len(ids), fanout]: the engine pads missing neighbors
    with -1 (isolated node or fanout > degree); those slots are replaced
    by the node itself so downstream gathers stay in-bounds and the mean
    aggregator degrades to self-features — static shapes, no masks.
    """
    ids = np.asarray(ids, np.int64)
    neigh = client.sample_neighbors(etype, ids, fanout)
    self_col = np.broadcast_to(ids[:, None], neigh.shape)
    return np.where(neigh < 0, self_col, neigh)


def gather_features(client, etype, ids, dim):
    """Features for a (possibly shaped) id array: [*, dim] float32."""
    ids = np.asarray(ids, np.int64)
    flat = client.get_node_feat(etype, ids.reshape(-1), dim)
    return flat.reshape(ids.shape + (dim,))


def sample_and_gather(client, etype, batch_ids, fanouts, dim):
    """Multi-hop subgraph batch: returns (self_feat, [hop1_feat, ...])
    where hop k has shape [B, fanout_1, ..., fanout_k, dim]. The sampling
    rides the service (host side); the returned arrays are ready for one
    jitted forward."""
    ids = np.asarray(batch_ids, np.int64)
    feats = [gather_features(client, etype, ids, dim)]
    frontier = ids
    for f in fanouts:
        frontier = neighbor_sample(client, etype, frontier.reshape(-1),
                                   f).reshape(frontier.shape + (f,))
        feats.append(gather_features(client, etype, frontier, dim))
    return feats[0], feats[1:]


class GraphSageLayer(nn.Layer):
    """GraphSAGE mean aggregator (Hamilton et al.; the PGL layer the
    reference's graph engine feeds): h = act(W [self || mean(neigh)])."""

    def __init__(self, in_dim, out_dim, act='relu'):
        super().__init__()
        self.linear = nn.Linear(2 * in_dim, out_dim)
        self._act = act

    def forward(self, self_feat, neigh_feat):
        from .. import tensor as T
        agg = T.mean(neigh_feat, axis=-2)
        h = self.linear(T.concat([self_feat, agg], axis=-1))
        if self._act:
            h = getattr(nn.functional, self._act)(h)
        return h
