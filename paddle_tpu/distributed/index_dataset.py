"""Tree-based index for TDM-style retrieval training.

Parity: paddle/fluid/distributed/index_dataset/ (index_wrapper.h TreeIndex
+ IndexWrapper, index_sampler.h LayerWiseSampler/BeamSearchSampler,
python/paddle/distributed/fleet/dataset/index_dataset.py). The protobuf
node storage collapses to numpy arrays; codes use the max-heap layout
(children of code c under branch b are b*c+1 .. b*c+b), same as TDM.
"""
import numpy as np

__all__ = ['TreeIndex', 'IndexWrapper', 'LayerWiseSampler',
           'BeamSearchSampler']


class TreeIndex:
    """A complete b-ary tree over item ids.

    Leaves hold item ids; internal nodes are virtual categories. Node
    `code` is the heap position (root=0); `id` of a leaf is the item id,
    internal nodes get synthetic ids above max_item_id.
    """

    def __init__(self, name='tree', branch=2):
        self.name = name
        self.branch = branch
        self._code_to_id = {}
        self._id_to_code = {}
        self._height = 0

    # -- construction --------------------------------------------------------
    @classmethod
    def from_items(cls, item_ids, name='tree', branch=2):
        """Build a balanced tree with the given leaf items (offline
        clustering in the reference; here items are placed in given order,
        which callers can pre-sort by embedding similarity)."""
        t = cls(name=name, branch=branch)
        n = len(item_ids)
        height = 1
        cap = 1
        while cap < n:
            cap *= branch
            height += 1
        t._height = height
        first_leaf = (branch ** (height - 1) - 1) // (branch - 1) \
            if branch > 1 else height - 1
        next_internal = max(item_ids) + 1 if len(item_ids) else 0
        for i, item in enumerate(item_ids):
            code = first_leaf + i
            t._code_to_id[code] = int(item)
            t._id_to_code[int(item)] = code
        # materialize ancestors of used leaves
        used = sorted(t._code_to_id)
        seen = set(used)
        for code in used:
            c = code
            while c > 0:
                c = (c - 1) // branch
                if c in seen:
                    break
                seen.add(c)
                t._code_to_id[c] = next_internal
                t._id_to_code[next_internal] = c
                next_internal += 1
        return t

    def save(self, path):
        arr = np.asarray(sorted(self._code_to_id.items()), np.int64)
        np.savez(path, codes_ids=arr, branch=self.branch,
                 height=self._height)

    @classmethod
    def load(cls, path, name='tree'):
        data = np.load(path if path.endswith('.npz') else path + '.npz')
        t = cls(name=name, branch=int(data['branch']))
        t._height = int(data['height'])
        for code, nid in data['codes_ids']:
            t._code_to_id[int(code)] = int(nid)
            t._id_to_code[int(nid)] = int(code)
        return t

    # -- queries (index_wrapper.h surface) -----------------------------------
    def total_node_nums(self):
        return len(self._code_to_id)

    def height(self):
        return self._height

    def branch_size(self):
        return self.branch

    def _level_of(self, code):
        level, c = 0, code
        while c > 0:
            c = (c - 1) // self.branch
            level += 1
        return level

    def get_all_leafs(self):
        first_leaf = (self.branch ** (self._height - 1) - 1) // \
            (self.branch - 1) if self.branch > 1 else self._height - 1
        return [nid for code, nid in sorted(self._code_to_id.items())
                if code >= first_leaf]

    def get_nodes(self, codes):
        return [self._code_to_id.get(int(c), -1) for c in codes]

    def get_layer_codes(self, level):
        return [c for c in sorted(self._code_to_id)
                if self._level_of(c) == level]

    def get_travel_codes(self, item_id):
        """Leaf→root path codes for an item (reference get_travel_codes)."""
        code = self._id_to_code[int(item_id)]
        out = [code]
        while code > 0:
            code = (code - 1) // self.branch
            out.append(code)
        return out

    def get_travel_path(self, child, ancestor):
        out = []
        while child > ancestor:
            out.append(child)
            child = (child - 1) // self.branch
        return out

    def get_ancestor_codes(self, item_ids, level):
        out = []
        for i in item_ids:
            code = self._id_to_code[int(i)]
            while self._level_of(code) > level:
                code = (code - 1) // self.branch
            out.append(code)
        return out

    def get_children_codes(self, code, level=None):
        lo = code * self.branch + 1
        kids = [lo + i for i in range(self.branch)]
        return [k for k in kids if k in self._code_to_id]

    def get_pi_relation(self, item_ids, level):
        """item id -> its ancestor code at `level`."""
        return {int(i): a for i, a in
                zip(item_ids, self.get_ancestor_codes(item_ids, level))}


class IndexWrapper:
    """Named registry of tree indexes (index_wrapper.h IndexWrapper)."""

    def __init__(self):
        self._trees = {}

    def insert_tree_index(self, name, tree_path):
        self._trees[name] = TreeIndex.load(tree_path, name=name)

    def add_tree_index(self, name, tree):
        self._trees[name] = tree

    def get_tree_index(self, name):
        if name not in self._trees:
            raise KeyError('tree index %r not registered' % name)
        return self._trees[name]

    def clear_tree(self):
        self._trees.clear()


class LayerWiseSampler:
    """TDM layer-wise sampling (index_sampler.h LayerWiseSampler): for each
    (user, target item) pair emit per-layer (positive ancestor, sampled
    negatives-in-layer) training rows, root layer excluded."""

    def __init__(self, tree, layer_sample_counts=None, start_sample_layer=1,
                 seed=0):
        self.tree = tree
        self.start = start_sample_layer
        self.counts = layer_sample_counts
        self.rng = np.random.RandomState(seed)
        # per-level code lists precomputed once: sample() runs per batch
        # and must not rescan the whole tree per (item, level)
        self._layers = [tree.get_layer_codes(lvl)
                        for lvl in range(tree.height())]

    def sample(self, user_inputs, target_ids, with_hierarchy=False):
        rows = []
        height = self.tree.height()
        for user, item in zip(user_inputs, target_ids):
            codes = self.tree.get_travel_codes(item)
            # codes: leaf .. root; layer index = height-1 .. 0
            for code in codes[:-1]:
                level = self.tree._level_of(code)
                if level < self.start:
                    continue
                layer = self._layers[level]
                k = (self.counts[level - self.start]
                     if self.counts and level - self.start < len(self.counts)
                     else min(4, max(len(layer) - 1, 1)))
                negs = [c for c in layer if c != code]
                if negs:
                    sel = self.rng.choice(len(negs),
                                          size=min(k, len(negs)),
                                          replace=False)
                    neg_codes = [negs[int(s)] for s in sel]
                else:
                    neg_codes = []
                rows.append((list(user), self.tree._code_to_id[code], 1))
                for nc in neg_codes:
                    rows.append((list(user), self.tree._code_to_id[nc], 0))
        return rows


class BeamSearchSampler:
    """Beam retrieval over the tree with a user-supplied scorer
    (index_sampler.h BeamSearchSampler): at each level keep the best
    `beam_size` children by score(user, node_id)."""

    def __init__(self, tree, beam_size=2):
        self.tree = tree
        self.beam = beam_size

    def sample(self, user, score_fn):
        frontier = [0]
        height = self.tree.height()
        for level in range(height - 1):
            kids = []
            for code in frontier:
                kids += self.tree.get_children_codes(code)
            if not kids:
                break
            ids = self.tree.get_nodes(kids)
            scores = np.asarray([score_fn(user, nid) for nid in ids])
            top = np.argsort(-scores)[:self.beam]
            frontier = [kids[int(i)] for i in top]
        return self.tree.get_nodes(frontier)
