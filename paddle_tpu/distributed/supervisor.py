"""Elastic training supervisor: preemption-aware checkpoints, exactly-once
resume, and supervised recovery of stateful PS/graph shards.

Three cooperating pieces (ISSUE 14 tentpole):

``TrainingSupervisor``
    Rides inside ``Model.fit(supervisor=...)``. Writes periodic
    checkpoints through ``CheckpointManager`` (atomic + manifest via
    io_save) that capture params, optimizer state AND a full
    ``ResumeCursor`` — epoch, step, global step, plus the RNG streams
    (global numpy and ``framework.random``) at BOTH the epoch start and
    the checkpoint instant. The epoch-start capture replays the data
    loader's shuffle (``RandomSampler`` draws its permutation from the
    global numpy RNG when the iterator is built); the checkpoint-time
    capture re-seats compute RNG mid-epoch. Together they make a resumed
    run bit-identical to an uninterrupted one. A ``PreemptionWatcher``
    turns SIGTERM into an urgent checkpoint at the next step boundary
    followed by a clean ``Preempted`` stop.

``PushJournal``
    Client-side exactly-once write journal. Every journaled PS/graph
    push records an entry and gets a monotonically increasing ``seq``;
    servers remember the highest applied seq per ``client_id`` and
    drop duplicates (``journal_apply`` in embedding_service /
    graph_service), so a retry or a post-recovery replay applies each
    write at most once. Entries are retained until a snapshot barrier
    vouches for them (``trim``).

``ShardSupervisor``
    Heartbeats stateful shards (EmbeddingServer / GraphPyServer) over
    their ``ping`` op, snapshots them at checkpoint barriers, and walks
    an escalation ladder when a shard goes quiet: restart (bounded
    attempts with backoff) -> restore newest valid snapshot + replay
    client journals -> abort with a flight-recorder dump and
    ``SupervisorAbort``. Recovery runs under a ``supervisor.recover``
    span and feeds the ``supervisor_*`` metric families (MTTR histogram,
    restart/escalation counters, shards-alive gauge).
"""
import os
import re
import signal
import threading
import time

import numpy as np

from ..framework import random as _random
from ..framework.io_save import manifest_path, verify_checkpoint
from ..monitor import tracing as _tracing
from ..monitor.registry import default_registry
from ..monitor.telemetry import record_supervisor_schema
from . import resilience
from .checkpoint import CheckpointManager, _to_arrays

__all__ = ['Preempted', 'SupervisorAbort', 'ResumeCursor',
           'PreemptionWatcher', 'PushJournal', 'TrainingSupervisor',
           'ShardSpec', 'ShardSupervisor']


class Preempted(Exception):
    """Raised out of the training step loop after a preemption notice was
    honored with an urgent checkpoint; ``Model.fit`` treats it as a clean
    stop (``stop_training``), not an error."""


class SupervisorAbort(RuntimeError):
    """The escalation ladder ran out: a shard could not be restarted or
    restored. The flight recorder has already dumped by the time this
    propagates."""


class ResumeCursor:
    """Deterministic restart coordinates for a ``Model.fit`` run.

    ``epoch``/``step``/``global_step`` count COMPLETED work: the cursor
    says "epoch e, first `step` batches done, `global_step` batches done
    overall". ``epoch_rng`` is the RNG capture from the top of epoch e
    (before the loader iterator was built — replaying it re-draws the
    identical shuffle permutation); ``rng`` is the capture at the
    checkpoint instant (re-seated after fast-forwarding the loader).
    ``ingest`` is the attached streaming pipeline's ``IngestCursor``
    state dict (exact shard/record/shuffle position) when training reads
    from ``data.IngestPipeline`` — resume then SEEKS the stream instead
    of draining the trained prefix batch by batch.
    """

    def __init__(self, epoch=0, step=0, global_step=0, epoch_rng=None,
                 rng=None, ingest=None):
        self.epoch = int(epoch)
        self.step = int(step)
        self.global_step = int(global_step)
        self.epoch_rng = epoch_rng
        self.rng = rng
        self.ingest = ingest

    @staticmethod
    def capture_rng():
        """Both host-side RNG streams training consumes: the global
        numpy RNG (data-loader shuffles, numpy-based init) and the
        framework.random generator key (dropout etc. via next_key)."""
        return {'numpy': np.random.get_state(),
                'paddle': np.asarray(_random.get_rng_state())}

    @staticmethod
    def restore_rng(state):
        np.random.set_state(state['numpy'])
        _random.set_rng_state(np.asarray(state['paddle']))

    def to_state(self):
        return {'epoch': self.epoch, 'step': self.step,
                'global_step': self.global_step,
                'epoch_rng': self.epoch_rng, 'rng': self.rng,
                'ingest': self.ingest}

    @classmethod
    def from_state(cls, state):
        return cls(epoch=state['epoch'], step=state['step'],
                   global_step=state['global_step'],
                   epoch_rng=state.get('epoch_rng'),
                   rng=state.get('rng'),
                   ingest=state.get('ingest'))

    def __repr__(self):
        return ('ResumeCursor(epoch=%d, step=%d, global_step=%d)'
                % (self.epoch, self.step, self.global_step))


class PreemptionWatcher:
    """Turns a preemption notice (SIGTERM by default, or a programmatic
    ``request()`` — e.g. a cloud metadata poller) into a flag the
    supervisor checks at every step boundary. Signal handlers only set
    an Event, so the notice is async-signal-safe; all checkpoint work
    happens on the training thread."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self._flag = threading.Event()
        self._signals = tuple(signals)
        self._prev = {}

    def install(self):
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        return self

    def uninstall(self):
        prev, self._prev = self._prev, {}
        for sig, handler in prev.items():
            signal.signal(sig, handler)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()

    def _on_signal(self, signum, frame):
        self._flag.set()

    def request(self):
        """Programmatic preemption notice (tests, metadata watchers)."""
        self._flag.set()

    def requested(self):
        return self._flag.is_set()

    def clear(self):
        self._flag.clear()


class PushJournal:
    """Client-side write journal backing exactly-once PS/graph pushes.

    Hand one to ``EmbeddingClient(journal=...)`` / ``GraphPyClient
    (journal=...)``: every push records its payload here first and is
    sent tagged ``(client_id, seq)``. Servers keep the highest applied
    seq per client and drop anything at or below it, so retries and
    post-recovery replays are idempotent end to end. ``trim()`` runs at
    snapshot barriers — once a server snapshot vouches for a prefix of
    the journal, those entries can never need replaying again.
    """

    def __init__(self, client_id, registry=None):
        self.client_id = str(client_id)
        self._entries = []            # [(seq, entry)] oldest-first
        self._seq = 0
        self._lock = threading.Lock()
        fams = record_supervisor_schema(
            registry if registry is not None else default_registry())
        self._m_replays = fams['supervisor_journal_replays_total']
        self._m_dedup = fams['supervisor_journal_dedup_hits_total']
        self.replayed = 0
        self.dedup_hits = 0

    @property
    def seq(self):
        """Highest seq handed out so far."""
        with self._lock:
            return self._seq

    def record(self, entry):
        """Append `entry` and return its seq (first seq is 1)."""
        with self._lock:
            self._seq += 1
            self._entries.append((self._seq, entry))
            return self._seq

    def entries(self):
        """Untrimmed [(seq, entry)] oldest-first — the replay set."""
        with self._lock:
            return list(self._entries)

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def trim(self, up_to_seq=None):
        """Drop entries with seq <= up_to_seq (default: everything
        recorded so far). Call ONLY at a snapshot barrier with no pushes
        in flight — a trimmed entry is unrecoverable if no snapshot
        covers it."""
        with self._lock:
            cut = self._seq if up_to_seq is None else int(up_to_seq)
            self._entries = [(s, e) for s, e in self._entries if s > cut]

    def note_replay(self):
        self.replayed += 1
        self._m_replays.inc()

    def note_dedup(self):
        """A journaled push came back ``applied=False`` — the server had
        already applied this seq (retry of an acked-but-lost reply, or a
        replay overlapping the snapshot)."""
        self.dedup_hits += 1
        self._m_dedup.inc()


class TrainingSupervisor:
    """Checkpoint/resume driver for ``Model.fit(supervisor=...)``.

    Lifecycle inside fit: ``restore(model)`` before the epoch loop (loads
    the newest valid checkpoint and yields the cursor), ``begin_epoch``
    at each epoch top BEFORE the loader iterator is built,
    ``fast_forward(data_iter)`` right after building the resumed epoch's
    iterator, ``on_step`` after every completed step (may write a
    periodic checkpoint, or honor a preemption notice by writing an
    urgent one and raising ``Preempted``).
    """

    def __init__(self, directory, save_every_steps=0, keep_last=3,
                 watcher=None, shard_supervisor=None,
                 snapshot_shards=True, registry=None):
        self.manager = CheckpointManager(directory, keep_last=keep_last)
        self.save_every_steps = int(save_every_steps)
        self.watcher = watcher
        self.shards = shard_supervisor
        self.snapshot_shards = bool(snapshot_shards)
        fams = record_supervisor_schema(
            registry if registry is not None else default_registry())
        self._m_ckpts = fams['supervisor_checkpoints_total']
        self._m_preempt = fams['supervisor_preemptions_total']
        self._epoch_rng = None
        self._cursor = None           # pending resume cursor
        self._pipeline = None         # attached data.IngestPipeline
        self.last_saved_step = None

    def attach_pipeline(self, pipeline):
        """Register the streaming pipeline feeding the supervised fit:
        checkpoints then embed its exact stream cursor, and resume SEEKS
        the pipeline (shard/record/shuffle-window position) instead of
        draining the trained prefix through ``fast_forward``."""
        self._pipeline = pipeline
        return pipeline

    # -- checkpoint side ----------------------------------------------------
    def _state_dict(self, model, cursor):
        state = {'network': _to_arrays(dict(model.network.state_dict())),
                 'cursor': cursor.to_state()}
        if model._optimizer is not None:
            state['optimizer'] = _to_arrays(model._optimizer.state_dict())
        return state

    def save(self, model, epoch, step, global_step, kind='periodic'):
        """Write a checkpoint capturing model + optimizer + cursor. The
        cursor's RNG pair is captured HERE — at a step boundary — so a
        resumed run re-enters the exact RNG stream."""
        ingest = None
        if self._pipeline is not None:
            ingest = self._pipeline.cursor().to_state()
        cursor = ResumeCursor(epoch=epoch, step=step,
                              global_step=global_step,
                              epoch_rng=self._epoch_rng,
                              rng=ResumeCursor.capture_rng(),
                              ingest=ingest)
        self.manager.save(global_step, self._state_dict(model, cursor))
        self._m_ckpts.labels(kind).inc()
        self.last_saved_step = global_step
        if self.shards is not None and self.snapshot_shards \
                and kind == 'periodic':
            # snapshot barrier: fit() is between steps, no pushes are in
            # flight, so shard snapshots vouch for the whole journal and
            # the journals trim. Urgent (preemption) saves skip this —
            # the shards outlive this pod and keep their own state.
            self.shards.snapshot_all()
        return cursor

    # -- resume side --------------------------------------------------------
    def restore(self, model):
        """Load the newest valid checkpoint into `model` and stage its
        cursor for ``begin_epoch``/``fast_forward``. Returns the cursor,
        or None for a cold start."""
        step, state = self.manager.restore_latest()
        if state is None:
            self._cursor = None
            return None
        model.network.set_state_dict(state['network'])
        if model._optimizer is not None and 'optimizer' in state:
            model._optimizer.set_state_dict(state['optimizer'])
        self._cursor = ResumeCursor.from_state(state['cursor'])
        if self._cursor.ingest is not None and self._pipeline is not None:
            # stage the seek NOW: the pipeline's next __iter__ resumes
            # at the exact stream position, so fast_forward won't drain
            self._pipeline.restore(self._cursor.ingest)
        return self._cursor

    def begin_epoch(self, epoch):
        """Epoch top, BEFORE ``iter(train_loader)``. On the resumed
        epoch this re-seats the epoch-start RNG so the loader re-draws
        the interrupted epoch's exact permutation; on any other epoch it
        captures the current state for future cursors."""
        if self._cursor is not None and epoch == self._cursor.epoch:
            ResumeCursor.restore_rng(self._cursor.epoch_rng)
            self._epoch_rng = self._cursor.epoch_rng
        else:
            self._epoch_rng = ResumeCursor.capture_rng()

    def fast_forward(self, data_iter):
        """Drain the already-trained prefix of the resumed epoch from
        `data_iter`, then seat the checkpoint-instant RNG. Returns the
        number of batches skipped (the resumed epoch's starting step)."""
        cursor, self._cursor = self._cursor, None
        if cursor is None:
            return 0
        if cursor.ingest is None or self._pipeline is None:
            # plain loaders re-shuffle from epoch_rng, so the trained
            # prefix must be drained to reach the right position
            for _ in range(cursor.step):
                next(data_iter)
        # pipelines were staged in restore(): their iterator is already
        # seeking to cursor.ingest — nothing to drain
        if cursor.rng is not None:
            ResumeCursor.restore_rng(cursor.rng)
        return cursor.step

    def on_step(self, model, epoch, step, global_step):
        """After every completed step. Raises ``Preempted`` after the
        urgent checkpoint when a preemption notice is pending."""
        if self.watcher is not None and self.watcher.requested():
            self.watcher.clear()
            self.save(model, epoch, step, global_step, kind='urgent')
            self._m_preempt.inc()
            raise Preempted('preemption honored at epoch %d step %d '
                            '(global step %d): urgent checkpoint written'
                            % (epoch, step, global_step))
        if self.save_every_steps and \
                global_step % self.save_every_steps == 0:
            self.save(model, epoch, step, global_step, kind='periodic')


class ShardSpec:
    """One supervised stateful shard.

    restart: nullary callable that rebinds the shard's service (e.g.
    constructs a fresh EmbeddingServer on the same port). May return a
    new ``endpoint`` string if the rebind moved; returning None keeps
    the current one. clients: client objects exposing
    ``replay_journal()`` and ``.journal`` (EmbeddingClient /
    GraphPyClient built with a PushJournal) — replayed after a restore,
    trimmed at snapshot barriers.
    """

    def __init__(self, name, endpoint, role='ps', restart=None,
                 snapshot_dir=None, clients=(), keep_snapshots=2):
        self.name = str(name)
        self.endpoint = endpoint
        self.role = str(role)
        self.restart = restart
        self.snapshot_dir = snapshot_dir
        self.clients = tuple(clients)
        self.keep_snapshots = max(int(keep_snapshots), 1)


class _ShardState:
    def __init__(self, spec):
        self.spec = spec
        self.misses = 0
        self.restarts = 0
        self.snap_seq = 0
        self.alive = True


class ShardSupervisor:
    """Liveness + recovery driver for PS/graph shards.

    ``poll()`` runs one synchronous heartbeat round (tests drive this
    directly); ``start(interval)`` runs it on a background thread. A
    shard that misses ``miss_threshold`` consecutive pings enters
    ``recover()``: restart with backoff (``restart_budget`` attempts),
    then restore the newest manifest-valid snapshot and replay every
    client journal, else abort — flight dump + ``SupervisorAbort``.
    """

    _SNAP_RE = re.compile(r'_snap_(\d+)\.ckpt$')

    def __init__(self, miss_threshold=2, restart_budget=3, backoff=None,
                 ping_timeout=1.0, op_timeout=30.0, registry=None,
                 clock=time.monotonic):
        self.miss_threshold = int(miss_threshold)
        self.restart_budget = int(restart_budget)
        self._backoff = backoff if backoff is not None else \
            resilience.RetryPolicy(base_delay=0.05, max_delay=1.0,
                                   jitter=0.0)
        self.ping_timeout = float(ping_timeout)
        self.op_timeout = float(op_timeout)
        self._clock = clock
        fams = record_supervisor_schema(
            registry if registry is not None else default_registry())
        self._m_restarts = fams['supervisor_restarts_total']
        self._m_recover = fams['supervisor_recover_seconds']
        self._m_escalations = fams['supervisor_escalations_total']
        self._m_alive = fams['supervisor_shards_alive']
        self._shards = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread = None
        self.abort_error = None

    # -- membership ---------------------------------------------------------
    def add_shard(self, spec):
        with self._lock:
            self._shards[spec.name] = _ShardState(spec)
        return spec

    def shard(self, name):
        return self._shards[name].spec

    def alive(self, name):
        return self._shards[name].alive

    # -- rpc helpers --------------------------------------------------------
    def _ping(self, spec):
        try:
            out = resilience.call_once(spec.endpoint, {'op': 'ping'},
                                       timeout=self.ping_timeout,
                                       connect_timeout=self.ping_timeout)
            return isinstance(out, dict) and bool(out.get('ok'))
        except Exception:
            return False

    # -- snapshot barrier ---------------------------------------------------
    def _snap_path(self, spec, seq):
        return os.path.join(spec.snapshot_dir,
                            '%s_snap_%06d.ckpt' % (spec.name, seq))

    def _snapshots(self, spec):
        """[(seq, path)] newest-first for this shard."""
        out = []
        try:
            names = os.listdir(spec.snapshot_dir)
        except (OSError, TypeError):
            return out
        prefix = spec.name + '_snap_'
        for n in names:
            m = self._SNAP_RE.search(n)
            if m and n.startswith(prefix):
                out.append((int(m.group(1)),
                            os.path.join(spec.snapshot_dir, n)))
        return sorted(out, reverse=True)

    def snapshot_all(self):
        """Snapshot every shard that has a snapshot_dir, then trim the
        client journals. MUST run at a barrier (no pushes in flight):
        the journal cut is taken before the snapshot RPCs, so every
        trimmed entry was already applied server-side and is covered by
        the snapshot. Any snapshot failure propagates BEFORE trimming —
        journals are never cut without a snapshot vouching for them."""
        with self._lock:
            cuts, seen = [], set()
            for st in self._shards.values():
                for c in st.spec.clients:
                    j = getattr(c, 'journal', None)
                    if j is not None and id(j) not in seen:
                        seen.add(id(j))
                        cuts.append((j, j.seq))
            paths = {}
            for st in self._shards.values():
                spec = st.spec
                if spec.snapshot_dir is None:
                    continue
                os.makedirs(spec.snapshot_dir, exist_ok=True)
                st.snap_seq += 1
                path = self._snap_path(spec, st.snap_seq)
                resilience.call_once(spec.endpoint,
                                     {'op': 'snapshot', 'path': path},
                                     timeout=self.op_timeout)
                paths[spec.name] = path
                for _, old in self._snapshots(spec)[spec.keep_snapshots:]:
                    for p in (old, manifest_path(old)):
                        try:
                            os.remove(p)
                        except OSError:
                            pass
            for j, cut in cuts:
                j.trim(cut)
            return paths

    # -- heartbeat ----------------------------------------------------------
    def poll(self):
        """One heartbeat round. Recovers (synchronously) any shard past
        the miss threshold. Returns {name: alive}."""
        with self._lock:
            out = {}
            for name, st in self._shards.items():
                if self._ping(st.spec):
                    st.misses = 0
                    st.alive = True
                else:
                    st.misses += 1
                    st.alive = False
                    if st.misses >= self.miss_threshold:
                        self.recover(name)
                out[name] = st.alive
            self._m_alive.set(sum(1 for a in out.values() if a))
            return out

    def start(self, interval=0.5):
        """Heartbeat on a background thread; a SupervisorAbort lands in
        ``self.abort_error`` and stops the loop."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()

            def _loop():
                while not self._stop.wait(interval):
                    try:
                        self.poll()
                    except SupervisorAbort as e:
                        self.abort_error = e
                        break
            self._thread = threading.Thread(target=_loop, daemon=True,
                                            name='shard-supervisor')
            self._thread.start()

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    # -- escalation ladder --------------------------------------------------
    def recover(self, name):
        """restart -> restore+replay -> abort. Returns MTTR seconds."""
        st = self._shards[name]
        spec = st.spec
        t0 = self._clock()
        tr = _tracing.default_tracer()
        with tr.start_span('supervisor.recover',
                           tags={'shard': name, 'role': spec.role}) as span:
            try:
                self._restart_stage(st, span)
                self._restore_stage(st, span)
            except SupervisorAbort:
                st.alive = False
                self._m_escalations.labels('abort').inc()
                if span:
                    span.set_tag('outcome', 'abort')
                tr.recorder.maybe_dump('supervisor_abort')
                raise
            st.alive = True
            st.misses = 0
            mttr = self._clock() - t0
            self._m_recover.observe(mttr)
            self._m_restarts.labels(spec.role).inc()
            if span:
                span.set_tag('outcome', 'recovered')
                span.set_tag('mttr_s', round(mttr, 6))
        tr.recorder.maybe_dump('supervisor_recover')
        return mttr

    def _restart_stage(self, st, span):
        spec = st.spec
        self._m_escalations.labels('restart').inc()
        last_err = None
        for attempt in range(1, self.restart_budget + 1):
            if spec.restart is not None:
                try:
                    new_ep = spec.restart()
                    if new_ep is not None:
                        spec.endpoint = new_ep
                except Exception as e:
                    last_err = e
                    time.sleep(self._backoff.backoff(attempt))
                    continue
            if self._ping(spec):
                st.restarts += 1
                if span:
                    span.add_event('restarted', attempt=attempt)
                return
            time.sleep(self._backoff.backoff(attempt))
        raise SupervisorAbort(
            'shard %r did not come back after %d restart attempts%s'
            % (spec.name, self.restart_budget,
               ': last error %s' % last_err if last_err else ''))

    def _restore_stage(self, st, span):
        """A restarted shard is blank: restore the newest manifest-valid
        snapshot, then replay every client journal — the journaled seqs
        make the replay exactly-once even where it overlaps the
        snapshot (the server dedups anything the snapshot covered)."""
        spec = st.spec
        self._m_escalations.labels('restore').inc()
        snap = None
        for _, path in self._snapshots(spec):
            # torn snapshots (writer died pre-manifest) are skipped, not
            # trusted — same rule as CheckpointManager.restore_latest
            if verify_checkpoint(path, require_manifest=True):
                snap = path
                break
        try:
            if snap is not None:
                resilience.call_once(spec.endpoint,
                                     {'op': 'restore', 'path': snap},
                                     timeout=self.op_timeout)
                if span:
                    span.add_event('restored',
                                   snapshot=os.path.basename(snap))
            replayed = dedup = 0
            for client in spec.clients:
                r, d = client.replay_journal()
                replayed += r
                dedup += d
            if span and (replayed or dedup):
                span.add_event('journal_replayed', entries=replayed,
                               dedup_hits=dedup)
        except Exception as e:
            raise SupervisorAbort('shard %r restore/replay failed: %s'
                                  % (spec.name, e))
