"""Shared RPC resilience layer (reference: the brpc PS/graph services —
brpc_ps_client.cc retry/timeout knobs, graph_brpc_client reconnect — made
a first-class subsystem instead of per-callsite copy-paste).

Three pieces compose:

- ``RetryPolicy``: exponential backoff with jitter, a max-attempt cap,
  and retryable-exception classification (connection resets / timeouts
  retry; protocol and application errors never do).
- ``Deadline``: an absolute wall-clock budget shared across every
  attempt of a call (and across a multi-shard fan-out) — retries must
  tighten, never extend, the caller's wait.
- ``ResilientChannel``: one endpoint's framed-message connection with
  socket timeouts, transparent reconnect-and-retry for idempotent ops,
  and a half-open circuit breaker so a dead shard fails fast instead of
  burning a full backoff ladder per call.

Fault injection for tests rides through ``_fire()``: the hooks list is
empty (zero cost) until paddle_tpu.testing.chaos installs injectors.
"""
import errno
import random
import socket
import struct
import threading
import time

from ..monitor import default_registry as _monitor_registry
from ..monitor import tracing as _tracing

__all__ = ['RetryPolicy', 'Deadline', 'CircuitBreaker', 'ResilientChannel',
           'RpcError', 'RetryableError', 'DeadlineExceeded',
           'CircuitOpenError', 'FrameError', 'FrameTooLargeError',
           'FrameDecodeError', 'fire_fault_points', 'DEFAULT_CALL_TIMEOUT',
           'DEFAULT_CONNECT_TIMEOUT']

DEFAULT_CALL_TIMEOUT = 30.0      # per-attempt send+recv budget (seconds)
DEFAULT_CONNECT_TIMEOUT = 5.0


# -- observability (paddle_tpu/monitor) -------------------------------------
# Families bind once at import via the single-source schema table
# (monitor/telemetry.py RPC_FAMILIES — the same table dryrun_registry
# and the committed schema baseline register); channels/breakers cache
# their labeled children at construction, so the per-call cost is one
# enabled-flag check per event (and nothing at all for events that
# don't happen).
from ..monitor.telemetry import record_rpc_schema as _record_rpc_schema

_FAMS = _record_rpc_schema(_monitor_registry())
_M_ATTEMPTS = _FAMS['rpc_attempts_total']
_M_FAILURES = _FAMS['rpc_attempt_failures_total']
_M_BACKOFF = _FAMS['rpc_backoff_seconds_total']
_M_DEADLINE = _FAMS['rpc_deadline_expired_total']
_M_CIRCUIT_REJECT = _FAMS['rpc_circuit_open_total']
_M_TRANSITIONS = _FAMS['rpc_breaker_transitions_total']
_M_BREAKER_STATE = _FAMS['rpc_breaker_state']
_STATE_CODES = {'closed': 0, 'open': 1, 'half_open': 2}


# -- fault-injection hook points (see paddle_tpu/testing/chaos.py) ----------
# Each hook is `fn(point, endpoint)` where point is one of 'connect',
# 'send', 'recv'. Hooks may sleep (delay faults) or raise (drop faults).
_FAULT_HOOKS = []


def _fire(point, endpoint):
    for hook in list(_FAULT_HOOKS):
        hook(point, endpoint)


def fire_fault_points(point, endpoint):
    """Public hook-point trigger for subsystems that are not socket
    channels but still carry requests worth chaos-testing. The serving
    gateway's in-proc replicas fire 'send' before a submission and
    'recv' after each engine step, so chaos injectors (partition /
    drop_connections scoped to the replica's endpoint string) apply to
    them exactly as they do to a ResilientChannel: a partitioned replica
    can neither accept new work nor deliver tokens."""
    _fire(point, endpoint)


# -- error taxonomy ---------------------------------------------------------
class RpcError(Exception):
    """Base for transport-level RPC failures (application-level errors —
    the server's {'error': ...} replies — stay plain RuntimeError)."""


class RetryableError(RpcError):
    """Transport failure that a fresh connection may fix; raised once the
    retry budget (attempts or deadline) is exhausted."""

    def __init__(self, msg, endpoint=None, attempts=0):
        super().__init__(msg)
        self.endpoint = endpoint
        self.attempts = attempts


class DeadlineExceeded(RetryableError):
    """The caller's deadline lapsed before any attempt succeeded."""


class CircuitOpenError(RetryableError):
    """Fast-fail: the endpoint's breaker is open (recent failures, the
    reset window has not elapsed). Callers should back off or re-shard."""


class FrameError(RpcError):
    """Malformed or oversized frame. Deliberately NOT retryable:
    resending the same bytes reproduces the same corruption, and a
    peer speaking a different protocol should fail loud, not retry
    until the deadline burns down."""


class FrameTooLargeError(FrameError):
    """Declared frame length exceeds the codec's max_frame bound —
    refuse before allocating, so a corrupted length header cannot OOM
    the receiver."""


class FrameDecodeError(FrameError):
    """Frame arrived whole but the payload failed to decode."""


# transient socket errnos worth a reconnect (vs e.g. EACCES/EBADF bugs)
_RETRYABLE_ERRNOS = frozenset({
    errno.ECONNREFUSED, errno.ECONNRESET, errno.ECONNABORTED,
    errno.EPIPE, errno.ETIMEDOUT, errno.EHOSTUNREACH, errno.ENETUNREACH,
    errno.ENETRESET, errno.EAGAIN,
})


class RetryPolicy:
    """Exponential backoff + full jitter, capped attempts, and the
    retryable/terminal classification used by ResilientChannel."""

    def __init__(self, max_attempts=4, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.5, retryable_exceptions=None):
        if max_attempts < 1:
            raise ValueError('max_attempts must be >= 1')
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self._extra_retryable = tuple(retryable_exceptions or ())

    def is_retryable(self, exc):
        if isinstance(exc, self._extra_retryable):
            return True
        if isinstance(exc, (socket.timeout, TimeoutError, ConnectionError,
                            BrokenPipeError, EOFError)):
            return True
        if isinstance(exc, OSError):
            return exc.errno in _RETRYABLE_ERRNOS or exc.errno is None
        return False

    def backoff(self, attempt):
        """Delay before retry number `attempt` (1-based), jittered."""
        d = min(self.base_delay * (self.multiplier ** (attempt - 1)),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * random.random()
        return d


class Deadline:
    """Absolute time budget. All attempts of a call (and all shards of a
    fan-out) share one Deadline so the total wait stays bounded."""

    def __init__(self, seconds):
        self._t_end = time.monotonic() + float(seconds)

    @classmethod
    def after(cls, seconds):
        return cls(seconds)

    def remaining(self):
        return self._t_end - time.monotonic()

    def expired(self):
        return self.remaining() <= 0.0

    def clamp(self, timeout):
        """Per-attempt socket timeout: never longer than what's left."""
        rem = self.remaining()
        if rem <= 0.0:
            raise DeadlineExceeded('deadline expired')
        return rem if timeout is None else min(timeout, rem)


class CircuitBreaker:
    """Half-open circuit breaker for one endpoint.

    closed -> (failure_threshold consecutive failures) -> open;
    open -> (reset_timeout elapsed) -> half-open: ONE probe call goes
    through; its success closes the breaker, its failure re-opens.
    """

    CLOSED, OPEN, HALF_OPEN = 'closed', 'open', 'half_open'

    def __init__(self, failure_threshold=5, reset_timeout=5.0, name=None):
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self._failures = 0
        self._opened_at = None
        self._probing = False
        self._lock = threading.Lock()
        self._m_state = None
        self.name = None
        if name is not None:
            self.bind_name(name)

    def bind_name(self, name):
        """Label this breaker's metrics with `name` (its endpoint).
        Unnamed breakers stay un-instrumented — standalone unit-test
        breakers don't pollute the endpoint label space."""
        self.name = name
        self._m_state = _M_BREAKER_STATE.labels(name)
        self._m_state.set(_STATE_CODES[self.CLOSED])

    def _note_transition(self, to_state):
        if self._m_state is not None:
            _M_TRANSITIONS.labels(self.name, to_state).inc()
            self._m_state.set(_STATE_CODES[to_state])

    @property
    def state(self):
        with self._lock:
            return self._state_locked()

    def _state_locked(self):
        if self._opened_at is None:
            return self.CLOSED
        if time.monotonic() - self._opened_at >= self.reset_timeout:
            return self.HALF_OPEN
        return self.OPEN

    def allow(self):
        """True if a call may proceed (claims the half-open probe slot)."""
        with self._lock:
            st = self._state_locked()
            if st == self.CLOSED:
                return True
            if st == self.HALF_OPEN and not self._probing:
                self._probing = True
                # the observable open -> half_open moment: a probe claim
                self._note_transition(self.HALF_OPEN)
                return True
            return False

    def record_success(self):
        with self._lock:
            was = self._state_locked()
            self._failures = 0
            self._opened_at = None
            self._probing = False
            if was != self.CLOSED:
                self._note_transition(self.CLOSED)

    def record_failure(self):
        """Count one failure; returns True exactly when this failure
        (re)opened the breaker — the edge the flight recorder dumps on,
        so a failure storm yields one dump, not one per call."""
        with self._lock:
            was = self._state_locked()
            self._failures += 1
            self._probing = False
            if self._failures >= self.failure_threshold:
                # (re)open and restart the reset window
                self._opened_at = time.monotonic()
                if was != self.OPEN:
                    self._note_transition(self.OPEN)
                    return True
        return False


# -- framed messages, codec-pluggable ---------------------------------------
# Same frame as ps/embedding_service (8-byte big-endian length + payload
# bytes); lives here so the channel owns its transport end-to-end and the
# ps module can keep its server-side helpers without an import cycle.
# `codec` is an (encode, decode) pair; None means the PS binary wire
# codec (the historical default — existing PS/graph clients unchanged).
# The serving fabric passes its length-prefixed JSON codec instead
# (serving/fabric/protocol.py), riding the identical retry/breaker/
# deadline/trace machinery over a different payload encoding.

def _send_frame(sock, obj, codec=None, max_frame=None):
    if codec is None:
        from .ps import wire
        payload = wire.encode(obj)
    else:
        payload = codec[0](obj)
    if max_frame is not None and len(payload) > max_frame:
        raise FrameTooLargeError(
            'refusing to send %d-byte frame (max_frame=%d)'
            % (len(payload), max_frame))
    sock.sendall(struct.pack('>Q', len(payload)) + payload)


def _recv_frame(sock, codec=None, max_frame=None):
    hdr = b''
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError('peer closed')
        hdr += chunk
    n = struct.unpack('>Q', hdr)[0]
    if max_frame is not None and n > max_frame:
        raise FrameTooLargeError(
            'peer declared %d-byte frame (max_frame=%d)' % (n, max_frame))
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError('peer closed')
        buf.extend(chunk)
    if codec is None:
        from .ps import wire
        return wire.decode(bytes(buf))
    return codec[1](bytes(buf))


class ResilientChannel:
    """One endpoint's connection with timeouts, reconnect-and-retry for
    idempotent ops, and a circuit breaker.

    Connection is lazy: construction never blocks on a dead server, the
    first call (or the first call after a failure) reconnects. One
    in-flight call at a time per channel (the frame protocol has no
    request ids); the internal lock serializes callers.
    """

    def __init__(self, endpoint, retry_policy=None,
                 call_timeout=DEFAULT_CALL_TIMEOUT,
                 connect_timeout=DEFAULT_CONNECT_TIMEOUT,
                 breaker=None, codec=None, max_frame=None):
        host, port = endpoint.rsplit(':', 1)
        self.endpoint = endpoint
        self._addr = (host, int(port))
        self.policy = retry_policy or RetryPolicy()
        self.codec = codec
        self.max_frame = max_frame
        self.call_timeout = call_timeout
        self.connect_timeout = connect_timeout
        self.breaker = breaker if breaker is not None \
            else CircuitBreaker(name=endpoint)
        if self.breaker.name is None:
            self.breaker.bind_name(endpoint)
        # labeled children cached once: per-event cost is a flag check
        self._m_attempts = _M_ATTEMPTS.labels(endpoint)
        self._m_failures = _M_FAILURES.labels(endpoint)
        self._m_backoff = _M_BACKOFF.labels(endpoint)
        self._m_deadline = _M_DEADLINE.labels(endpoint)
        self._m_circuit = _M_CIRCUIT_REJECT.labels(endpoint)
        self._sock = None
        self._lock = threading.Lock()

    # -- connection management ----------------------------------------------
    def _connect(self, deadline=None):
        _fire('connect', self.endpoint)
        timeout = self.connect_timeout
        if deadline is not None:
            timeout = deadline.clamp(timeout)
        sock = socket.create_connection(self._addr, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _drop_connection(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._lock:
            self._drop_connection()

    @property
    def connected(self):
        return self._sock is not None

    # -- the call path -------------------------------------------------------
    def _attempt(self, msg, timeout, deadline):
        if self._sock is None:
            self._sock = self._connect(deadline)
        sock = self._sock
        per_try = timeout if deadline is None else deadline.clamp(timeout)
        sock.settimeout(per_try)
        _fire('send', self.endpoint)
        _send_frame(sock, msg, self.codec, self.max_frame)
        _fire('recv', self.endpoint)
        return _recv_frame(sock, self.codec, self.max_frame)

    def call(self, msg, idempotent=True, timeout=None, deadline=None):
        """Send one request, return the decoded reply.

        idempotent=False disables the retry loop: after a transport
        failure the server may or may not have applied the op, so a
        blind resend could double-apply (grad pushes). The connection is
        still timed out and reconnected for the NEXT call.

        With tracing enabled the call runs under an 'rpc.call' span with
        one 'rpc.attempt' child per wire attempt; each attempt's trace
        context rides the message under TRACE_KEY so the server-side
        handler span parents on the exact attempt that reached it.
        """
        if timeout is None:
            timeout = self.call_timeout
        attempts = self.policy.max_attempts if idempotent else 1
        tr = _tracing.default_tracer()
        if not tr.enabled:
            with self._lock:
                return self._call_locked(msg, timeout, deadline, attempts,
                                         tr, _tracing.NULL_SPAN)
        with tr.start_span('rpc.call',
                           tags={'endpoint': self.endpoint,
                                 'idempotent': bool(idempotent)}) as span:
            with self._lock:
                return self._call_locked(msg, timeout, deadline, attempts,
                                         tr, span)

    def _call_locked(self, msg, timeout, deadline, attempts, tr, span):
        last_exc = None
        for attempt in range(1, attempts + 1):
            if deadline is not None and deadline.expired():
                self._m_deadline.inc()
                if span:
                    span.set_tag('deadline_expired', True)
                    tr.recorder.maybe_dump('deadline_expired')
                raise DeadlineExceeded(
                    'deadline expired before attempt %d to %s'
                    % (attempt, self.endpoint),
                    endpoint=self.endpoint, attempts=attempt - 1) \
                    from last_exc
            if not self.breaker.allow():
                self._m_circuit.inc()
                span.set_tag('circuit_open_fast_fail', True)
                raise CircuitOpenError(
                    'circuit open for %s (%d consecutive failures)'
                    % (self.endpoint, self.breaker._failures),
                    endpoint=self.endpoint, attempts=attempt - 1) \
                    from last_exc
            if span:
                att = tr.start_span('rpc.attempt', parent=span,
                                    tags={'attempt': attempt,
                                          'retries': attempt - 1,
                                          'breaker': self.breaker.state})
                wire = dict(msg)
                wire[_tracing.TRACE_KEY] = att.ctx()
            else:
                att = _tracing.NULL_SPAN
                wire = msg
            try:
                self._m_attempts.inc()
                out = self._attempt(wire, timeout, deadline)
                self.breaker.record_success()
                att.finish()
                return out
            except DeadlineExceeded as e:
                self._drop_connection()
                self._m_deadline.inc()
                att.set_error(e)
                att.finish()
                if span:
                    tr.recorder.maybe_dump('deadline_expired')
                raise
            except Exception as e:
                self._drop_connection()
                att.set_error(e)
                att.finish()
                if not self.policy.is_retryable(e):
                    raise
                opened = self.breaker.record_failure()
                self._m_failures.inc()
                if opened and span:
                    # the failing attempt span is already in the ring
                    tr.recorder.maybe_dump('circuit_open')
                last_exc = e
                if attempt < attempts:
                    delay = self.policy.backoff(attempt)
                    if deadline is not None:
                        rem = deadline.remaining()
                        if rem <= 0:
                            break
                        delay = min(delay, rem)
                    span.add_event('backoff', attempt=attempt,
                                   seconds=round(delay, 6))
                    self._m_backoff.inc(delay)
                    time.sleep(delay)
        if deadline is not None and deadline.expired():
            self._m_deadline.inc()
            if span:
                span.set_tag('deadline_expired', True)
                tr.recorder.maybe_dump('deadline_expired')
            raise DeadlineExceeded(
                'deadline expired after %d attempts to %s: %r'
                % (attempts, self.endpoint, last_exc),
                endpoint=self.endpoint, attempts=attempts) from last_exc
        raise RetryableError(
            '%d attempts to %s failed: %r'
            % (attempts, self.endpoint, last_exc),
            endpoint=self.endpoint, attempts=attempts) from last_exc


def call_once(endpoint, msg, timeout=DEFAULT_CALL_TIMEOUT,
              connect_timeout=DEFAULT_CONNECT_TIMEOUT):
    """One-shot request over a fresh ephemeral connection (blocking ops
    like barriers that must not pin a shared channel). No retries — the
    caller owns retry semantics for these — but fully timed out."""
    ch = ResilientChannel(endpoint,
                          retry_policy=RetryPolicy(max_attempts=1),
                          call_timeout=timeout,
                          connect_timeout=connect_timeout,
                          breaker=CircuitBreaker(failure_threshold=1 << 30))
    try:
        return ch.call(msg, idempotent=False)
    finally:
        ch.close()
