"""Process/env bootstrap (reference: fleet launch env PADDLE_TRAINER_ID /
PADDLE_TRAINER_ENDPOINTS; platform/gen_comm_id_helper.cc TCP bootstrap).

TPU-native: jax.distributed.initialize replaces gen_nccl_id + NCCLCommContext
entirely (SURVEY.md §5.8). Env-name parity is kept so reference launch
scripts work unchanged.
"""
import os

import jax

_STATE = {'initialized': False}


def get_rank(group=None):
    if group is not None:
        return group.rank
    try:
        return jax.process_index()
    except Exception:
        return int(os.environ.get('PADDLE_TRAINER_ID', 0))


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    try:
        return jax.process_count()
    except Exception:
        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        return len(eps.split(',')) if eps else 1


def is_initialized():
    return _STATE['initialized']


def init_parallel_env():
    """reference: distributed/parallel.py:58 init_parallel_env. Multi-host:
    reads PADDLE_TRAINER_* env (or jax-native vars) and calls
    jax.distributed.initialize; single-host it is a no-op (ICI mesh over
    local devices needs no process group)."""
    if _STATE['initialized']:
        return
    n = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
    eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
    rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    if n > 1 and eps:
        coordinator = eps.split(',')[0]
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=n, process_id=rank)
    _STATE['initialized'] = True


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get('FLAGS_selected_tpus', '0').split(',')[0])

    @property
    def current_endpoint(self):
        return os.environ.get('PADDLE_CURRENT_ENDPOINT', '127.0.0.1:6170')

    @property
    def trainer_endpoints(self):
        return os.environ.get('PADDLE_TRAINER_ENDPOINTS', '').split(',')

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return self.rank
