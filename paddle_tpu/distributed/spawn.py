"""paddle.distributed.spawn (reference: distributed/spawn.py:333).

On TPU, one process drives all local chips (single-controller SPMD), so
nprocs defaults to 1 process and spawn degenerates to calling func; true
multi-host spawn goes through `python -m paddle_tpu.distributed.launch`.
"""
import multiprocessing as mp
import os


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    if nprocs in (-1, 0, 1, None):
        func(*args)
        return None
    ctx = mp.get_context('spawn')
    procs = []
    for rank in range(nprocs):
        env = {'PADDLE_TRAINER_ID': str(rank),
               'PADDLE_TRAINERS_NUM': str(nprocs)}
        p = ctx.Process(target=_wrap, args=(func, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError('spawned process failed: %s' % p.exitcode)
    return procs


def _wrap(func, args, env):
    os.environ.update(env)
    func(*args)
