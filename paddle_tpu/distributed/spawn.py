"""paddle.distributed.spawn (reference: distributed/spawn.py:333 +
fleet/launch_utils.py env contract).

On TPU, one process drives all local chips (single-controller SPMD), so
nprocs<=1 degenerates to calling func inline; nprocs>1 spawns real
processes with the reference's env contract (PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINER_ENDPOINTS)
— the per-rank bootstrap a jax.distributed.initialize picks up on
multi-host. Failures propagate with the failing rank's traceback text
(launch_utils TrainerProc watch-loop behavior).
"""
import multiprocessing as mp
import os
import traceback

__all__ = ['spawn', 'SpawnContext']


class SpawnContext:
    def __init__(self, procs, error_queue):
        self.processes = procs
        self._errors = error_queue

    def join(self, timeout=None):
        for p in self.processes:
            p.join(timeout)
        failures = []
        while not self._errors.empty():
            failures.append(self._errors.get())
        for p in self.processes:
            if p.exitcode not in (0, None):
                rank_tb = next((tb for r, tb in failures), None)
                raise RuntimeError(
                    'spawned rank failed (exitcode %s)%s'
                    % (p.exitcode,
                       (':\n' + rank_tb) if rank_tb else ''))
        return True


def _free_ports(n):
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(('127.0.0.1', 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _platform_env():
    """CPU-forcing env for spawned children.

    A spawn-context child re-imports the worker's module at startup —
    including the framework — and under the axon TPU shim that import
    wedges on the device claim. One process drives all local TPU chips in
    the single-controller model anyway, so multi-process children default
    to the CPU backend (JAX_PLATFORMS=cpu must ride together with an
    empty PALLAS_AXON_POOL_IPS: the env var alone routes through the shim
    and hangs). Set PADDLE_TPU_SPAWN_PLATFORM=tpu to opt a child into the
    real backend (multi-host deployments where each host owns its chips).
    """
    plat = os.environ.get('PADDLE_TPU_SPAWN_PLATFORM', 'cpu')
    if plat == 'cpu':
        return {'JAX_PLATFORMS': 'cpu', 'PALLAS_AXON_POOL_IPS': ''}
    return {}


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    if nprocs in (-1, 0, 1, None):
        func(*args)
        return None
    ctx = mp.get_context('spawn')
    error_queue = ctx.SimpleQueue()
    ports = _free_ports(nprocs)
    endpoints = ','.join('127.0.0.1:%d' % p for p in ports)
    procs = []
    plat_env = _platform_env()
    # children inherit os.environ at exec time — seat the platform env in
    # the parent around start() so it is active BEFORE the child's module
    # re-imports (per-rank vars still applied in _wrap, which runs after)
    saved = {k: os.environ.get(k) for k in plat_env}
    os.environ.update(plat_env)
    try:
        trace_base = os.environ.get('PADDLE_TRAINER_TRACE_DIR')
        for rank in range(nprocs):
            env = {'PADDLE_TRAINER_ID': str(rank),
                   'PADDLE_TRAINERS_NUM': str(nprocs),
                   'PADDLE_CURRENT_ENDPOINT': '127.0.0.1:%d' % ports[rank],
                   'PADDLE_TRAINER_ENDPOINTS': endpoints}
            if trace_base:
                # per-rank trace dirs, merge_traces-ready (profiler)
                env['PADDLE_TRAINER_TRACE_DIR'] = os.path.join(
                    trace_base, 'rank_%d' % rank)
            env.update(plat_env)
            p = ctx.Process(target=_wrap,
                            args=(func, args, env, rank, error_queue),
                            daemon=daemon)
            p.start()
            procs.append(p)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    context = SpawnContext(procs, error_queue)
    if join:
        context.join()
        return None
    return context


def _wrap(func, args, env, rank, error_queue):
    os.environ.update(env)
    try:
        func(*args)
    except Exception:
        error_queue.put((rank, traceback.format_exc()))
        raise
