"""Sequence-parallel execution context (beyond-reference, SURVEY.md §5.7).

The strategy compiler (fleet_train_step) builds an sp state dict when
`strategy.sequence_parallel` and the mesh's 'sp' degree > 1, and the
TrainStep activates it ONLY around its own trace/execution (so a plain
eval/generation call outside the step keeps ordinary attention); while
active, every `F.scaled_dot_product_attention` call routes through ring
attention (K/V rotating over ICI via ppermute, ops/ring_attention.py) or
Ulysses all-to-all — the model code does not change between sp=1 and sp>1.

The reference has no sequence parallelism; its long-sequence levers are
recompute + pipeline (SURVEY §5.7). Here the 'sp' mesh axis shards the
sequence dimension of activations end-to-end: embeddings/MLP/layernorm are
token-local (XLA SPMD handles them), attention is the one op that mixes
tokens — and it goes through the ring.
"""
import functools

from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

__all__ = ['enable_sequence_parallel', 'disable_sequence_parallel',
           'sequence_parallel_state', 'sp_attention', 'make_sp_state',
           'sp_scope']

_STATE = {'active': None}


def make_sp_state(mesh, axis='sp', mode='ring', batch_axes=(),
                  head_axis=None):
    """Build (without activating) an sp routing state. batch_axes/head_axis
    describe how the OTHER q/k/v dims are sharded so shard_map's specs keep
    dp/mp layouts intact."""
    assert mode in ('ring', 'ulysses', 'zigzag'), mode
    return {'mesh': mesh, 'axis': axis, 'mode': mode,
            'batch_axes': tuple(batch_axes), 'head_axis': head_axis}


def enable_sequence_parallel(mesh, axis='sp', mode='ring', batch_axes=(),
                             head_axis=None):
    _STATE['active'] = make_sp_state(mesh, axis, mode, batch_axes, head_axis)


def disable_sequence_parallel():
    _STATE['active'] = None


class sp_scope:
    """Context manager activating an sp state only around a step's
    trace/execution — prevents the global context from hijacking eval or
    generation calls made between training steps."""

    def __init__(self, state):
        self._state = state

    def __enter__(self):
        self._saved = _STATE['active']
        if self._state is not None:
            _STATE['active'] = self._state
        return self

    def __exit__(self, *exc):
        _STATE['active'] = self._saved
        return False


def sequence_parallel_state():
    return _STATE['active']


def sp_attention(q, k, v, causal, scale, state=None, dropout_p=0.0,
                 dropout_key=None):
    """Attention over [B, N, H, D] with N sharded on the sp axis.

    Called with GLOBAL (traced) arrays inside jit; shard_map splits the
    sequence and runs the ring/Ulysses kernel per device.

    dropout_p/dropout_key: attention-prob dropout; the replicated key
    crosses the shard_map boundary and is folded with the sp rank inside,
    so every sequence shard draws independent masks (sp-aware RNG — the
    mp RNGStatesTracker pattern applied to the sequence axis).
    """
    import jax
    from jax import lax
    from ..ops import ring_attention as ra

    st = state or _STATE['active']
    mesh, axis, mode = st['mesh'], st['axis'], st['mode']
    b_ax = st['batch_axes'] or None
    if b_ax is not None and len(b_ax) == 1:
        b_ax = b_ax[0]
    spec = P(b_ax, axis, st['head_axis'], None)
    if mode == 'zigzag':
        n_dev = mesh.shape[axis]
        n = q.shape[1]
        if causal and n % (2 * n_dev) == 0:
            return _zigzag_sp(q, k, v, scale, mesh, axis, spec, n_dev,
                              dropout_p, dropout_key)
        # zigzag's balance argument IS causality; non-causal (or
        # non-chunkable N) falls back to the plain ring
        mode = 'ring'
    # ring mode prefers the Pallas-block ring (falls back to the jnp ring
    # internally when the kernel cannot run on this backend/shape; dropout
    # routes to the jnp ring)
    fn = ra.ring_flash_attention if mode == 'ring' else ra.ulysses_attention
    if dropout_p and dropout_key is not None:
        def body(qq, kk, vv, key):
            rank_key = jax.random.fold_in(key, lax.axis_index(axis))
            return fn(qq, kk, vv, axis_name=axis, causal=causal,
                      scale=scale, dropout_p=dropout_p,
                      dropout_key=rank_key)
        wrapped = shard_map(body, mesh=mesh,
                            in_specs=(spec, spec, spec, P()),
                            out_specs=spec, check_rep=False)
        return wrapped(q, k, v, dropout_key)
    wrapped = shard_map(
        functools.partial(fn, axis_name=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return wrapped(q, k, v)


def _zigzag_sp(q, k, v, scale, mesh, axis, spec, n_dev, dropout_p,
               dropout_key):
    """Zigzag-balanced causal ring: permute the sequence so rank r holds
    chunks (r, 2P-1-r), run the balanced kernel, permute back. The gather
    costs one HBM copy each way; the kernel saves ~half the attention
    flops AND equalizes them across ranks (the plain causal ring's wall
    clock is gated by the all-visible last rank)."""
    import jax.numpy as jnp
    from ..ops import ring_attention as ra

    idx, inv = ra.zigzag_layout_indices(q.shape[1], n_dev)
    qz = jnp.take(q, idx, axis=1)
    kz = jnp.take(k, idx, axis=1)
    vz = jnp.take(v, idx, axis=1)
    if dropout_p and dropout_key is not None:
        def body(qq, kk, vv, key):
            return ra.zigzag_ring_attention(
                qq, kk, vv, axis_name=axis, scale=scale,
                dropout_p=dropout_p, dropout_key=key)
        out = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec, P()),
                        out_specs=spec, check_rep=False)(qz, kz, vz,
                                                         dropout_key)
    else:
        out = shard_map(
            functools.partial(ra.zigzag_ring_attention, axis_name=axis,
                              scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False)(qz, kz, vz)
    return jnp.take(out, inv, axis=1)
