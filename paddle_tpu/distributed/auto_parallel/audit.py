"""Compile-time sharding auditor.

XLA's partitioner warnings come out of C++ absl logging, which writes
straight to file descriptor 2 — sys.stderr redirection never sees them.
The capture here dup2()s fd 2 into a temp file around the compile, then
restores it; parsing is delegated to parser.py so the detector is
testable from stored fixtures without compiling anything.

Every audit entry point compiles FRESH (a new jax.jit wrapper, or
TrainStep.compiled_executable which re-lowers each call): XLA only
emits the warnings while actually partitioning, so auditing a cached
executable would report a false pass. For the same reason the compile
runs with the PERSISTENT compilation cache suspended
(framework/compile_cache.py makes it process-wide) — a cache hit skips
the partitioner entirely and would silently report clean.
"""
import contextlib
import os
import sys
import tempfile

import jax

from .parser import (parse_spmd_warnings, parse_hlo_collectives,
                     ShardingEvent)

__all__ = ['ShardingAuditReport', 'capture_compiler_stderr',
           'audit_callable', 'audit_train_step', 'audit_from_text',
           'assert_no_involuntary_resharding']

_TAIL_CHARS = 4000


@contextlib.contextmanager
def capture_compiler_stderr():
    """Capture EVERYTHING written to fd 2 (Python and C++ alike) for the
    duration of the block. Yields a dict whose 'text' key holds the
    captured output after the block exits."""
    buf = {'text': ''}
    saved = os.dup(2)
    tmp = tempfile.TemporaryFile(mode='w+b')
    try:
        sys.stderr.flush()
        os.dup2(tmp.fileno(), 2)
        yield buf
    finally:
        try:
            sys.stderr.flush()
        except Exception:
            pass
        os.dup2(saved, 2)
        os.close(saved)
        tmp.seek(0)
        buf['text'] = tmp.read().decode('utf-8', 'replace')
        tmp.close()


class ShardingAuditReport:
    """What GSPMD did to one compiled step: involuntary-reshard events
    (the failure signal), collective counts/bytes from the optimized
    HLO (the context), and the raw stderr tail (the evidence)."""

    def __init__(self, label='', events=(), collectives=None,
                 stderr_tail=''):
        self.label = label
        self.events = list(events)
        self.collectives = dict(collectives or {})
        self.stderr_tail = stderr_tail

    @property
    def passed(self):
        return not self.events

    @property
    def involuntary_bytes(self):
        return sum(e.bytes for e in self.events)

    def to_dict(self):
        return {
            'label': self.label,
            'ok': self.passed,
            'n_events': len(self.events),
            'involuntary_bytes': self.involuntary_bytes,
            'events': [e.to_dict() for e in self.events],
            'collectives': self.collectives,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(label=d.get('label', ''),
                   events=[ShardingEvent.from_dict(e)
                           for e in d.get('events', ())],
                   collectives=d.get('collectives'))

    def summary(self):
        head = ('sharding audit [%s]: %s' %
                (self.label or 'step',
                 'clean' if self.passed else
                 '%d involuntary reshard(s), ~%d bytes replicated'
                 % (len(self.events), self.involuntary_bytes)))
        lines = [head]
        for e in self.events:
            lines.append('  %r' % (e,))
        if self.collectives:
            coll = ' '.join('%s=%d' % (k, v['count'])
                            for k, v in sorted(self.collectives.items()))
            lines.append('  collectives: %s' % coll)
        return '\n'.join(lines)


def audit_from_text(stderr_text, hlo_text=None, label=''):
    """Build a report from already-captured text (stored capture tails,
    the dryrun gate, fixture tests)."""
    return ShardingAuditReport(
        label=label,
        events=parse_spmd_warnings(stderr_text),
        collectives=parse_hlo_collectives(hlo_text) if hlo_text else None,
        stderr_tail=(stderr_text or '')[-_TAIL_CHARS:])


@contextlib.contextmanager
def _compile_cache_suspended():
    """Force the audited compile through XLA even when the process has a
    persistent compile cache configured (restored on exit). The config
    flip alone is not enough: jax memoizes cache-in-use at the first
    compile of the process (compilation_cache._cache_checked), so the
    latch must be dropped on BOTH transitions for the flip to be seen."""
    try:
        was = bool(jax.config.jax_enable_compilation_cache)
    except Exception:
        yield
        return
    if not was:
        yield
        return
    try:
        from ...framework.compile_cache import _drop_cache_latch
    except Exception:
        def _drop_cache_latch():
            pass
    try:
        jax.config.update('jax_enable_compilation_cache', False)
    except Exception:
        yield
        return
    _drop_cache_latch()
    try:
        yield
    finally:
        try:
            jax.config.update('jax_enable_compilation_cache', True)
        except Exception:
            pass
        _drop_cache_latch()


@contextlib.contextmanager
def _mesh_scope(mesh):
    """Make `mesh` the ambient mesh for PartitionSpec-based constraints
    inside the audited fn, across jax generations."""
    if mesh is None:
        yield
        return
    use_mesh = getattr(getattr(jax, 'sharding', None), 'use_mesh', None)
    if use_mesh is not None:
        with use_mesh(mesh):
            yield
        return
    with mesh:
        yield


def audit_callable(fn, args=(), kwargs=None, mesh=None, label=''):
    """Freshly jit-compile fn(*args, **kwargs) under stderr capture and
    report what the partitioner did. fn may itself be jitted (jit of jit
    is fine); args should carry NamedShardings (or the callable should
    place constraints) for the audit to be about anything."""
    kwargs = kwargs or {}
    wrapped = jax.jit(lambda *a, **k: fn(*a, **k))
    with _mesh_scope(mesh):
        lowered = wrapped.lower(*args, **kwargs)
        with _compile_cache_suspended(), capture_compiler_stderr() as cap:
            compiled = lowered.compile()
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = None
    return audit_from_text(cap['text'], hlo, label=label or
                           getattr(fn, '__name__', 'fn'))


def audit_train_step(step, inputs, labels, label=''):
    """Audit a framework.functional.TrainStep for one batch. Uses
    compiled_executable (which re-lowers+recompiles every call, so the
    partitioner warnings are emitted even for a step that already ran)."""
    with _compile_cache_suspended(), capture_compiler_stderr() as cap:
        compiled = step.compiled_executable(inputs, labels)
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = None
    return audit_from_text(cap['text'], hlo, label=label or 'train_step')


def assert_no_involuntary_resharding(fn, mesh=None, args=(), kwargs=None,
                                     label=''):
    """CI gate: compile fn and fail loudly if GSPMD had to fall back to
    replicate-then-repartition anywhere. Returns the report on success
    so tests can additionally pin collective counts."""
    report = audit_callable(fn, args=args, kwargs=kwargs, mesh=mesh,
                            label=label)
    if not report.passed:
        raise AssertionError(report.summary())
    return report
