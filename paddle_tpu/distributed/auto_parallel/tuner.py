"""Sharding autotuner: close the audit -> plan loop into search.

The planner (planner.py) pins the pipeline boundaries it KNOWS GSPMD
guesses wrong, from first principles. This module searches instead of
asserting: it enumerates candidate PartitionSpec entries per boundary
(micro / stacked / batch — the same three the planner names), compiles
a small probe program under each candidate, scores it with

  1. audit-reported involuntary-reshard bytes (the failure signal the
     whole subsystem exists to eliminate),
  2. HLO collective bytes from the optimized module (parser.py), and
  3. the analytic cost model's ideal step time (monitor/perf/costmodel)
     as the tiebreaker,

ranked lexicographically in that order, and emits a versioned,
content-addressed **plan artifact**: canonical JSON keyed by a sha256
of {mesh axis sizes, pipeline axis, batch axes, jaxlib version, model
fingerprint}. The pipeline engines resolve their constraint plans
through :func:`resolve_plan` — when ``PADDLE_TPU_PLAN_DIR`` holds an
artifact for the live key they apply ITS specs (a :class:`TunedPlan`),
otherwise they fall back to the analytic planner exactly as before.
``PADDLE_TPU_PLAN_STRICT=1`` turns a key mismatch (stale artifact, or
a dir with plans for other configs only) into a hard error instead of
a silent fallback.

Probe compiles run with the persistent compile cache suspended (a
cache hit skips the partitioner and would score every candidate as
clean), so tuning always measures real partitioner behavior.
"""
import glob
import hashlib
import json
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import audit as ap_audit
from .planner import (PipelinePlan, plan_pipeline, _axis_sizes, _pad, _U)

__all__ = ['PLAN_VERSION', 'BOUNDARIES', 'PlanKeyError', 'TunedPlan',
           'current_config', 'key_of_config', 'encode_entries',
           'decode_entries', 'score_report', 'score_key',
           'candidate_entries', 'default_probe', 'tune_pipeline',
           'build_artifact', 'dump_plan', 'save_plan', 'load_plan',
           'verify_artifact', 'plan_from_artifact', 'plan_path',
           'resolve_plan', 'resolve_plan_for_state']

PLAN_VERSION = 1
BOUNDARIES = ('micro', 'stacked', 'batch')

_ENV_DIR = 'PADDLE_TPU_PLAN_DIR'
_ENV_STRICT = 'PADDLE_TPU_PLAN_STRICT'


class PlanKeyError(RuntimeError):
    """A loaded plan artifact does not match the live configuration
    (or its content hash), under PADDLE_TPU_PLAN_STRICT=1."""


# ---------------------------------------------------------------- keys

def current_config(mesh_sizes, axis, batch_axes, model_fingerprint=None):
    """The content-address payload for one live configuration. Mesh
    axis sizes + pipeline axis + batch axes fix the search space;
    jaxlib pins the partitioner generation (a jaxlib upgrade must
    invalidate tuned plans); the model fingerprint is the caller's
    hook for plans tuned against a specific program."""
    try:
        import jaxlib
        jl = getattr(jaxlib, '__version__', 'unknown')
    except Exception:
        jl = 'unknown'
    return {'version': PLAN_VERSION,
            'mesh': {str(k): int(v) for k, v in dict(mesh_sizes).items()},
            'axis': str(axis),
            'batch_axes': [str(a) for a in batch_axes],
            'jaxlib': jl,
            'model': model_fingerprint}


def key_of_config(config):
    """sha256 content address of a config payload (16 hex chars —
    collision space is tiny: a handful of configs per deployment)."""
    blob = json.dumps(config, sort_keys=True, separators=(',', ':'))
    return hashlib.sha256(blob.encode('utf-8')).hexdigest()[:16]


# ------------------------------------------------- spec (de)serialization

def encode_entries(entries):
    """Per-dim spec entries -> JSON: None stays null, UNCONSTRAINED
    becomes '*', an axis name stays a string, an axis tuple a list."""
    if entries is None:
        return None
    out = []
    for e in entries:
        if e is None:
            out.append(None)
        elif _U is not None and e is _U:
            out.append('*')
        elif isinstance(e, (list, tuple)):
            out.append([str(a) for a in e])
        else:
            out.append(str(e))
    return out


def decode_entries(enc):
    if enc is None:
        return None
    out = []
    for e in enc:
        if e is None:
            out.append(None)
        elif e == '*':
            out.append(_U)
        elif isinstance(e, list):
            out.append(tuple(e))
        else:
            out.append(e)
    return tuple(out)


# ---------------------------------------------------------------- plan

class TunedPlan(PipelinePlan):
    """A PipelinePlan whose boundary entries come from a plan artifact.

    Substitutable everywhere the engines use the analytic plan: the
    shape guards (divisibility, pp extent) stay the planner's — an
    artifact can change WHAT is pinned, never make an unpinnable shape
    pinned — only the per-dim entries are swapped."""

    def __init__(self, mesh, axis, batch_axes, entries, key=None,
                 path=None):
        super().__init__(mesh, axis, batch_axes)
        self.entries = {b: (tuple(e) if e is not None else None)
                        for b, e in dict(entries).items()}
        self.key = key
        self.path = path

    def _entry_spec(self, boundary, shape, fallback):
        e = self.entries.get(boundary)
        if e is None:
            return fallback(shape)
        if fallback(shape) is None:     # planner refuses -> we refuse
            return None
        return _pad(e, len(shape))

    def micro_spec(self, shape):
        return self._entry_spec('micro', shape,
                                super().micro_spec)

    def stacked_spec(self, shape):
        return self._entry_spec('stacked', shape,
                                super().stacked_spec)

    def batch_spec(self, shape):
        return self._entry_spec('batch', shape,
                                super().batch_spec)

    def describe(self):
        out = super().describe()
        out['tuned'] = {b: encode_entries(e)
                        for b, e in sorted(self.entries.items())}
        if self.key:
            out['plan_key'] = self.key
        return out


# ------------------------------------------------------------- scoring

def score_report(report, cost=None):
    """Pure scoring of one candidate from its audit report (a
    ShardingAuditReport or its to_dict form) plus optional cost-model
    fields — fixture-testable without compiling anything."""
    d = report.to_dict() if hasattr(report, 'to_dict') else dict(report)
    colls = d.get('collectives') or {}
    score = {
        'involuntary_bytes': int(d.get('involuntary_bytes', 0) or 0),
        'collective_bytes': int(sum(
            int((v or {}).get('bytes', 0) or 0) for v in colls.values())),
        'collective_count': int(sum(
            int((v or {}).get('count', 0) or 0) for v in colls.values())),
    }
    if cost and cost.get('ideal_step_s') is not None:
        score['ideal_step_s'] = float(cost['ideal_step_s'])
    return score


def score_key(score):
    """Lexicographic rank: involuntary bytes dominate (the audit's
    failure signal), collective bytes second (real per-step traffic),
    analytic ideal step time as the tiebreaker. Lower is better."""
    return (score.get('involuntary_bytes', 0),
            score.get('collective_bytes', 0),
            float(score.get('ideal_step_s') or 0.0))


# ------------------------------------------------------------ search

def candidate_entries(plan):
    """Closed candidate sets per boundary. Index 0 is always the
    analytic planner's own choice, so score ties resolve to it."""
    ba = tuple(plan.batch_axes)
    micro = [(None, ba),        # planner: micro index is a TIME axis
             (ba, None),        # the transposed guess GSPMD makes
             (None, None)]      # fully replicated rows
    if len(ba) > 1:
        micro.append((None, (ba[0],)))   # batch tiling on one axis only
    stacked = [(plan.axis,),    # planner: pp-sharded stage dim
               (None,)]         # replicated stages
    batch = [(ba,),             # planner: rows carry full batch tiling
             (None,)]
    return {'micro': micro, 'stacked': stacked, 'batch': batch}


def default_probe(plan):
    """cfg5-analog probe for one candidate plan: batch activations
    sharded over the batch axes, reshaped into microbatches, a scan
    dynamic-slicing ZeRO-tiled stacked stage weights — the exact
    producer/consumer structure of the pipeline while-body (the
    tests/test_sharding_audit.py cfg5 pin, shrunk for search). Returns
    (fn, args)."""
    mesh = plan.mesh
    sizes = _axis_sizes(mesh)
    pp = sizes[plan.axis]
    n_micro = max(pp, 2)
    b = n_micro * plan.batch_div
    hidden = 32
    x = jax.device_put(jnp.ones((b, 8, hidden), jnp.float32),
                       NamedSharding(mesh, P(tuple(plan.batch_axes))))
    # stage weights enter ZeRO-tiled on a weight dim, like stage-3
    # sharding leaves them
    w = jax.device_put(
        jnp.ones((pp, 2, hidden, hidden), jnp.float32),
        NamedSharding(mesh, P(None, None, tuple(plan.batch_axes), None)))

    def f(x, w):
        micro = plan.constrain_micro(
            x.reshape((n_micro, b // n_micro) + x.shape[1:]))
        wts = plan.constrain_stacked({'w': w})['w']

        def tick(carry, t):
            def layer(c, j):
                lw = lax.dynamic_index_in_dim(
                    lax.dynamic_index_in_dim(wts, t % pp, 0,
                                             keepdims=False),
                    j, 0, keepdims=False)
                return jnp.tanh(c @ lw), None
            y, _ = lax.scan(layer, micro[t % n_micro],
                            jnp.arange(w.shape[1]))
            return carry + y.sum(), None
        out, _ = lax.scan(tick, 0.0, jnp.arange(3))
        merged = plan.constrain_batch(x + out)
        return merged.sum()

    return f, (x, w)


def _audit_probe(fn, args, mesh, label):
    """Fresh-compile fn under the stderr capture WITH the persistent
    compile cache suspended; returns (report, compiled) so the cost
    model can score the same executable the audit saw."""
    wrapped = jax.jit(lambda *a: fn(*a))
    with ap_audit._mesh_scope(mesh):
        lowered = wrapped.lower(*args)
        with ap_audit._compile_cache_suspended(), \
                ap_audit.capture_compiler_stderr() as cap:
            compiled = lowered.compile()
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = None
    return ap_audit.audit_from_text(cap['text'], hlo, label=label), compiled


def _cost_fields(compiled):
    from ...monitor.perf import costmodel
    cost = costmodel.cost_of(compiled)
    if not cost:
        return None
    rf = costmodel.roofline(cost['flops'], cost['bytes_accessed'])
    return {'flops': cost['flops'],
            'bytes_accessed': cost['bytes_accessed'],
            'ideal_step_s': rf['ideal_step_s']}


def tune_pipeline(mesh, axis='pp', batch_axes=None, probe=None,
                  model_fingerprint=None, use_costmodel=True):
    """Greedy per-boundary coordinate search over candidate_entries.

    Starts from the analytic planner's choices and, boundary by
    boundary, keeps any alternative that strictly improves the score
    (compile count is 1 + sum(len(candidates)-1), not the product).
    Returns the plan artifact dict (save with save_plan), or None on a
    mesh with nothing to plan."""
    plan = plan_pipeline(mesh, axis, batch_axes)
    if plan is None:
        return None
    probe = probe or default_probe
    cands = candidate_entries(plan)
    chosen = {b: cands[b][0] for b in BOUNDARIES}
    trials = {b: [] for b in BOUNDARIES}
    n_compiles = [0]

    def evaluate(entries, label):
        tp = TunedPlan(mesh, axis, plan.batch_axes, entries)
        fn, args = probe(tp)
        report, compiled = _audit_probe(fn, args, mesh, label)
        n_compiles[0] += 1
        cost = _cost_fields(compiled) if use_costmodel else None
        return score_report(report, cost)

    base_score = evaluate(chosen, 'base')
    for b in BOUNDARIES:
        trials[b].append({'spec': encode_entries(chosen[b]),
                          'score': base_score, 'chosen': True})
        best = (score_key(base_score), chosen[b], base_score)
        for alt in cands[b][1:]:
            trial = dict(chosen)
            trial[b] = alt
            s = evaluate(trial, '%s=%s' % (b, encode_entries(alt)))
            trials[b].append({'spec': encode_entries(alt), 'score': s,
                              'chosen': False})
            if score_key(s) < best[0]:
                best = (score_key(s), alt, s)
        if best[1] is not chosen[b]:
            for t in trials[b]:
                t['chosen'] = t['spec'] == encode_entries(best[1])
            chosen[b] = best[1]
        base_score = best[2]

    boundaries = {b: {'spec': encode_entries(chosen[b]),
                      'score': next(t['score'] for t in trials[b]
                                    if t['chosen']),
                      'candidates': trials[b]}
                  for b in BOUNDARIES}
    return build_artifact(_axis_sizes(mesh), axis, plan.batch_axes,
                          boundaries, model_fingerprint=model_fingerprint,
                          extra={'probe_compiles': n_compiles[0],
                                 'final_score': base_score})


# ------------------------------------------------------------ artifact

def build_artifact(mesh_sizes, axis, batch_axes, boundaries,
                   model_fingerprint=None, extra=None):
    """Assemble + canonicalize the artifact dict. `boundaries` maps
    boundary -> {'spec': encoded entries, 'score': {...}, ...}."""
    config = current_config(mesh_sizes, axis, batch_axes,
                            model_fingerprint)
    art = {'version': PLAN_VERSION,
           'key': key_of_config(config),
           'config': config,
           'boundaries': dict(boundaries)}
    if extra:
        art.update(extra)
    # normalize to JSON-native types so emit == re-emit, byte for byte
    return json.loads(dump_plan(art))


def dump_plan(artifact):
    """Canonical serialization: sorted keys, fixed indent, trailing
    newline — load_plan + dump_plan is byte-identical to the file."""
    return json.dumps(artifact, sort_keys=True, indent=1) + '\n'


def plan_path(dirpath, key):
    return os.path.join(dirpath, 'plan_%s.json' % key)


def save_plan(artifact, dirpath):
    """Write the artifact into `dirpath` under its content address
    (atomic rename). Returns the path."""
    os.makedirs(dirpath, exist_ok=True)
    path = plan_path(dirpath, artifact['key'])
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        f.write(dump_plan(artifact))
    os.replace(tmp, path)
    return path


def load_plan(path):
    with open(path) as f:
        return json.load(f)


def verify_artifact(art, expect_key=None):
    """Content check: the stored key must re-derive from the stored
    config, and (when given) match the live config's key. Raises
    PlanKeyError with the mismatch spelled out."""
    if art.get('version') != PLAN_VERSION:
        raise PlanKeyError('plan version %r != supported %d'
                           % (art.get('version'), PLAN_VERSION))
    stored = art.get('key')
    derived = key_of_config(art.get('config') or {})
    if stored != derived:
        raise PlanKeyError('plan key %r does not re-derive from its own '
                           'config (%r) — artifact edited or corrupt'
                           % (stored, derived))
    if expect_key is not None and stored != expect_key:
        raise PlanKeyError('plan key %r is stale for live config key %r '
                           '(mesh/jaxlib/model changed since tuning)'
                           % (stored, expect_key))
    return art


def plan_from_artifact(art, mesh, path=None):
    cfg = art['config']
    entries = {b: decode_entries(spec.get('spec'))
               for b, spec in (art.get('boundaries') or {}).items()}
    return TunedPlan(mesh, cfg['axis'], tuple(cfg['batch_axes']),
                     entries, key=art.get('key'), path=path)


# ----------------------------------------------------------- resolution

def _strict():
    return os.environ.get(_ENV_STRICT) == '1'


def resolve_plan(mesh, axis='pp', batch_axes=None, model_fingerprint=None):
    """The engines' plan source: a TunedPlan from PADDLE_TPU_PLAN_DIR
    when an artifact matches the live content key, else the analytic
    planner's PipelinePlan (or None on trivial meshes). Under
    PADDLE_TPU_PLAN_STRICT=1 a mismatching or missing-but-expected
    artifact raises PlanKeyError instead of falling back."""
    plan = plan_pipeline(mesh, axis, batch_axes)
    dirpath = os.environ.get(_ENV_DIR)
    if not dirpath or plan is None:
        return plan
    config = current_config(_axis_sizes(mesh), axis, plan.batch_axes,
                            model_fingerprint)
    key = key_of_config(config)
    path = plan_path(dirpath, key)
    if os.path.exists(path):
        try:
            art = verify_artifact(load_plan(path), expect_key=key)
        except (PlanKeyError, ValueError, OSError, KeyError) as e:
            if _strict():
                if isinstance(e, PlanKeyError):
                    raise
                raise PlanKeyError('unreadable plan artifact %s: %s'
                                   % (path, e))
            return plan
        return plan_from_artifact(art, mesh, path=path)
    others = sorted(os.path.basename(p) for p in
                    glob.glob(os.path.join(dirpath, 'plan_*.json')))
    if others and _strict():
        raise PlanKeyError(
            'no plan for live config key %s in %s (stale artifacts: %s) '
            '— re-run the tuner or unset %s'
            % (key, dirpath, ', '.join(others), _ENV_STRICT))
    return plan


def resolve_plan_for_state(pp_state):
    """resolve_plan for a pipeline state dict (make_pp_state output) —
    the drop-in for planner.plan_for_state at the engine call sites."""
    if pp_state is None:
        return None
    return resolve_plan(pp_state['mesh'], pp_state['axis'])
