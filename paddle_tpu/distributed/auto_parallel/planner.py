"""Sharding constraint planner for the pipeline engines.

Root cause of the MULTICHIP r05 config-5 warnings (pp2 x sharding4):
the GPipe/1F1B bodies run Manual over 'pp' with every other axis left
Auto, and GSPMD must GUESS shardings for the values flowing through the
while-body. Two guesses go wrong:

  * the microbatch split reshapes the [B, ...] batch — sharded 4-way
    over ('dp','sharding') — into [n_micro, mb, ...], and the
    partitioner may split that 4-way tiling across BOTH new dims
    ({devices=[2,2]}-style, transposed orders). Everything downstream
    in the loop then inherits mixed 2x2 tilings.
  * the stacked per-stage params [pp, per, ...] enter the loop with
    ZeRO's 'sharding' tiling on a weight dim; inside the body the
    per-layer dynamic-slice+squeeze meets consumers that prefer the
    (contaminated) transposed tilings, and tiled->tiled transitions
    with transposed device orders are exactly what the partitioner can
    only do by replicate-then-repartition ("Involuntary full
    rematerialization", spmd_partitioner.cc:652) — once per microbatch
    tick.

The plan makes both boundaries explicit so there is nothing to guess:
the microbatch index is pinned as a TIME axis (replicated) with each
row carrying the WHOLE batch tiling, and the stacked params are pinned
pp-sharded on dim 0. Every other dim is left UNCONSTRAINED — pinning
them would itself force transitions (e.g. forcing a ZeRO-tiled weight
dim to replicated is exactly a transposed tiled->tiled move and
reintroduces the warning); the point is to remove the partitioner's
bad choices at the two contaminating boundaries, not to override its
good ones. Constraints are placed OUTSIDE the shard_map boundary,
which every supported jax generation handles identically.
"""
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ['PipelinePlan', 'plan_pipeline', 'plan_for_state']

# axes that shard the global batch dim (strategy.py batch_axes order)
_BATCH_AXES = ('dp', 'sharding')

# per-dim "keep whatever you infer" marker (predates every jax line we
# support, but probe anyway so the planner degrades to shorter specs —
# unmentioned trailing dims mean REPLICATED, which is still correct,
# just stronger than necessary)
_U = getattr(P, 'UNCONSTRAINED', None)


def _pad(entries, rank):
    """Extend a spec to `rank` dims with UNCONSTRAINED placeholders."""
    if _U is None or rank <= len(entries):
        return P(*entries)
    return P(*(tuple(entries) + (_U,) * (rank - len(entries))))


def _axis_sizes(mesh):
    return dict(zip(mesh.axis_names, np.shape(mesh.devices)))


def _constrain(arr, spec, mesh):
    if spec is None:
        return arr
    try:
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, spec))
    except (ValueError, TypeError):
        # unknown jax dialect for this placement: leave the value
        # unconstrained rather than break the schedule — the audit gate
        # reports whether the plan actually took effect
        return arr


class PipelinePlan:
    """Constraint specs for one pipelined region on one mesh."""

    def __init__(self, mesh, axis, batch_axes):
        self.mesh = mesh
        self.axis = axis
        self.batch_axes = tuple(batch_axes)
        sizes = _axis_sizes(mesh)
        self.batch_div = 1
        for a in self.batch_axes:
            self.batch_div *= sizes[a]

    # ---- specs (pure; unit-testable without compiling) ----

    def batch_spec(self, shape):
        """[B, ...] activations outside the region: rows carry the full
        batch tiling; other dims keep whatever GSPMD inferred."""
        if len(shape) < 1 or shape[0] % self.batch_div:
            return None
        return _pad((self.batch_axes,), len(shape))

    def micro_spec(self, shape):
        """[n_micro, mb, ...] microbatch stream: the microbatch index is
        a TIME axis (replicated), each row keeps the full batch tiling.
        This pins the reshape so the partitioner cannot split the batch
        tiling across the two new dims."""
        if len(shape) < 2 or shape[1] % self.batch_div:
            return None
        return _pad((None, self.batch_axes), len(shape))

    def stacked_spec(self, shape):
        """Stacked per-stage params [pp, per, ...]: pp-sharded on dim 0;
        the weight dims stay UNCONSTRAINED so an incoming ZeRO tiling is
        kept IN PLACE (forcing it anywhere else is itself an inefficient
        transition)."""
        sizes = _axis_sizes(self.mesh)
        if len(shape) < 1 or shape[0] != sizes.get(self.axis):
            return None
        return _pad((self.axis,), len(shape))

    def describe(self):
        """Boundary -> spec map (docs/auto_parallel.md renders this)."""
        u = '*' if _U is not None else 'None'
        ba = '(%s)' % ','.join(self.batch_axes)
        return {
            'microbatch-slice [n_micro, mb, ...]':
                'P(None, %s, %s...)' % (ba, u),
            'stacked stage params [pp, per, ...]':
                "P('%s', %s...)" % (self.axis, u),
            'pipeline output [n_micro, mb, ...]':
                'P(None, %s, %s...)' % (ba, u),
            'merged output [B, ...]': 'P(%s, %s...)' % (ba, u),
        }

    # ---- application helpers (used from the engines, inside jit) ----

    def constrain_micro(self, arr):
        return _constrain(arr, self.micro_spec(arr.shape), self.mesh)

    def constrain_stacked(self, stacked):
        return {n: _constrain(a, self.stacked_spec(a.shape), self.mesh)
                for n, a in stacked.items()}

    def constrain_batch(self, arr):
        return _constrain(arr, self.batch_spec(arr.shape), self.mesh)


def plan_pipeline(mesh, axis='pp', batch_axes=None):
    """Build the constraint plan for a pipelined region on `mesh`.

    Returns None when there is nothing to plan: no such axis, or no
    other nontrivial axis (a pure-pp mesh leaves GSPMD nothing to
    guess, and the unconstrained program is already clean)."""
    sizes = _axis_sizes(mesh)
    if axis not in sizes:
        return None
    if all(n == 1 for a, n in sizes.items() if a != axis):
        return None
    if batch_axes is None:
        batch_axes = [a for a in _BATCH_AXES
                      if sizes.get(a, 1) > 1]
    batch_axes = tuple(a for a in batch_axes if a in sizes)
    return PipelinePlan(mesh, axis, batch_axes)


def plan_for_state(pp_state):
    """Plan for a pipeline state dict (make_pp_state output)."""
    if pp_state is None:
        return None
    return plan_pipeline(pp_state['mesh'], pp_state['axis'])
