"""Parsers for XLA's SPMD partitioner diagnostics and optimized HLO.

Two generations of the involuntary-rematerialization warning exist in
the wild and both must parse (the stored MULTICHIP captures carry one,
the locally-installed jaxlib emits the other):

  newer XLA (spmd_partitioner.cc:652, W-level):
    [SPMD] Involuntary full rematerialization. The compiler cannot go
    from sharding {A} to {B} efficiently for HLO operation %op = ...,
    metadata={op_name="..." stack_frame_id=N}. As the last resort,
    SPMD will replicate the tensor and then partition it ...

  older XLA (spmd_partitioner.cc:613, E-level):
    [spmd] Involuntary full rematerialization. The compiler was not
    able to go from sharding {A} to {B} without doing a full
    rematerialization of the tensor for HLO operation: %op = ...,
    metadata={op_name="..." source_file="..." source_line=N}. You
    probably want to enrich the sharding annotations ...

Capture tails may also cut the first warning mid-line (a bounded tail
is stored, not the whole stderr), so a fragment that still shows the
target sharding and the HLO operation is recovered as an event rather
than dropped — losing the first event would make a 3-warning capture
diff clean against a 2-warning run.
"""
import re

__all__ = ['ShardingEvent', 'parse_spmd_warnings', 'parse_hlo_collectives',
           'INVOLUNTARY_KIND']

INVOLUNTARY_KIND = 'involuntary-full-rematerialization'

# bytes per element for HLO primitive type names
_DTYPE_BYTES = {
    'pred': 1, 's4': 1, 'u4': 1, 's8': 1, 'u8': 1,
    'f8e4m3fn': 1, 'f8e5m2': 1, 'f8e4m3b11fnuz': 1,
    's16': 2, 'u16': 2, 'f16': 2, 'bf16': 2,
    's32': 4, 'u32': 4, 'f32': 4,
    's64': 8, 'u64': 8, 'f64': 8, 'c64': 8, 'c128': 16,
}

_FULL_RE = re.compile(
    r'Involuntary full rematerialization\.\s+The compiler '
    r'(?:cannot|was not able to) go from sharding \{(?P<src>[^{}]+)\} '
    r'to \{(?P<dst>[^{}]+)\}'
    r'(?:\s+efficiently\s+for|\s+without doing a full rematerialization '
    r'of the tensor for)'
    r'\s+HLO operation:?\s+%(?P<op>[\w.\-]+)\s+=\s+'
    r'(?P<dtype>[a-z]\w*)\[(?P<dims>[\d,]*)\]')

# a tail-truncated warning: the leading "...go from sharding {A} to {" is
# gone but "<dst tiling>} efficiently for HLO operation %op = ..." remains
_FRAG_RE = re.compile(
    r'(?P<dst>devices=[^{}]+)\}\s+(?:efficiently\s+)?for HLO '
    r'operation:?\s+%(?P<op>[\w.\-]+)\s+=\s+'
    r'(?P<dtype>[a-z]\w*)\[(?P<dims>[\d,]*)\]')

_OPCODE_RE = re.compile(r'\](?:\{[\d,]*\})?\s+(?P<opcode>[\w\-]+)\(')
_OP_NAME_RE = re.compile(r'op_name="(?P<v>[^"]*)"')
_STACK_RE = re.compile(r'stack_frame_id=(?P<v>\d+)')
_SRC_FILE_RE = re.compile(r'source_file="(?P<v>[^"]*)"')
_SRC_LINE_RE = re.compile(r'source_line=(?P<v>\d+)')
_OP_SHARD_RE = re.compile(r'sharding=\{(?P<v>[^{}]*)\}')

# one optimized-HLO collective definition, e.g.
#   %all-reduce.1 = f32[512,64]{1,0} all-reduce(f32[512,64]{1,0} %x), ...
_COLLECTIVE_RE = re.compile(
    r'=\s+\(?\s*(?P<dtype>[a-z]\w*)\[(?P<dims>[\d,]*)\]\S*\s+'
    r'(?P<kind>all-reduce|all-gather|reduce-scatter|collective-permute|'
    r'all-to-all)(?:-start)?\(')


def _shape_bytes(dtype, dims):
    shape = [int(d) for d in dims.split(',') if d] if dims else []
    n = 1
    for d in shape:
        n *= d
    return shape, n * _DTYPE_BYTES.get(dtype, 4)


class ShardingEvent:
    """One partitioner fallback: a tensor GSPMD could only move between
    the producer and consumer shardings by replicating it."""

    def __init__(self, kind, op, dtype, shape, nbytes, src_sharding,
                 dst_sharding, opcode=None, op_sharding=None, op_name=None,
                 stack_frame_id=None, source_file=None, source_line=None,
                 raw=''):
        self.kind = kind
        self.op = op                      # HLO value name, e.g. squeeze.63
        self.opcode = opcode              # HLO opcode, e.g. copy
        self.dtype = dtype
        self.shape = shape
        self.bytes = nbytes               # estimated resharded bytes
        self.src_sharding = src_sharding  # producer tiling (None if cut)
        self.dst_sharding = dst_sharding  # target tiling
        self.op_sharding = op_sharding    # the op's own annotation
        self.op_name = op_name            # jax op_name metadata
        self.stack_frame_id = stack_frame_id
        self.source_file = source_file
        self.source_line = source_line
        self.raw = raw

    def key(self):
        """Identity for diffing a run against a stored capture. Excludes
        the HLO value number (squeeze.63 vs squeeze.65 across compiler
        versions is the same event) and the raw text."""
        return (self.kind, self.opcode or '', self.dtype,
                tuple(self.shape), self.op_name or '',
                self.src_sharding or '', self.dst_sharding or '')

    def to_dict(self):
        return {
            'kind': self.kind, 'op': self.op, 'opcode': self.opcode,
            'dtype': self.dtype, 'shape': self.shape, 'bytes': self.bytes,
            'src_sharding': self.src_sharding,
            'dst_sharding': self.dst_sharding,
            'op_sharding': self.op_sharding, 'op_name': self.op_name,
            'stack_frame_id': self.stack_frame_id,
            'source_file': self.source_file,
            'source_line': self.source_line,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(d.get('kind', INVOLUNTARY_KIND), d.get('op'),
                   d.get('dtype'), list(d.get('shape') or []),
                   int(d.get('bytes') or 0), d.get('src_sharding'),
                   d.get('dst_sharding'), opcode=d.get('opcode'),
                   op_sharding=d.get('op_sharding'),
                   op_name=d.get('op_name'),
                   stack_frame_id=d.get('stack_frame_id'),
                   source_file=d.get('source_file'),
                   source_line=d.get('source_line'))

    def __repr__(self):
        where = self.op_name or self.source_file or '?'
        return ('<ShardingEvent %s %s[%s] {%s} -> {%s} ~%d B at %s>'
                % (self.opcode or self.op, self.dtype,
                   ','.join(map(str, self.shape)),
                   self.src_sharding, self.dst_sharding, self.bytes, where))


def _event_from_line(line):
    m = _FULL_RE.search(line)
    src = None
    if m is None:
        # only attempt fragment recovery on lines that still look like a
        # partitioner fallback (tail cut the prefix off)
        if ('HLO operation' not in line
                or ('rematerialization' not in line
                    and 'last resort' not in line)):
            return None
        m = _FRAG_RE.search(line)
        if m is None:
            return None
    else:
        src = m.group('src').strip()
    shape, nbytes = _shape_bytes(m.group('dtype'), m.group('dims'))
    opm = _OPCODE_RE.match(line, m.end('dims'))

    def _opt(rx, cast=str):
        g = rx.search(line)
        return cast(g.group('v')) if g else None

    return ShardingEvent(
        INVOLUNTARY_KIND, m.group('op'), m.group('dtype'), shape, nbytes,
        src, m.group('dst').strip(),
        opcode=opm.group('opcode') if opm else None,
        op_sharding=_opt(_OP_SHARD_RE),
        op_name=_opt(_OP_NAME_RE),
        stack_frame_id=_opt(_STACK_RE, int),
        source_file=_opt(_SRC_FILE_RE),
        source_line=_opt(_SRC_LINE_RE, int),
        raw=line.strip())


def parse_spmd_warnings(text):
    """Extract involuntary-reshard events from compiler stderr (or a
    stored capture tail). Returns a list of ShardingEvent."""
    events = []
    for line in (text or '').splitlines():
        ev = _event_from_line(line)
        if ev is not None:
            events.append(ev)
    return events


def parse_hlo_collectives(hlo_text):
    """Count collectives (and their payload bytes) in optimized HLO
    text — the coarse 'what does one step move over ICI' summary that
    sits next to the warning events in the audit report."""
    stats = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text or ''):
        _, nbytes = _shape_bytes(m.group('dtype'), m.group('dims'))
        s = stats.setdefault(m.group('kind'), {'count': 0, 'bytes': 0})
        s['count'] += 1
        s['bytes'] += nbytes
    return stats
