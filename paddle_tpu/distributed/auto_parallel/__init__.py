"""SPMD sharding audit & planning (reference: the multi-devices graph
pass layer, framework/ir/multi_devices_graph_pass/).

The reference framework decides collective/sharding placement with
static graph passes; this rebuild delegates partitioning to GSPMD and
therefore needs the inverse tooling: *observe* what GSPMD actually did
and *constrain* it where propagation guesses wrong. Three parts:

  parser   — turns XLA's spmd_partitioner warning stream and optimized
             HLO text into structured events (no compilation needed, so
             the detector itself is fixture-testable).
  audit    — compiles a callable/TrainStep under an fd-level stderr
             capture (XLA's C++ logs bypass sys.stderr) and emits a
             ShardingAuditReport with a pass/fail gate.
  planner  — builds the with_sharding_constraint specs the pipeline
             engines place on their carry / microbatch-slice /
             collective boundaries so producer and consumer shardings
             reach GSPMD already compatible.
  tuner    — searches candidate specs per boundary (scored by audit
             reshard bytes + HLO collective bytes + the analytic cost
             model) and emits content-addressed plan artifacts the
             engines resolve instead of the hand-derived specs.

CI surface: `assert_no_involuntary_resharding(fn, mesh=..., args=...)`
from any test, and the MULTICHIP dryrun embeds one report per config
(tools/check_sharding_regression.py diffs those against the stored
capture).
"""
from .parser import (ShardingEvent, parse_spmd_warnings,
                     parse_hlo_collectives, INVOLUNTARY_KIND)
from .audit import (ShardingAuditReport, capture_compiler_stderr,
                    audit_callable, audit_train_step, audit_from_text,
                    assert_no_involuntary_resharding)
from .planner import PipelinePlan, plan_pipeline, plan_for_state
from .tuner import (TunedPlan, PlanKeyError, tune_pipeline, resolve_plan,
                    resolve_plan_for_state, save_plan, load_plan,
                    verify_artifact, plan_from_artifact, score_report,
                    score_key, current_config, key_of_config)

__all__ = [
    'ShardingEvent', 'parse_spmd_warnings', 'parse_hlo_collectives',
    'INVOLUNTARY_KIND',
    'ShardingAuditReport', 'capture_compiler_stderr', 'audit_callable',
    'audit_train_step', 'audit_from_text',
    'assert_no_involuntary_resharding',
    'PipelinePlan', 'plan_pipeline', 'plan_for_state',
    'TunedPlan', 'PlanKeyError', 'tune_pipeline', 'resolve_plan',
    'resolve_plan_for_state', 'save_plan', 'load_plan',
    'verify_artifact', 'plan_from_artifact', 'score_report', 'score_key',
    'current_config', 'key_of_config',
]
