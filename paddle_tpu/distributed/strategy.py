"""Strategy compiler: DistributedStrategy -> shardings for TrainStep.

This replaces the reference's fleet meta-optimizer program-rewrite pipeline
(fleet/meta_optimizers/*): instead of inserting c_allreduce/broadcast ops
into a ProgramDesc, each strategy contributes PartitionSpecs for params /
optimizer slots / batch, and XLA's SPMD partitioner inserts the collectives
(SURVEY.md §7.1 mapping table).

  data_parallel      -> batch P('dp'), params replicated  => psum on grads
  sharding (ZeRO1-3) -> opt slots / grads / params sharded on 'sharding'
  tensor_parallel    -> per-param placements from layer hints (mp_layers)
  sequence_parallel  -> activations sharded on 'sp' (long-context)
"""
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import functional as func_mod

__all__ = ['build_shardings', 'shard_params_for_zero3']


def _param_spec(placement, ndim, strategy, name=''):
    """PartitionSpec for a param: TP placement hint (tuple aligned to shape)
    + optional ZeRO-3 sharding of the largest remaining axis."""
    dims = [None] * ndim
    if placement:
        for i, ax in enumerate(placement):
            if ax is not None and i < ndim:
                dims[i] = ax
    if strategy.get('zero_stage', 0) >= 3 and ndim >= 1:
        for i in range(ndim):
            if dims[i] is None:
                dims[i] = 'sharding'
                break
    return P(*dims)


def build_shardings(model, optimizer, mesh, strategy=None):
    """Returns kwargs for TrainStep: in_shardings/out_shardings/batch.

    strategy keys: zero_stage (0/1/2/3), tensor_parallel (bool),
    sequence_parallel (bool).
    """
    strategy = strategy or {}
    params = func_mod.extract_params(model)
    buffers = func_mod.extract_buffers(model)
    pmap = dict(model.named_parameters())

    def ns(spec):
        return NamedSharding(mesh, spec)

    replicated = ns(P())
    param_shardings = {}
    for name, arr in params.items():
        placement = getattr(pmap[name], 'placement', None)
        if placement:
            # keep only axes the mesh actually parallelizes (mp for TP
            # layers, ep for expert-stacked MoE params, ...)
            placement = tuple(
                ax if (ax in mesh.axis_names
                       and mesh.shape.get(ax, 1) > 1) else None
                for ax in placement)
            if not any(placement):
                placement = None
        spec = _param_spec(placement, arr.ndim, strategy, name)
        # avoid sharding axes not divisible
        dims = []
        for i, ax in enumerate(spec):
            if ax is not None and arr.shape[i] % mesh.shape.get(ax, 1) != 0:
                dims.append(None)
            else:
                dims.append(ax)
        param_shardings[name] = ns(P(*dims))

    buffer_shardings = {name: replicated for name in buffers}

    zero = strategy.get('zero_stage', 0)

    def slot_sharding_for(name, arr):
        if zero >= 1:
            # shard optimizer state over the sharding axis on dim0 if divisible
            if arr.ndim >= 1 and arr.shape[0] % max(
                    mesh.shape.get('sharding', 1), 1) == 0 \
                    and mesh.shape.get('sharding', 1) > 1:
                return ns(P('sharding'))
        return param_shardings[name]

    # opt_state pytree: {'slots': {name: {slot: arr}}, 'step': scalar}
    pmap_t = {n: p for n, p in model.named_parameters() if not p.stop_gradient}
    slots_shardings = {}
    for name, p in pmap_t.items():
        slot = optimizer._get_slots(p)
        slots_shardings[name] = {k: slot_sharding_for(name, v)
                                 for k, v in slot.items()}
    opt_shardings = {'slots': slots_shardings, 'step': replicated}
    if strategy.get('amp_dtype') == 'float16':
        # fp16 engages dynamic loss scaling: scalar state rides along
        opt_shardings['loss_scale'] = replicated
        opt_shardings['growth'] = replicated
    if strategy.get('gradient_merge_k', 1) > 1:
        # TrainStep's opt_state grows accumulators under gradient merge
        opt_shardings['acc'] = {name: param_shardings[name]
                                for name in pmap_t}
        opt_shardings['micro'] = replicated

    batch_axes = ['dp']
    if 'sharding' in mesh.axis_names and mesh.shape.get('sharding', 1) > 1:
        # ZeRO composes with dp over the batch: flatten both axes onto batch
        batch_axes = [('dp', 'sharding')]
    if strategy.get('sequence_parallel') and \
            mesh.shape.get('sp', 1) > 1:
        # long-context: dim 1 (sequence) sharded over 'sp'; attention
        # runs as ring/Ulysses via the sp context (distributed/sp.py)
        batch_axes = batch_axes + ['sp']
    batch_spec = P(*batch_axes)
    batch_sharding = ns(batch_spec)
    scalar = replicated

    # pure_step signature: (params, buffers, opt_state, batch, lr, key)
    in_shardings = (param_shardings, buffer_shardings, opt_shardings,
                    ((batch_sharding,), (batch_sharding,)), scalar, scalar)
    out_shardings = (param_shardings, buffer_shardings, opt_shardings, scalar)
    return {
        'mesh': mesh,
        'in_shardings': None,   # let jit infer from device_put of inputs
        'out_shardings': out_shardings,
        'batch_sharding': batch_sharding,
        'param_shardings': param_shardings,
    }


def place_params(model, param_shardings):
    """device_put every param/buffer onto its sharding (pre-step layout)."""
    pmap = dict(model.named_parameters())
    for name, sh in param_shardings.items():
        p = pmap[name]
        p._data = jax.device_put(p._data, sh)


def place_opt_slots(model, optimizer, opt_shardings):
    """Create+place optimizer slots per their shardings. Must run AFTER
    place_params so zeros_like starts from the sharded param, and the
    explicit device_put pins the slot layout the out_shardings promise
    (donation requires in/out layouts to agree)."""
    pmap = dict(model.named_parameters())
    for name, slot_shs in opt_shardings['slots'].items():
        p = pmap[name]
        slots = optimizer._get_slots(p)
        for k, sh in slot_shs.items():
            slots[k] = jax.device_put(slots[k], sh)


def shard_params_for_zero3(model, mesh):
    place_params(model, build_shardings(
        model, _NullOpt(), mesh, {'zero_stage': 3})['param_shardings'])


class _NullOpt:
    def _get_slots(self, p):
        return {}
