"""Distributed graph engine service (reference fork highlight:
distributed/service/graph_py_service.h:46,100,123 GraphPyService/
GraphPyServer/GraphPyClient over graph_brpc_{client,server}).

TPU-native: per-shard C++ GraphStore (native/graph_store.cc) hosted by
socket servers (same frame protocol as the PS embedding service); the
client key-shards requests by node id and merges results. API names follow
the reference so GNN training code ports directly: load_edge_file,
random_sample_neighboors, random_sample_nodes, pull_graph_list,
get_node_feat, add_graph_node, remove_graph_node (remove = tombstone).
"""
import socketserver
import threading

import numpy as np

from ..monitor import default_registry as _monitor_registry
from ..monitor import tracing as _tracing
from ..native.graph_store import GraphStore
from .ps.embedding_service import _send_msg, _recv_msg
from .resilience import Deadline, ResilientChannel, RetryPolicy

__all__ = ['GraphPyService', 'GraphPyServer', 'GraphPyClient']

# registered through the single-source schema table
# (monitor/telemetry.py CLIENT_OP_FAMILIES) so the committed metrics
# baseline and this module cannot drift
from ..monitor.telemetry import record_client_op_schema \
    as _record_client_op_schema

_CLIENT_FAMS = _record_client_op_schema(_monitor_registry())
_M_GRAPH_CALLS = _CLIENT_FAMS['graph_client_calls_total']
_M_GRAPH_ERRORS = _CLIENT_FAMS['graph_client_call_errors_total']

# Retry semantics of every op _GraphHandler dispatches, declared at the
# server and enforced against client send sites by graftlint's
# idempotency checker (tools/graftlint). Same vocabulary as the
# embedding service's OP_SEMANTICS.
OP_SEMANTICS = {
    'stop': 'non_idempotent',           # second delivery hits a dead server
    # store appends duplicate on resend, UNLESS the send is journaled:
    # a (client, seq) pair lets the server dedup on its high-water mark
    'add_edges': 'conditional',         # idempotent iff journaled
    'add_nodes': 'idempotent',          # no-op on an existing node
    'remove_nodes': 'idempotent',       # tombstone: resend re-tombstones
    'load_edge_file': 'non_idempotent',  # bulk append of the same file
    'sample_neighbors': 'idempotent',   # pure read
    'random_sample_nodes': 'idempotent',  # pure read
    'pull_graph_list': 'idempotent',    # pure read
    'degree': 'idempotent',             # pure read
    'set_node_feat': 'idempotent',      # re-writes the same values
    'get_node_feat': 'idempotent',      # pure read
    'stats': 'idempotent',              # pure read
    'ping': 'idempotent',               # liveness probe, pure read
    'snapshot': 'idempotent',           # rewrites the same snapshot file
    'restore': 'idempotent',            # reloads the same snapshot file
}


def _apply_graph_write(store, entry):
    """Apply one mutation oplog entry to a store. Shared by the live
    dispatch path and snapshot restore (the GraphStore may be the
    opaque C++ backend, so snapshots persist the mutation log and
    restore replays it into a fresh store)."""
    kind = entry['kind']
    if kind == 'add_edges':
        return store.add_edges(entry['src'], entry['dst'],
                               entry.get('weight'))
    if kind == 'add_nodes':
        return store.add_nodes(entry['ids'])
    if kind == 'remove_nodes':
        return store.remove_nodes(entry['ids'])
    if kind == 'set_node_feat':
        for i, f in zip(entry['ids'], entry['feats']):
            store.set_node_feat(i, f)
        return None
    if kind == 'load_edge_file':
        return store.load_edge_file(entry['path'],
                                    entry.get('reversed', False))
    raise ValueError('unknown graph write %r' % kind)


class _GraphHandler(socketserver.BaseRequestHandler):
    def setup(self):
        # registry lets chaos.kill_server sever established connections,
        # not just the listener — a killed pod drops both
        self.server.live_connections.add(self.request)

    def finish(self):
        self.server.live_connections.discard(self.request)

    def handle(self):
        while True:
            try:
                msg = _recv_msg(self.request)
            except (ConnectionError, OSError):
                return
            # continues the client's rpc.attempt span when the message
            # carries trace context; always strips the metadata key
            span = _tracing.default_tracer().server_span(msg, 'graph.server')
            op = msg['op']
            gsrv = self.server.graph_server
            try:
                if op == 'stop':
                    _send_msg(self.request, b'ok')
                    self.server.shutdown()
                    return
                if op == 'ping':
                    _send_msg(self.request, {'ok': True,
                                             'rank': gsrv.rank})
                    continue
                if op == 'snapshot':
                    gsrv.snapshot(msg['path'])
                    _send_msg(self.request, b'ok')
                    continue
                if op == 'restore':
                    gsrv.restore(msg['path'])
                    _send_msg(self.request, b'ok')
                    continue
                # read stores fresh each request: restore() swaps the
                # whole map and long-lived connections must see the
                # rebuilt stores, not the pre-recovery ones
                store = self.server.stores[msg.get('etype', 'default')]
                if op == 'add_edges':
                    entry = {'kind': 'add_edges',
                             'etype': msg.get('etype', 'default'),
                             'src': msg['src'], 'dst': msg['dst'],
                             'weight': msg.get('weight')}
                    cid = msg.get('client')
                    if cid is not None:
                        # journaled append: dedup on the per-client seq
                        # high-water mark — exactly-once under retry
                        applied = gsrv.journal_apply(
                            cid, msg['seq'],
                            lambda: gsrv.apply_write(entry))
                        _send_msg(self.request,
                                  {'ok': True, 'applied': applied})
                    else:
                        gsrv.apply_write(entry)
                        _send_msg(self.request, b'ok')
                elif op == 'add_nodes':
                    gsrv.apply_write({'kind': 'add_nodes',
                                      'etype': msg.get('etype', 'default'),
                                      'ids': msg['ids']})
                    _send_msg(self.request, b'ok')
                elif op == 'remove_nodes':
                    _send_msg(self.request, gsrv.apply_write(
                        {'kind': 'remove_nodes',
                         'etype': msg.get('etype', 'default'),
                         'ids': msg['ids']}))
                elif op == 'load_edge_file':
                    n = gsrv.apply_write(
                        {'kind': 'load_edge_file',
                         'etype': msg.get('etype', 'default'),
                         'path': msg['path'],
                         'reversed': msg.get('reversed', False)})
                    _send_msg(self.request, n)
                elif op == 'sample_neighbors':
                    out = store.sample_neighbors(msg['ids'],
                                                 msg['sample_size'])
                    _send_msg(self.request, out)
                elif op == 'random_sample_nodes':
                    _send_msg(self.request, store.random_sample_nodes(msg['k']))
                elif op == 'pull_graph_list':
                    _send_msg(self.request,
                              store.pull_graph_list(msg['shard'],
                                                    msg['cursor'],
                                                    msg['cap']))
                elif op == 'degree':
                    _send_msg(self.request, store.degree(msg['ids']))
                elif op == 'set_node_feat':
                    gsrv.apply_write({'kind': 'set_node_feat',
                                      'etype': msg.get('etype', 'default'),
                                      'ids': msg['ids'],
                                      'feats': msg['feats']})
                    _send_msg(self.request, b'ok')
                elif op == 'get_node_feat':
                    _send_msg(self.request,
                              store.get_node_feat(msg['ids'], msg['dim']))
                elif op == 'stats':
                    _send_msg(self.request, {'nodes': store.node_count(),
                                             'edges': store.edge_count()})
                else:
                    _send_msg(self.request, {'error': 'unknown op %r' % op})
            except Exception as e:  # report instead of killing the server
                span.set_error(e)
                _send_msg(self.request, {'error': repr(e)})
            finally:
                span.finish()


class _GraphTCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    # rebinding the port right after a kill must not wait out TIME_WAIT:
    # restart-on-the-same-endpoint is the recovery path under test
    allow_reuse_address = True


class GraphPyServer:
    """One graph shard server (graph_brpc_server parity)."""

    def __init__(self, rank=0, host='127.0.0.1', port=0, edge_types=('default',)):
        self._srv = _GraphTCPServer((host, port), _GraphHandler)
        self._srv.stores = {et: GraphStore() for et in edge_types}
        self._srv.live_connections = set()
        self._srv.graph_server = self
        self.port = self._srv.server_address[1]
        self.rank = rank
        self._edge_types = tuple(edge_types)
        # the GraphStore may be the opaque ctypes backend, so durable
        # state is an append-only mutation log: snapshot persists it,
        # restore replays it into fresh stores. The journal holds the
        # exactly-once (client -> last applied seq) marks.
        self._oplog = []
        self._journal = {}
        # RLock: journal_apply holds it across apply_fn, and apply_fn is
        # apply_write, which re-enters to append the oplog entry
        self._state_lock = threading.RLock()

    def journal_apply(self, client_id, seq, apply_fn):
        """Apply a journaled write exactly once (mark-and-apply under
        one lock, same contract as EmbeddingServer.journal_apply).
        Returns False on a dedup hit."""
        seq = int(seq)
        with self._state_lock:
            if seq <= self._journal.get(client_id, -1):
                return False
            apply_fn()
            self._journal[client_id] = seq
            return True

    def apply_write(self, entry):
        """Apply a mutation to its store and append it to the oplog."""
        store = self._srv.stores[entry.get('etype', 'default')]
        out = _apply_graph_write(store, entry)
        with self._state_lock:
            self._oplog.append(entry)
        return out

    def snapshot(self, path):
        """Persist the mutation log + journal marks atomically (io_save:
        temp + rename + CRC manifest)."""
        from ..framework import io_save
        with self._state_lock:
            state = {'oplog': list(self._oplog),
                     'journal': dict(self._journal),
                     'edge_types': list(self._edge_types)}
        io_save.save(state, path)

    def restore(self, path):
        """Rebuild every store by replaying a snapshot's mutation log
        into fresh GraphStores, then seat its journal marks."""
        from ..framework import io_save
        state = io_save.load(path)
        stores = {et: GraphStore()
                  for et in state.get('edge_types', self._edge_types)}
        for entry in state['oplog']:
            _apply_graph_write(stores[entry.get('etype', 'default')],
                               entry)
        with self._state_lock:
            self._srv.stores = stores
            self._oplog = list(state['oplog'])
            self._journal = {str(k): int(v)
                             for k, v in state['journal'].items()}

    def start_server(self, block=False):
        if block:
            self._srv.serve_forever()
        else:
            t = threading.Thread(target=self._srv.serve_forever, daemon=True)
            t.start()

    def stop_server(self):
        self._srv.shutdown()
        self._srv.server_close()

    # ---- fleet telemetry ------------------------------------------

    def metrics_server(self, **kwargs):
        """A MetricsServer over this process's registry — start it in a
        graph shard process and add `.url` to a FleetCollector as an
        HTTP target for the federated fleet view."""
        from ..monitor.server import MetricsServer
        return MetricsServer(registry=_monitor_registry(), **kwargs)

    def fleet_register(self, collector, instance=None):
        """Register this shard on an in-process FleetCollector. Server
        metrics live on the PROCESS registry: register each process
        once (in-proc shards share the registry; registering every
        shard would double-count the merge)."""
        return collector.add_target(instance or 'graph-%d' % self.rank,
                                    registry=_monitor_registry())


class GraphPyClient:
    """Key-sharded client (graph_brpc_client parity): node id % n_servers
    selects the shard; batch ops split/merge per shard.

    Transport is a ResilientChannel per shard: socket timeouts, reconnect
    + retry for idempotent ops, circuit breaker per endpoint. add_edges
    is conditional: unjournaled, a resend after an applied-but-unacked
    write would duplicate edges, so it runs single-attempt; with
    `journal=` (a supervisor.PushJournal) each send carries a (client,
    seq) pair the server dedups on, so it retries — and replays after a
    shard restore — exactly once. Everything else retries across
    reconnects. `op_deadline` (seconds) bounds each public operation
    across all its shards and retries.
    """

    def __init__(self, endpoints, retry_policy=None, call_timeout=None,
                 op_deadline=None, journal=None):
        self._channels = [
            ResilientChannel(ep, retry_policy=retry_policy,
                             **({'call_timeout': call_timeout}
                                if call_timeout is not None else {}))
            for ep in endpoints]
        self._n = len(endpoints)
        self._op_deadline = op_deadline
        self._journal = journal

    def _deadline(self):
        return None if self._op_deadline is None \
            else Deadline(self._op_deadline)

    def _call(self, server_idx, msg, idempotent=True, deadline=None):
        op = str(msg.get('op', '?'))
        _M_GRAPH_CALLS.labels(op).inc()
        try:
            out = self._channels[server_idx].call(msg,
                                                  idempotent=idempotent,
                                                  deadline=deadline)
        except Exception:
            _M_GRAPH_ERRORS.labels(op).inc()
            raise
        if isinstance(out, dict) and 'error' in out:
            _M_GRAPH_ERRORS.labels(op).inc()
            raise RuntimeError(out['error'])
        return out

    def _shard(self, ids):
        ids = np.asarray(ids, np.int64)
        return ids, ids % self._n

    def add_graph_node(self, etype, ids, weight_list=None):
        # idempotent: adding an existing node is a no-op on the store
        ids, shard = self._shard(ids)
        dl = self._deadline()
        for s in range(self._n):
            sub = ids[shard == s]
            if len(sub):
                self._call(s, {'op': 'add_nodes', 'etype': etype,
                               'ids': sub.tolist()}, deadline=dl)

    def remove_graph_node(self, etype, ids):
        # idempotent: remove is a tombstone, a resend re-tombstones
        ids, shard = self._shard(ids)
        dl = self._deadline()
        removed = 0
        for s in range(self._n):
            sub = ids[shard == s]
            if len(sub):
                removed += self._call(s, {'op': 'remove_nodes',
                                          'etype': etype,
                                          'ids': sub.tolist()},
                                      deadline=dl)
        return removed

    @property
    def journal(self):
        """The PushJournal backing exactly-once sends (None when
        unjournaled) — ShardSupervisor trims it at snapshot barriers."""
        return self._journal

    def _note_applied(self, out, seq):
        """Count a server-side dedup hit on a journaled send."""
        if seq is not None and isinstance(out, dict) \
                and not out.get('applied', True):
            self._journal.note_dedup()

    def add_edges(self, etype, src, dst, weight=None):
        src, shard = self._shard(src)
        dst = np.asarray(dst, np.int64)
        w = np.asarray(weight, np.float32) if weight is not None else None
        seq = None
        if self._journal is not None:
            seq = self._journal.record({'kind': 'add_edges',
                                        'etype': etype,
                                        'src': src.tolist(),
                                        'dst': dst.tolist(),
                                        'weight': None if w is None
                                        else w.tolist()})
        dl = self._deadline()
        for s in range(self._n):
            m = shard == s
            if m.any():
                # unjournaled appends are NOT idempotent (a blind resend
                # after an applied-but-unacked write duplicates edges);
                # journaled sends dedup server-side and may retry
                msg = {'op': 'add_edges', 'etype': etype,
                       'src': src[m].tolist(), 'dst': dst[m].tolist(),
                       'weight': w[m].tolist() if w is not None else None}
                if seq is not None:
                    msg['client'] = self._journal.client_id
                    msg['seq'] = seq
                out = self._call(s, msg, idempotent=seq is not None,
                                 deadline=dl)
                self._note_applied(out, seq)

    def replay_journal(self):
        """Resend every retained add_edges entry (oldest first) after a
        graph shard restart/restore; the server's journal marks make the
        replay exactly-once. Returns (entries_replayed, dedup_hits)."""
        if self._journal is None:
            return 0, 0
        before = self._journal.dedup_hits
        replayed = 0
        for seq, entry in self._journal.entries():
            src = np.asarray(entry['src'], np.int64)
            dst = np.asarray(entry['dst'], np.int64)
            w = entry.get('weight')
            w = np.asarray(w, np.float32) if w is not None else None
            shard = src % self._n
            dl = self._deadline()
            for s in range(self._n):
                m = shard == s
                if not m.any():
                    continue
                msg = {'op': 'add_edges', 'etype': entry['etype'],
                       'src': src[m].tolist(), 'dst': dst[m].tolist(),
                       'weight': w[m].tolist() if w is not None else None,
                       'client': self._journal.client_id, 'seq': seq}
                out = self._call(s, msg, idempotent=seq is not None,
                                 deadline=dl)
                self._note_applied(out, seq)
            replayed += 1
            self._journal.note_replay()
        return replayed, self._journal.dedup_hits - before

    def load_edge_file(self, etype, path, reversed=False):
        """Each server loads the rows whose src hashes to it; for the local
        all-in-one case, load on server 0 then re-shard via add_edges."""
        data = np.loadtxt(path, ndmin=2)
        src = data[:, 0].astype(np.int64)
        dst = data[:, 1].astype(np.int64)
        w = data[:, 2].astype(np.float32) if data.shape[1] > 2 else None
        if reversed:
            src, dst = dst, src
        self.add_edges(etype, src, dst, w)
        return len(src)

    def random_sample_neighboors(self, etype, ids, sample_size):
        # (sic) reference spells it "neighboors"
        ids, shard = self._shard(ids)
        dl = self._deadline()
        out = np.full((len(ids), sample_size), -1, np.int64)
        for s in range(self._n):
            m = shard == s
            if m.any():
                res = self._call(s, {'op': 'sample_neighbors', 'etype': etype,
                                     'ids': ids[m].tolist(),
                                     'sample_size': sample_size},
                                 deadline=dl)
                out[m] = res
        return out

    sample_neighbors = random_sample_neighboors

    def random_sample_nodes(self, etype, server_idx, k):
        return self._call(server_idx % self._n,
                          {'op': 'random_sample_nodes', 'etype': etype,
                           'k': k}, deadline=self._deadline())

    def pull_graph_list(self, etype, server_idx, shard, cursor, cap):
        return self._call(server_idx % self._n,
                          {'op': 'pull_graph_list', 'etype': etype,
                           'shard': shard, 'cursor': cursor, 'cap': cap},
                          deadline=self._deadline())

    def get_node_feat(self, etype, ids, dim):
        ids, shard = self._shard(ids)
        dl = self._deadline()
        out = np.zeros((len(ids), dim), np.float32)
        for s in range(self._n):
            m = shard == s
            if m.any():
                out[m] = self._call(s, {'op': 'get_node_feat', 'etype': etype,
                                        'ids': ids[m].tolist(), 'dim': dim},
                                    deadline=dl)
        return out

    def set_node_feat(self, etype, ids, feats):
        # idempotent: a resend re-writes the same feature values
        ids, shard = self._shard(ids)
        feats = np.asarray(feats, np.float32)
        dl = self._deadline()
        for s in range(self._n):
            m = shard == s
            if m.any():
                self._call(s, {'op': 'set_node_feat', 'etype': etype,
                               'ids': ids[m].tolist(),
                               'feats': feats[m].tolist()}, deadline=dl)

    def get_degree(self, etype, ids):
        ids, shard = self._shard(ids)
        dl = self._deadline()
        out = np.zeros(len(ids), np.int64)
        for s in range(self._n):
            m = shard == s
            if m.any():
                out[m] = self._call(s, {'op': 'degree', 'etype': etype,
                                        'ids': ids[m].tolist()},
                                    deadline=dl)
        return out

    def stop_server(self):
        for s in range(self._n):
            try:
                # single attempt: a dead server IS the desired end state
                self._call(s, {'op': 'stop'}, idempotent=False)
            except Exception:
                pass
        self.close()

    def close(self):
        for ch in self._channels:
            ch.close()


class GraphPyService:
    """Orchestration (graph_py_service.h:46): builds a mini graph-PS cluster
    from an ip list and hands out client/server objects."""

    def __init__(self):
        self._servers = []
        self._client = None
        self._edge_types = ('default',)

    def set_up(self, ips_str=None, shard_num=None, node_types=None,
               edge_types=None, num_servers=2):
        if edge_types:
            self._edge_types = tuple(edge_types)
        self._servers = [GraphPyServer(rank=i, edge_types=self._edge_types)
                         for i in range(num_servers)]
        for s in self._servers:
            s.start_server()
        eps = ['127.0.0.1:%d' % s.port for s in self._servers]
        self._client = GraphPyClient(eps)
        return self._client

    @property
    def client(self):
        return self._client

    def stop(self):
        if self._client:
            self._client.stop_server()
            self._client = None
        for s in self._servers:
            # the 'stop' op only shuts down serve_forever; release the
            # listening socket too so repeated set_up/stop cycles don't
            # leak fds
            try:
                s.stop_server()
            except Exception:
                pass
        self._servers = []
