"""Collective API (reference: python/paddle/distributed/collective.py:348-1578
+ operators/collective/ c_* op family).

TPU-native (SURVEY.md §5.8): a "group" is a mesh axis; inside a shard_map /
pjit trace these lower to XLA collectives over ICI (psum, all_gather,
ppermute, all_to_all). Outside a trace with world_size==1 they are
identities (the common single-process case); eager cross-device collectives
are expressed by jit-ing the caller, which is the jax execution model.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..framework.core import Tensor, run_op, wrap_out
from ..tensor._helpers import ensure_tensor
from .topology import Group
from .shard_map_compat import axis_size as _axis_size
from .env import get_world_size

__all__ = ['ReduceOp', 'new_group', 'all_reduce', 'all_gather', 'broadcast',
           'reduce', 'scatter', 'alltoall', 'send', 'recv', 'barrier',
           'split', 'wait', 'get_group']


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_GROUPS = {}
_GROUP_COUNTER = [0]


def _in_trace(x):
    return isinstance(x, jax.core.Tracer)


def new_group(ranks=None, backend=None, timeout=None):
    _GROUP_COUNTER[0] += 1
    gid = _GROUP_COUNTER[0]
    nranks = len(ranks) if ranks else get_world_size()
    g = Group(None, nranks, ranks=ranks, gid=gid)
    _GROUPS[gid] = g
    return g


def get_group(gid=0):
    return _GROUPS.get(gid)


def _axis_of(group):
    if group is None:
        return 'dp'
    return getattr(group, 'axis_name', None) or 'dp'


def _collective(name, x, trace_fn, eager_identity=True):
    """Run trace_fn if x is traced (inside shard_map), else identity at
    world size 1."""
    t = ensure_tensor(x)
    if _in_trace(t._data):
        try:
            return run_op(name, trace_fn, t)
        except NameError:
            return t
    return t if eager_identity else t


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_of(group)
    t = ensure_tensor(tensor)
    if _in_trace(t._data):
        def fn(a):
            if op == ReduceOp.SUM:
                return lax.psum(a, axis)
            if op == ReduceOp.MAX:
                return lax.pmax(a, axis)
            if op == ReduceOp.MIN:
                return lax.pmin(a, axis)
            if op == ReduceOp.AVG:
                return lax.pmean(a, axis)
            return lax.psum(a, axis)  # PROD unsupported by ICI; sum-of-logs
        out = run_op('c_allreduce', fn, t)
        tensor._data = out._data
        tensor._grad_node = out._grad_node
        tensor._node_out_idx = out._node_out_idx
        tensor.stop_gradient = out.stop_gradient
        return tensor
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = _axis_of(group)
    t = ensure_tensor(tensor)
    if _in_trace(t._data):
        out = run_op('c_allgather',
                     lambda a: lax.all_gather(a, axis), t)
        n = out.shape[0]
        from ..tensor.manipulation import unstack
        parts = unstack(out, axis=0)
        tensor_list.extend(parts)
        return parts
    tensor_list.append(t)
    return [t]


def broadcast(tensor, src=0, group=None, sync_op=True):
    # SPMD: all replicas hold the value; broadcast is identity in-trace
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        tensor._data = ensure_tensor(tensor_list[0])._data
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    axis = _axis_of(group)
    if isinstance(in_tensor_list, (list, tuple)):
        from ..tensor.manipulation import stack, unstack
        stacked = stack(list(in_tensor_list), axis=0)
    else:
        stacked = ensure_tensor(in_tensor_list)
    if _in_trace(stacked._data):
        out = run_op('c_alltoall',
                     lambda a: lax.all_to_all(a, axis, 0, 0), stacked)
        from ..tensor.manipulation import unstack
        parts = unstack(out, axis=0)
        if out_tensor_list is not None:
            out_tensor_list.extend(parts)
        return parts
    if out_tensor_list is not None:
        out_tensor_list.extend(list(in_tensor_list))
    return list(in_tensor_list)


def send(tensor, dst=0, group=None, sync_op=True):
    """In-trace: ppermute to the next rank (pipeline p2p); the paired recv
    is the same ppermute's output on the receiver."""
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


def p2p_shift(x, axis_name, shift=1):
    """ppermute helper used by pipeline/ring schedules: returns x from the
    rank at (idx - shift) along axis."""
    t = ensure_tensor(x)

    def fn(a):
        n = _axis_size(axis_name)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(a, axis_name, perm)
    return run_op('ppermute', fn, t)


def barrier(group=None):
    pass


def wait(tensor, group=None, use_calc_stream=True):
    # XLA orders async collectives; block_until_ready for eager parity
    t = ensure_tensor(tensor)
    if not _in_trace(t._data):
        try:
            t._data.block_until_ready()
        except AttributeError:
            pass
    return tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference distributed.split (collective.py:748): here the TP layers in
    fleet.meta_parallel are the supported surface; this shim maps to them."""
    from .meta_parallel.mp_layers import (ColumnParallelLinear,
                                          RowParallelLinear,
                                          VocabParallelEmbedding)
    if operation == 'linear':
        cls = ColumnParallelLinear if axis == 1 else RowParallelLinear
        layer = cls(size[0], size[1], weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    gather_output=gather_out)
        return layer(x)
    if operation == 'embedding':
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError("unsupported split operation %r" % operation)
