"""Pipeline layer partitioning (reference: fleet/meta_parallel/
parallel_layers/pp_layers.py:76 PipelineLayer, SegmentLayers:23,
SharedLayerDesc:62).

TPU-native execution of the schedule lives in pipeline.py (scan+ppermute);
this module keeps the declarative stage-partition API: a PipelineLayer
describes the model as a flat list of LayerDescs and assigns contiguous
segments to 'pp' mesh ranks.
"""
import numpy as np

from ... import nn

__all__ = ['LayerDesc', 'SharedLayerDesc', 'PipelineLayer', 'SegmentLayers']


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied weights across stages (e.g. embedding/unembedding)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr='weight',
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    def __init__(self, layers_desc, num_parts, method='uniform'):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self):
        n = len(self.layers_desc)
        if self.method == 'uniform':
            return self.uniform(n, self.num_parts)
        if self.method.startswith('layer:'):
            # segment at layers whose class name matches
            name = self.method.split(':', 1)[1]
            marks = [i for i, d in enumerate(self.layers_desc)
                     if getattr(d, 'layer_cls', type(None)).__name__ == name]
            # distribute matched blocks evenly over parts
            per = max(1, len(marks) // self.num_parts)
            bounds = [0]
            for p in range(1, self.num_parts):
                idx = min(p * per, len(marks) - 1)
                bounds.append(marks[idx])
            bounds.append(n)
            return bounds
        raise ValueError(self.method)

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = num_items // num_parts
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result


class PipelineLayer(nn.Layer):
    """Declarative pipeline container. On a 1-stage mesh it runs like
    Sequential; the pipeline engine consumes `stage_segments` to build the
    scan/ppermute schedule."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method='uniform', recompute_interval=0,
                 **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self.segment_parts = SegmentLayers(
            self._layers_desc, self._num_stages, seg_method).do_segment()

        self._shared = {}
        self.run_function = []
        built = nn.LayerList()
        for i, d in enumerate(self._layers_desc):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    self._shared[d.layer_name] = d.build_layer()
                layer = self._shared[d.layer_name]
                fwd = d.forward_func
                if fwd is not None:
                    self.run_function.append(
                        (lambda l, f: (lambda x: f(l, x)))(layer, fwd))
                else:
                    self.run_function.append(layer)
                built.append(layer)
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
                self.run_function.append(layer)
                built.append(layer)
            elif callable(d) and not isinstance(d, nn.Layer):
                self.run_function.append(d)
            else:
                self.run_function.append(d)
                built.append(d)
        self._built = built

    @property
    def stage_segments(self):
        return self.segment_parts

    def get_stage_fns(self):
        """List of per-stage callables (composition of the segment)."""
        fns = []
        for s in range(self._num_stages):
            lo, hi = self.segment_parts[s], self.segment_parts[s + 1]
            seg = self.run_function[lo:hi]

            def stage_fn(x, seg=seg):
                for f in seg:
                    x = f(x)
                return x
            fns.append(stage_fn)
        return fns

    def forward(self, x):
        from .. import pipeline as pp_mod
        pp_state = pp_mod.pipeline_state()
        if pp_state is not None and self._num_stages > 1 and self.training:
            # thread this container's params AND buffers through the pp
            # shard_map as explicit replicated inputs (see
            # pipeline_stage_fns doc) — a closure-captured outer tracer
            # (e.g. a mask buffer) would recreate the Auto-mesh aval
            # failure. Buffers are read-only inside a pipelined stage
            # (running-stat mutation doesn't survive the restore, same
            # stance as pipeline_blocks' buffer guard).
            tmap = dict(self.named_parameters())
            for n, b in self.named_buffers():
                if b is not None:
                    tmap.setdefault(n, b)
            params = {n: t._data for n, t in tmap.items()}

            def rebind(params_in):
                saved = [(tmap[n], tmap[n]._data) for n in params_in]
                for n, arr in params_in.items():
                    tmap[n]._data = arr

                def restore():
                    for t, arr in saved:
                        t._data = arr
                return restore

            return pp_mod.pipeline_stage_fns(self.get_stage_fns(), x,
                                             pp_state, params=params,
                                             rebind=rebind)
        for f in self.run_function:
            x = f(x)
        return x
