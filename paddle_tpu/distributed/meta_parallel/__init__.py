"""Hybrid-parallel layers (reference: python/paddle/distributed/fleet/
meta_parallel/)."""
from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,  # noqa: F401
                        RowParallelLinear, ParallelCrossEntropy)
from .pp_layers import PipelineLayer, LayerDesc, SharedLayerDesc  # noqa: F401
from .random_ctrl import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed  # noqa: F401
from .sync_bn import SyncBatchNorm  # noqa: F401
from .parallel_base import (PipelineParallel, TensorParallel,  # noqa: F401
                            ShardingParallel)
