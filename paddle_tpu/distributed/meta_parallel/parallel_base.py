"""MetaParallel wrappers (reference: fleet/meta_parallel/pipeline_parallel.py
PipelineParallel:32, tensor_parallel.py, sharding_parallel.py).

These wrap a model per the hybrid config; the heavy lifting (shardings,
schedules) is delegated to distributed/strategy.py and
distributed/pipeline.py — under SPMD the wrapper's job is bookkeeping, not
communication.
"""
from ..parallel import DataParallel


class _MetaParallelBase:
    def __init__(self, layers, hcg, strategy=None):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__dict__['_layers'], name)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


class TensorParallel(_MetaParallelBase):
    pass


class ShardingParallel(_MetaParallelBase):
    pass


class PipelineParallel(_MetaParallelBase):
    """train_batch parity (pipeline_parallel.py:109): runs the scan-based
    1F1B/GPipe schedule from distributed/pipeline.py."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        self._engine = None

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from ..pipeline import PipelineEngine
        if self._engine is None:
            self._engine = PipelineEngine(self._layers, optimizer,
                                          self._hcg)
        inputs, labels = data
        loss = self._engine.step(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
