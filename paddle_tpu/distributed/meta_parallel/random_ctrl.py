"""Megatron-style RNG state isolation (reference: fleet/meta_parallel/
parallel_layers/random.py:24 RNGStatesTracker).

TPU-native: tracked states are jax PRNG keys; 'global' dropout must agree
across mp ranks, 'local' (e.g. within-TP-shard) must differ — achieved by
fold_in of the mp rank.
"""
import contextlib

import jax

from ...framework import random as rng_mod

MODEL_PARALLEL_RNG = 'model_parallel_rng'


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError('seed %s already exists' % seed)
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError('state %s already exists' % name)
        self.states_[name] = jax.random.PRNGKey(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError('state %s does not exist' % name)
        gen = rng_mod.default_generator()
        orig = gen._key
        gen._key = self.states_[name]
        try:
            yield
        finally:
            self.states_[name] = gen._key
            gen._key = orig


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom
    seed = seed or (pyrandom.getrandbits(32))
    global_seed = seed
    local_seed = seed + 1024 + 0  # + mp_rank under multi-controller
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
    rng_mod.seed(global_seed)
