"""Tensor-parallel layers (reference: fleet/meta_parallel/parallel_layers/
mp_layers.py:30,97,170,249 — VocabParallelEmbedding / ColumnParallelLinear /
RowParallelLinear / ParallelCrossEntropy).

TPU-native: a TP layer is an ordinary layer whose params carry 'mp'
PartitionSpec placements; XLA's SPMD partitioner inserts the all-gather /
reduce-scatter the reference implements via _c_identity/_mp_allreduce ops.
`sharding_constraint` pins activation layouts where inference would pick the
wrong one (the analog of the reference's explicit c_* calls).
"""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...framework.core import Tensor, run_op
from ...tensor._helpers import ensure_tensor
from ... import nn
from ...nn import functional as F
from ...nn import initializer as I


def sharding_constraint(x, spec):
    """with_sharding_constraint that is a no-op outside jit."""
    t = ensure_tensor(x)
    if not isinstance(t._data, jax.core.Tracer):
        return t

    def fn(a):
        try:
            return jax.lax.with_sharding_constraint(a, P(*spec))
        except Exception:
            return a
    return run_op('sharding_constraint', fn, t)


class VocabParallelEmbedding(nn.Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.placement = ('mp', None)
        self.weight.is_distributed = True

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.placement = (None, 'mp')
        self.weight.is_distributed = True
        self.gather_output = gather_output
        if has_bias is None or has_bias:
            self.bias = self.create_parameter(shape=[out_features],
                                              is_bias=True)
            self.bias.placement = ('mp',)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = sharding_constraint(
                out, [None] * (out.ndim - 1) + [None])
        else:
            out = sharding_constraint(
                out, [None] * (out.ndim - 1) + ['mp'])
        return out


class RowParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.placement = ('mp', None)
        self.weight.is_distributed = True
        self.input_is_parallel = input_is_parallel
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features],
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = sharding_constraint(x, [None] * (ensure_tensor(x).ndim - 1) +
                                    ['mp'])
        out = F.linear(x, self.weight, self.bias)
        # partial sums reduce automatically (XLA inserts psum over 'mp')
        out = sharding_constraint(out, [None] * (out.ndim - 1) + [None])
        return out


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel softmax CE (reference: mp_layers.py:249 backed by
    c_softmax_with_cross_entropy_op.cu). Under SPMD the plain CE lowers to
    the same pattern when logits are sharded on vocab."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction='none',
                               ignore_index=self.ignore_index)
