"""SyncBatchNorm for meta_parallel namespace parity — see nn.layer.norm
(stats sync is implicit under pjit; the class is re-exported)."""
from ...nn.layer.norm import SyncBatchNorm  # noqa: F401
