"""Cloud cluster discovery (reference: distributed/cloud_utils.py — reads
PADDLE_TRAINERS / POD_IP etc. set by cloud schedulers to assemble the
trainer endpoint list)."""
import os

__all__ = ['get_cloud_cluster']


def get_cloud_cluster(args_node_ips=None, args_node_ip=None, args_port=6170,
                      selected_devices=None):
    """Returns (node_ips, current_ip, trainer_endpoints) from cloud env
    with CLI-args fallback."""
    import re as _re
    node_ips = os.environ.get('PADDLE_TRAINERS', args_node_ips or '127.0.0.1')
    if isinstance(node_ips, str):
        node_ips = [ip for ip in _re.split(r'[,\s]+', node_ips) if ip]
    cur_ip = os.environ.get('POD_IP', args_node_ip or node_ips[0])
    port = int(os.environ.get('PADDLE_PORT', args_port))
    n_per = max(len(selected_devices or [0]), 1)
    endpoints = ['%s:%d' % (ip, port + i)
                 for ip in node_ips for i in range(n_per)]
    return node_ips, cur_ip, endpoints


def _get_trainers_num():
    return int(os.environ.get('PADDLE_TRAINERS_NUM', 1))
