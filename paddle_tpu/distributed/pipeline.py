"""Pipeline-parallel engine (reference: framework/section_worker.cc:104
micro-batch 1F1B loop + fleet/meta_parallel/pipeline_parallel.py:109
train_batch, pp_layers.py:76 stage partition).

TPU-native (SURVEY.md §7.4 hard-part #2): no executor schedules stages —
the schedule IS a jax program. A GPipe loop runs inside `jax.shard_map`
manual over the 'pp' mesh axis only (`axis_names={'pp'}`): each tick every
stage applies its segment and the activations rotate forward with
ppermute over ICI; dp/mp/sharding stay auto-sharded by XLA inside the
region, so pipeline composes with the other axes without manual
collectives. Two stage forms:

  pipeline_blocks     — homogeneous block lists (transformer): per-stage
                        params are STACKED [pp, layers/pp, ...] and
                        pp-sharded, so each device stores and computes
                        only its stage's layers (the memory win).
  pipeline_stage_fns  — heterogeneous declarative PipelineLayer segments:
                        a lax.switch picks this rank's segment; params are
                        closure-captured (schedule-real, memory-neutral),
                        which also makes SharedLayerDesc tied weights
                        work for free (same traced array in two stages).

Like sp (distributed/sp.py), the pp state is scoped to a TrainStep so
eval/generation calls between steps run the plain sequential forward.
Backward is jax AD through scan+ppermute (GPipe: all microbatches forward,
then reverse); combine with recompute for the activation-memory win.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework import functional as func_mod
from ..framework.core import Tensor

__all__ = ['PipelineEngine', 'make_pp_state', 'pp_scope', 'pipeline_state',
           'pipeline_blocks', 'pipeline_stage_fns']

_STATE = {'active': None}


def make_pp_state(mesh, n_stages, n_micro=None, axis='pp', remat=False):
    """Build (without activating) a pipeline routing state.

    n_micro: microbatches per step (reference PipelineConfig
    accumulate_steps); defaults to n_stages (minimum that fills the pipe).
    remat: checkpoint each layer application inside the stage scan.
    """
    return {'mesh': mesh, 'axis': axis, 'n_stages': int(n_stages),
            'n_micro': int(n_micro or n_stages), 'remat': bool(remat)}


def pipeline_state():
    return _STATE['active']


class pp_scope:
    """Activate a pp state only around a step's trace/execution."""

    def __init__(self, state):
        self._state = state

    def __enter__(self):
        self._saved = _STATE['active']
        if self._state is not None:
            _STATE['active'] = self._state
        return self

    def __exit__(self, *exc):
        _STATE['active'] = self._saved
        return False


def _gpipe_loop(stage_apply, micro, n_stages, n_micro, axis, dtype_like):
    """The schedule: n_micro + n_stages - 1 ticks; stage 0 ingests
    microbatch t, every stage applies its segment, ppermute rotates
    activations forward; the last stage's outputs are psum-broadcast so
    the (replicated-over-pp) loss/head code downstream sees all of them.

    stage_apply(x_array, stage_id) -> y_array, like-shaped with x.
    micro: [n_micro, mb, ...]; returns [n_micro, mb, ...].
    """
    stage = lax.axis_index(axis)
    n_ticks = n_micro + n_stages - 1
    mb_shape = micro.shape[1:]

    def tick(buf, t):
        idx = jnp.clip(t, 0, n_micro - 1)
        inject = jnp.where(stage == 0, micro[idx], buf)
        y = stage_apply(inject, stage)
        nxt = lax.ppermute(y, axis,
                           [(i, (i + 1) % n_stages)
                            for i in range(n_stages)])
        return nxt, y

    _, outs = lax.scan(tick, jnp.zeros(mb_shape, dtype_like),
                       jnp.arange(n_ticks))
    valid = outs[n_stages - 1:]  # meaningful on the last stage only
    # broadcast in f32: psum over a partial-manual region check-fails in
    # the XLA CPU backend on bf16 operands ("invalid binary opcode copy")
    out = lax.psum(
        jnp.where(stage == n_stages - 1, valid.astype(jnp.float32),
                  jnp.zeros(valid.shape, jnp.float32)),
        axis)
    return out.astype(valid.dtype)


def _split_micro(x, n_micro):
    b = x.shape[0]
    if b % n_micro:
        raise ValueError('batch %d not divisible by n_micro=%d'
                         % (b, n_micro))
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def pipeline_blocks(blocks, x, state):
    """Run a homogeneous Layer list through the GPipe schedule with
    per-stage params stacked [pp, layers/pp, ...] and pp-sharded.

    blocks: structurally identical Layers (e.g. GPTBlock list); their
    activations must be like-shaped (transformer residual stream).
    x: Tensor [B, ...]. Returns Tensor [B, ...].

    Note: inside the stage lax.scan all layers of a stage share one
    dropout key draw (the body traces once) — use dropout=0 under pp for
    exact parity with the sequential forward.
    """
    st = state
    n_stages, n_micro, axis = st['n_stages'], st['n_micro'], st['axis']
    blocks = list(blocks)
    n_layers = len(blocks)
    if n_layers % n_stages:
        raise ValueError('n_layers %d %% pp %d != 0'
                         % (n_layers, n_stages))
    per = n_layers // n_stages
    template = blocks[0]
    if any(b is not None for _, b in template.named_buffers()):
        raise NotImplementedError(
            'pipeline_blocks requires buffer-free blocks (running-stat '
            'layers inside a pipelined stage are not supported)')
    pnames = [n for n, _ in template.named_parameters()]

    # stack per-layer params: {name: [pp, per, ...]}. The storage params
    # stay ordinary named entries (optimizer/shardings unchanged); the
    # stack happens in-graph, and its transpose un-stacks the grads.
    stacked = {}
    for n in pnames:
        arrs = [dict(b.named_parameters())[n]._data for b in blocks]
        a = jnp.stack(arrs)
        stacked[n] = a.reshape((n_stages, per) + a.shape[1:])

    remat = st['remat']

    def apply_layer(xb, layer_params):
        out, _ = func_mod.functional_call(
            template, layer_params, {},
            args=(Tensor(xb, stop_gradient=False),))
        return out

    def stage_apply(xb, stage_id):
        # params for THIS rank's stage arrive with the pp dim localized
        def body(c, lp):
            f = apply_layer
            if remat:
                f = jax.checkpoint(apply_layer)
            return f(c, lp), None
        y, _ = lax.scan(body, xb, stage_apply.params)
        return y

    def pp_body(stacked_local, micro):
        local = {n: a[0] for n, a in stacked_local.items()}  # strip pp dim
        stage_apply.params = local
        return _gpipe_loop(stage_apply, micro, n_stages, n_micro, axis,
                           micro.dtype)

    in_specs = ({n: P(axis) for n in stacked}, P())
    fn = jax.shard_map(pp_body, mesh=st['mesh'], in_specs=in_specs,
                       out_specs=P(), axis_names={axis}, check_vma=False)
    x_arr = x._data if isinstance(x, Tensor) else x
    micro = _split_micro(x_arr, n_micro)
    out = fn(stacked, micro)
    out = out.reshape(x_arr.shape[:1] + out.shape[2:])
    return Tensor(out, stop_gradient=False)


def pipeline_stage_fns(stage_fns, x, state):
    """GPipe over heterogeneous per-stage callables (PipelineLayer
    segments): lax.switch picks this rank's segment each tick. Segment
    boundaries must be like-shaped (switch/ppermute need one aval).
    Params are closure-captured: every rank holds all params (replicated)
    — the schedule and comm pattern are real, the per-stage memory win
    needs the homogeneous pipeline_blocks form."""
    st = state
    n_stages, n_micro, axis = st['n_stages'], st['n_micro'], st['axis']
    if len(stage_fns) != n_stages:
        raise ValueError('%d stage fns != pp degree %d'
                         % (len(stage_fns), n_stages))

    def wrap(fn):
        def g(arr):
            out = fn(Tensor(arr, stop_gradient=False))
            return out._data if isinstance(out, Tensor) else out
        return g

    branches = [wrap(f) for f in stage_fns]

    def stage_apply(xb, stage_id):
        return lax.switch(stage_id, branches, xb)

    def pp_body(micro):
        return _gpipe_loop(stage_apply, micro, n_stages, n_micro, axis,
                           micro.dtype)

    fn = jax.shard_map(pp_body, mesh=st['mesh'], in_specs=P(),
                       out_specs=P(), axis_names={axis}, check_vma=False)
    x_arr = x._data if isinstance(x, Tensor) else x
    out = fn(_split_micro(x_arr, n_micro))
    out = out.reshape(x_arr.shape[:1] + out.shape[2:])
    return Tensor(out, stop_gradient=False)


class PipelineEngine:
    """Executes PipelineLayer models: microbatch split + GPipe schedule +
    grads + optimizer, jitted once (reference SectionWorker TrainFiles +
    PipelineParallel.train_batch)."""

    def __init__(self, pipeline_layer, optimizer, hcg, n_micro=None):
        self.layer = pipeline_layer
        self.optimizer = optimizer
        self.hcg = hcg
        pp = max(hcg.get_pipe_parallel_world_size(), 1)
        self.n_micro = n_micro or max(pp, 1)
        self._step = None
        self._pp_state = None
        if pp > 1:
            self._pp_state = make_pp_state(hcg.mesh, n_stages=pp,
                                           n_micro=self.n_micro)

    def _build(self):
        model = self.layer
        loss_fn = model._loss_fn

        def step_loss(out, labels):
            return loss_fn(out, labels)

        self._step = func_mod.TrainStep(model, step_loss, self.optimizer,
                                        mesh=self.hcg.mesh,
                                        pp_state=self._pp_state)

    def step(self, inputs, labels):
        if self._step is None:
            self._build()
        return self._step(inputs, labels)
