"""Pipeline-parallel engine (reference: framework/section_worker.cc:104
micro-batch 1F1B loop + fleet/meta_parallel/pipeline_parallel.py).

TPU-native (SURVEY.md §7.4 hard-part #2): no executor schedules stages —
the schedule is a jax program. Stage params live sharded on the 'pp' mesh
axis; a lax.scan over microbatches rotates activations between stages with
ppermute inside shard_map (GPipe-style; every stage computes every scan
step, bubble = pp-1 steps at fill+drain, matching 1F1B's steady state
utilization for activations-limited regimes when combined with remat).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework import functional as func_mod
from ..framework.core import Tensor

__all__ = ['PipelineEngine', 'pipeline_spmd_step']


def _stack_stage_params(stage_params):
    """[{name: arr}, ...] per stage -> {name: stacked [pp, ...]} requires
    homogeneous stages (same structure per stage — the transformer case)."""
    keys = stage_params[0].keys()
    return {k: jnp.stack([sp[k] for sp in stage_params]) for k in keys}


def pipeline_spmd_step(stage_fn, n_stages, n_micro, axis_name='pp'):
    """Build a shard_map-able function: each pp rank applies stage_fn with
    its own params; activations ppermute forward each tick.

    stage_fn(params_slice, x) -> y ; all stages must map like-shaped
    activations (transformer blocks). Returns fn(stacked_params, microbatches)
    -> final-stage outputs stacked [n_micro, ...].
    """

    def per_stage(params, micro_in):
        # params: this rank's slice (leading pp axis stripped by shard_map)
        # micro_in: [n_micro, mb, ...] (replicated input; stage0 consumes)
        stage_id = lax.axis_index(axis_name)
        n_ticks = n_micro + n_stages - 1
        mb_shape = micro_in.shape[1:]

        def tick(carry, t):
            buf = carry  # activation arriving at this stage this tick
            # stage 0 ingests microbatch t (if in range)
            idx = jnp.clip(t, 0, n_micro - 1)
            injected = jnp.where(stage_id == 0,
                                 micro_in[idx],
                                 buf)
            out = stage_fn(params, injected)
            # pass to next stage
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            nxt = lax.ppermute(out, axis_name, perm)
            # last stage's output at tick t corresponds to microbatch
            # t - (n_stages - 1)
            return nxt, out

        _, outs = lax.scan(tick, jnp.zeros(mb_shape, micro_in.dtype),
                           jnp.arange(n_ticks))
        # collect the last stage's valid outputs
        valid = outs[n_stages - 1:]
        return valid

    return per_stage


class PipelineEngine:
    """Executes PipelineLayer models: microbatch split + scan schedule +
    grads + optimizer, jitted once."""

    def __init__(self, pipeline_layer, optimizer, hcg, n_micro=None):
        self.layer = pipeline_layer
        self.optimizer = optimizer
        self.hcg = hcg
        self.n_micro = n_micro or max(hcg.get_pipe_parallel_world_size(), 1)
        self._step = None

    def step(self, inputs, labels):
        # Round-1 semantics: run the declarative model (correctness path).
        # The scan/ppermute schedule is exercised via pipeline_spmd_step in
        # tests; full fusion of arbitrary PipelineLayers lands with the
        # dryrun harness.
        model = self.layer
        loss_fn = model._loss_fn
        out = model(inputs)
        loss = loss_fn(out, labels)
        loss.backward()
        self.optimizer.step()
        self.optimizer.clear_grad()
        return loss
