"""Pipeline-parallel engine (reference: framework/section_worker.cc:104
micro-batch 1F1B loop + fleet/meta_parallel/pipeline_parallel.py:109
train_batch, pp_layers.py:76 stage partition).

TPU-native (SURVEY.md §7.4 hard-part #2): no executor schedules stages —
the schedule IS a jax program. A GPipe loop runs inside `jax.shard_map`
manual over the 'pp' mesh axis only (`axis_names={'pp'}`): each tick every
stage applies its segment and the activations rotate forward with
ppermute over ICI; dp/mp/sharding stay auto-sharded by XLA inside the
region, so pipeline composes with the other axes without manual
collectives. Two stage forms:

  pipeline_blocks     — homogeneous block lists (transformer): per-stage
                        params are STACKED [pp, layers/pp, ...] and
                        pp-sharded, so each device stores and computes
                        only its stage's layers (the memory win).
  pipeline_stage_fns  — heterogeneous declarative PipelineLayer segments:
                        a lax.switch picks this rank's segment; params are
                        closure-captured (schedule-real, memory-neutral),
                        which also makes SharedLayerDesc tied weights
                        work for free (same traced array in two stages).

Like sp (distributed/sp.py), the pp state is scoped to a TrainStep so
eval/generation calls between steps run the plain sequential forward.
Backward is jax AD through scan+ppermute (GPipe: all microbatches forward,
then reverse); combine with recompute for the activation-memory win.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework import functional as func_mod
from ..framework import random as rng_mod
from ..framework.core import Tensor
from .shard_map_compat import shard_map
from .auto_parallel import tuner as ap_tuner

__all__ = ['PipelineEngine', 'make_pp_state', 'pp_scope', 'pipeline_state',
           'pipeline_blocks', 'pipeline_stage_fns']

_STATE = {'active': None}


def _cpu_mesh(mesh):
    """True when the pp mesh runs on the XLA CPU backend, whose
    AllReducePromotion pass aborts on bf16 all-reduces once the SPMD
    partitioner has inserted a copy into the reduction region. All f32
    boundary casts in this module are gated on this — TPU keeps bf16
    collectives (half the ICI bytes)."""
    try:
        return mesh.devices.flat[0].platform == 'cpu'
    except Exception:
        return False


def make_pp_state(mesh, n_stages, n_micro=None, axis='pp', remat=False,
                  schedule='gpipe'):
    """Build (without activating) a pipeline routing state.

    n_micro: microbatches per step (reference PipelineConfig
    accumulate_steps); defaults to n_stages for GPipe (minimum that fills
    the pipe) and 2*n_stages for 1F1B (the regime where its O(pp) stash
    beats GPipe's O(n_micro)).
    remat: checkpoint each layer application inside the stage scan.
    schedule: 'gpipe' (this module) or '1f1b' (pipeline_1f1b.py —
    interleaved fwd/bwd, loss inside the last stage).
    """
    schedule = schedule.lower().replace('-', '')
    if schedule not in ('gpipe', '1f1b', 'fthenb'):
        raise ValueError('unknown pipeline schedule %r' % schedule)
    if schedule == 'fthenb':
        schedule = 'gpipe'
    default_micro = 2 * n_stages if schedule == '1f1b' else n_stages
    return {'mesh': mesh, 'axis': axis, 'n_stages': int(n_stages),
            'n_micro': int(n_micro or default_micro), 'remat': bool(remat),
            'schedule': schedule}


def pipeline_state():
    return _STATE['active']


class pp_scope:
    """Activate a pp state only around a step's trace/execution."""

    def __init__(self, state):
        self._state = state

    def __enter__(self):
        self._saved = _STATE['active']
        if self._state is not None:
            _STATE['active'] = self._state
        return self

    def __exit__(self, *exc):
        _STATE['active'] = self._saved
        return False


def _gpipe_loop(stage_apply, micro, n_stages, n_micro, axis, dtype_like,
                wire_dtype, base_key):
    """The schedule: n_micro + n_stages - 1 ticks; stage 0 ingests
    microbatch t, every stage applies its segment, ppermute rotates
    activations forward; the last stage's outputs are psum-broadcast so
    the (replicated-over-pp) loss/head code downstream sees all of them.

    stage_apply(x_array, stage_id, tick_key) -> y_array, like-shaped
    with x. micro: [n_micro, mb, ...]; returns [n_micro, mb, ...].
    base_key: per-step PRNG key (callers always thread one); each tick
    derives fold_in(base_key, microbatch_index) so dropout masks differ
    per microbatch (and per step, the base key being per-step).
    """
    stage = lax.axis_index(axis)
    n_ticks = n_micro + n_stages - 1
    # wire_dtype: what collectives (ppermute/psum) carry. f32 on the CPU
    # backend — bf16 collectives there abort in AllReducePromotion once
    # the SPMD partitioner inserts a copy into the reduction region (see
    # _cpu_mesh); on TPU it equals the compute dtype (half the ICI bytes)
    wire = wire_dtype or dtype_like

    def tick(buf, t):
        idx = jnp.clip(t, 0, n_micro - 1)
        inject = jnp.where(stage == 0, micro[idx], buf).astype(dtype_like)
        # key by the microbatch THIS stage is processing (t - stage),
        # so a microbatch keeps one mask set as it moves down the pipe
        i_mb = jnp.clip(t - stage, 0, n_micro - 1)
        tick_key = jax.random.fold_in(base_key, i_mb)
        y = stage_apply(inject, stage, tick_key)
        nxt = lax.ppermute(y.astype(wire), axis,
                           [(i, (i + 1) % n_stages)
                            for i in range(n_stages)])
        return nxt, y

    _, outs = lax.scan(tick, jnp.zeros(micro.shape[1:], wire),
                       jnp.arange(n_ticks))
    valid = outs[n_stages - 1:]  # meaningful on the last stage only
    out = lax.psum(
        jnp.where(stage == n_stages - 1, valid.astype(wire),
                  jnp.zeros(valid.shape, wire)),
        axis)
    return out.astype(valid.dtype)


def _split_micro(x, n_micro):
    b = x.shape[0]
    if b % n_micro:
        raise ValueError('batch %d not divisible by n_micro=%d'
                         % (b, n_micro))
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def pipeline_blocks(blocks, x, state):
    """Run a homogeneous Layer list through the GPipe schedule with
    per-stage params stacked [pp, layers/pp, ...] and pp-sharded.

    blocks: structurally identical Layers (e.g. GPTBlock list); their
    activations must be like-shaped (transformer residual stream).
    x: Tensor [B, ...]. Returns Tensor [B, ...].

    Dropout: when the blocks contain active dropout, a per-step base key
    is threaded through the schedule and folded with (microbatch, global
    layer) indices, so masks differ per microbatch/layer/step (the
    reference's parallel_layers/random.py capability). Masks do NOT
    bit-match the sequential forward's stream — parity tests run in eval
    mode or dropout=0.
    """
    st = state
    n_stages, n_micro, axis = st['n_stages'], st['n_micro'], st['axis']
    blocks = list(blocks)
    n_layers = len(blocks)
    # uneven layer counts: pad the stack to pp*ceil(n/pp) with zero
    # "ghost" layers masked to identity in the stage scan (their compute
    # is wasted but their output — and gradient contribution — is
    # discarded by the select; the reference's seg_method splits layer
    # counts unevenly the same way, pp_layers.py:76)
    per = -(-n_layers // n_stages)
    n_pad = n_stages * per - n_layers
    template = blocks[0]
    if any(b is not None for _, b in template.named_buffers()):
        raise NotImplementedError(
            'pipeline_blocks requires buffer-free blocks (running-stat '
            'layers inside a pipelined stage are not supported)')
    pnames = [n for n, _ in template.named_parameters()]

    # stack per-layer params: {name: [pp, per, ...]}. The storage params
    # stay ordinary named entries (optimizer/shardings unchanged); the
    # stack happens in-graph, and its transpose un-stacks the grads
    # (ghost entries are constants — no grad flows to them).
    stacked = {}
    for n in pnames:
        arrs = [dict(b.named_parameters())[n]._data for b in blocks]
        a = jnp.stack(arrs)
        if n_pad:
            a = jnp.concatenate(
                [a, jnp.zeros((n_pad,) + a.shape[1:], a.dtype)])
        stacked[n] = a.reshape((n_stages, per) + a.shape[1:])

    remat = st['remat']

    def apply_layer(xb, layer_params, layer_key):
        with rng_mod.key_scope(layer_key):
            out, _ = func_mod.functional_call(
                template, layer_params, {},
                args=(Tensor(xb, stop_gradient=False),))
        return out

    def stage_apply(xb, stage_id, tick_key):
        # params for THIS rank's stage arrive with the pp dim localized
        def body(c, xs):
            lp, lk, j = xs
            f = jax.checkpoint(apply_layer) if remat else apply_layer
            out = f(c, lp, lk)
            if n_pad:
                out = jnp.where(stage_id * per + j < n_layers, out, c)
            return out, None
        # decorrelate by GLOBAL layer index: stage*per + local j
        lkeys = jax.vmap(lambda j: jax.random.fold_in(
            tick_key, stage_id * per + j))(jnp.arange(per))
        y, _ = lax.scan(body, xb,
                        (stage_apply.params, lkeys, jnp.arange(per)))
        return y

    x_arr = x._data if isinstance(x, Tensor) else x
    dtype_like = x_arr.dtype
    wire = jnp.float32 if _cpu_mesh(st['mesh']) else dtype_like
    # the key ALWAYS threads (a heuristic "does this model draw RNG?"
    # check would silently bake one mask per trace for any dropout form
    # it missed — e.g. a direct F.dropout call); unused keys cost a few
    # fold_ins per tick and are DCE'd by XLA
    base_key = rng_mod.next_key()

    def pp_body(stacked_local, micro, key_in):
        local = {n: a[0] for n, a in stacked_local.items()}  # strip pp dim
        stage_apply.params = local
        return _gpipe_loop(stage_apply, micro, n_stages, n_micro, axis,
                           dtype_like, wire, base_key=key_in)

    fn = shard_map(pp_body, mesh=st['mesh'],
                   in_specs=({n: P(axis) for n in stacked}, P(), P()),
                   out_specs=P(), axis_names={axis}, check_vma=False)
    # the replicated micro operand crosses the boundary in the wire dtype:
    # its transpose is a psum over pp (f32 on CPU, see _cpu_mesh; the
    # stacked params are pp-sharded so their transpose needs no psum)
    micro = _split_micro(x_arr, n_micro).astype(wire)
    # pin the Auto-axis shardings at the region boundary (auto_parallel
    # planner): the micro reshape and the stacked stage params are where
    # GSPMD otherwise guesses and falls back to involuntary replication
    # inside the while body (MULTICHIP r05 cfg5 warnings). Specs come
    # from a tuned plan artifact when PADDLE_TPU_PLAN_DIR has one for
    # this mesh, else from the analytic planner.
    plan = ap_tuner.resolve_plan_for_state(st)
    if plan is not None:
        stacked = plan.constrain_stacked(stacked)
        micro = plan.constrain_micro(micro)
    out = fn(stacked, micro, base_key)
    if plan is not None:
        out = plan.constrain_micro(out)
    out = out.reshape(x_arr.shape[:1] + out.shape[2:]).astype(dtype_like)
    if plan is not None:
        out = plan.constrain_batch(out)
    return Tensor(out, stop_gradient=False)


def pipeline_stage_fns(stage_fns, x, state, params=None, rebind=None):
    """GPipe over heterogeneous per-stage callables (PipelineLayer
    segments): lax.switch picks this rank's segment each tick. Segment
    boundaries must be like-shaped (switch/ppermute need one aval).

    params/rebind thread the stage fns' parameter arrays through the
    shard_map boundary as explicit replicated inputs instead of closure
    captures: `params` is a {name: array} dict and `rebind(params)` swaps
    the (inner-tracer) arrays into the live layers, returning a restore
    thunk. Closure-captured outer tracers would otherwise carry
    Auto-mesh avals into the Manual pp region, which the scan transpose
    rejects (zeros_like on a mismatched context mesh). Every rank holds
    all params (replicated) — the schedule and comm pattern are real,
    the per-stage memory win needs the homogeneous pipeline_blocks
    form. Tied weights (SharedLayerDesc) are one dict entry used by two
    stages: their cotangents sum, which is exactly the tied-grad rule."""
    st = state
    n_stages, n_micro, axis = st['n_stages'], st['n_micro'], st['axis']
    if len(stage_fns) != n_stages:
        raise ValueError('%d stage fns != pp degree %d'
                         % (len(stage_fns), n_stages))

    def wrap(fn):
        def g(arr):
            out = fn(Tensor(arr, stop_gradient=False))
            return out._data if isinstance(out, Tensor) else out
        return g

    branches = [wrap(f) for f in stage_fns]

    def stage_apply(xb, stage_id, tick_key):
        if tick_key is None:
            return lax.switch(stage_id, branches, xb)
        # every branch traces under the stage-folded key scope; only this
        # rank's branch runs, and each branch's trace advances the scoped
        # stream at a distinct position, decorrelating stages
        with rng_mod.key_scope(jax.random.fold_in(tick_key, stage_id)):
            return lax.switch(stage_id, branches, xb)

    x_arr = x._data if isinstance(x, Tensor) else x
    dtype_like = x_arr.dtype
    cpu = _cpu_mesh(st['mesh'])
    wire = jnp.float32 if cpu else dtype_like
    params = params or {}
    # on CPU the threaded params cross the boundary in f32 too (their
    # transpose is also a psum over pp) and are cast back to their real
    # dtype inside the region before rebinding
    pdtypes = {n: a.dtype for n, a in params.items()}
    boundary = ({n: a.astype(jnp.float32) for n, a in params.items()}
                if cpu else params)
    base_key = rng_mod.next_key()  # always threads; see pipeline_blocks

    def pp_body(params_in, micro, key_in):
        if cpu:
            params_in = {n: a.astype(pdtypes[n])
                         for n, a in params_in.items()}
        restore = rebind(params_in) if rebind is not None else None
        try:
            return _gpipe_loop(stage_apply, micro, n_stages, n_micro,
                               axis, dtype_like, wire, base_key=key_in)
        finally:
            if restore is not None:
                restore()

    fn = shard_map(pp_body, mesh=st['mesh'],
                   in_specs=({n: P() for n in params}, P(), P()),
                   out_specs=P(), axis_names={axis}, check_vma=False)
    micro = _split_micro(x_arr, n_micro).astype(wire)
    plan = ap_tuner.resolve_plan_for_state(st)
    if plan is not None:  # see pipeline_blocks: pin the micro boundary
        micro = plan.constrain_micro(micro)
    out = fn(boundary, micro, base_key)
    if plan is not None:
        out = plan.constrain_micro(out)
    out = out.reshape(x_arr.shape[:1] + out.shape[2:]).astype(dtype_like)
    if plan is not None:
        out = plan.constrain_batch(out)
    return Tensor(out, stop_gradient=False)


class PipelineEngine:
    """Executes PipelineLayer models: microbatch split + GPipe schedule +
    grads + optimizer, jitted once (reference SectionWorker TrainFiles +
    PipelineParallel.train_batch)."""

    def __init__(self, pipeline_layer, optimizer, hcg, n_micro=None):
        self.layer = pipeline_layer
        self.optimizer = optimizer
        self.hcg = hcg
        pp = max(hcg.get_pipe_parallel_world_size(), 1)
        self.n_micro = n_micro or max(pp, 1)
        self._step = None
        self._pp_state = None
        if pp > 1:
            self._pp_state = make_pp_state(hcg.mesh, n_stages=pp,
                                           n_micro=self.n_micro)

    def _build(self):
        model = self.layer
        loss_fn = model._loss_fn

        def step_loss(out, labels):
            return loss_fn(out, labels)

        self._step = func_mod.TrainStep(model, step_loss, self.optimizer,
                                        mesh=self.hcg.mesh,
                                        pp_state=self._pp_state)

    def step(self, inputs, labels):
        if self._step is None:
            self._build()
        return self._step(inputs, labels)
