"""One shard_map entry point across JAX generations.

The pipeline engines run their schedules Manual over the 'pp' axis only,
with every other mesh axis left Auto for GSPMD (pipeline.py docstring).
Newer JAX spells that `jax.shard_map(..., axis_names={'pp'},
check_vma=False)`; the 0.4.x line spells the same partitioning
`jax.experimental.shard_map.shard_map(..., auto=<other axes>,
check_rep=False)`. This shim speaks whichever dialect the installed JAX
understands so the schedules (and the sharding auditor that compiles
them) work on both.
"""
import jax
from jax import lax

__all__ = ['shard_map', 'axis_size']


def axis_size(axis_name):
    """lax.axis_size where available; psum-of-1 (which constant-folds to
    the static axis extent) on jax lines that predate it."""
    fn = getattr(lax, 'axis_size', None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def shard_map(fn, mesh, in_specs, out_specs, axis_names=None,
              check_vma=False):
    """Partial-manual shard_map: Manual over `axis_names`, Auto elsewhere.

    axis_names: iterable of mesh axis names the body handles manually
    (None = all of them). check_vma: the replication-checking flag
    (check_rep on older JAX).
    """
    modern = getattr(jax, 'shard_map', None)
    if modern is not None:
        kwargs = {}
        if axis_names is not None:
            kwargs['axis_names'] = set(axis_names)
        return modern(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check_vma, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy
    auto = frozenset()
    if axis_names is not None:
        # only axes with real extent need Auto treatment — keeping size-1
        # axes out of `auto` lets single-real-axis meshes run full-manual,
        # which this jax line supports everywhere (its partial-auto path
        # lowers axis_index to partition-id, unsupported under SPMD)
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        auto = frozenset(a for a in mesh.axis_names
                         if a not in frozenset(axis_names)
                         and sizes.get(a, 1) > 1)
    return legacy(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=bool(check_vma), auto=auto)
