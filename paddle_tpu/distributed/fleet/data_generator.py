"""fleet data generator (reference: python/paddle/distributed/fleet/
data_generator/data_generator.py — the writer side of the MultiSlot
pipeline: user code yields (slot_name, values) tuples per sample and the
generator renders MultiSlotDataFeed text lines, usually under a hadoop
streaming job feeding the PS trainer).

Round-trips with native/datafeed.cc's parser and ps/dataset.py's
MultiSlotDataset.
"""
import sys

__all__ = ['DataGenerator', 'MultiSlotDataGenerator',
           'MultiSlotStringDataGenerator']


class DataGenerator:
    """Subclass and override generate_sample(line) to return a no-arg
    generator yielding one or more samples; each sample is a list of
    (slot_name, [values]) tuples in slot order."""

    def __init__(self):
        self._batch = 1
        self._line_proc = None

    def set_batch(self, batch_size):
        self._batch = int(batch_size)

    def generate_sample(self, line):
        raise NotImplementedError(
            'override generate_sample(line) to yield samples')

    def generate_batch(self, samples):
        """Optional batch-level hook (reference parity): receives the
        accumulated `samples` list, yields samples to emit."""
        def gen():
            for s in samples:
                yield s
        return gen

    def _gen_str(self, sample):
        raise NotImplementedError

    def run_from_stdin(self):
        self._run(sys.stdin, sys.stdout)

    def run_from_memory(self, lines=None):
        """Returns the rendered lines (test/runtime hook)."""
        out = []

        class _Sink:
            def write(self, s):
                out.append(s)
        self._run(lines if lines is not None else [None], _Sink())
        return ''.join(out)

    def _run(self, lines, sink):
        batch = []
        for line in lines:
            gen = self.generate_sample(line)
            for sample in gen():
                batch.append(sample)
                if len(batch) >= self._batch:
                    self._flush(batch, sink)
                    batch = []
        if batch:
            self._flush(batch, sink)

    def _flush(self, batch, sink):
        for sample in self.generate_batch(batch)():
            sink.write(self._gen_str(sample))


class MultiSlotDataGenerator(DataGenerator):
    """Renders [(name, values), ...] as MultiSlotDataFeed text:
    'n v1 .. vn' per slot, space-joined (data_feed.h:208 format)."""

    def _gen_str(self, sample):
        parts = []
        for _name, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return ' '.join(parts) + '\n'


class MultiSlotStringDataGenerator(DataGenerator):
    """Values are pre-stringified by the user (string variant)."""

    def _gen_str(self, sample):
        parts = []
        for _name, values in sample:
            parts.append(str(len(values)))
            parts.extend(values)
        return ' '.join(parts) + '\n'
