"""Elastic membership (reference: fleet/elastic.py:90 ElasticManager —
etcd-backed node registry, heartbeat leases, scale-event relaunch).

This environment has no etcd; the same protocol runs over a shared
filesystem directory (works for single-host tests and NFS/GCS-fuse pods) or
a plain TCP kv server. Each node writes a heartbeat file; membership = the
set of fresh heartbeats; a change triggers ELASTIC_EXIT_CODE relaunch in
the launcher.
"""
import json
import os
import threading
import time

HEARTBEAT_TTL = 10.0
ELASTIC_EXIT_CODE = 101


class ElasticManager:
    def __init__(self, server, job_id, np, host,
                 ttl=HEARTBEAT_TTL):
        # server: 'file:///shared/dir' or plain path
        path = server[len('file://'):] if server.startswith('file://') else server
        self.dir = os.path.join(path, 'paddle_elastic', job_id)
        os.makedirs(self.dir, exist_ok=True)
        self.job_id = job_id
        self.np = np
        self.host = host
        self.ttl = ttl
        self._last_view = None
        self._hb_stop = None
        self._hb_thread = None

    def _hb_path(self, host=None):
        return os.path.join(self.dir, 'hb_%s.json' % (host or self.host))

    def register(self):
        self.heartbeat()
        self._last_view = frozenset(self.hosts())
        # keep the lease fresh while the launcher blocks in its watch loop —
        # without this every peer's view goes stale after ttl and a clean
        # exit looks like a membership change (infinite relaunch)
        if (self._hb_thread is None or not self._hb_thread.is_alive()
                or self._hb_stop.is_set()):
            if self._hb_thread is not None and self._hb_thread.is_alive():
                # re-register after unregister: retire the stopping thread
                # before arming a fresh one, or the lease silently stops
                self._hb_stop.set()
                self._hb_thread.join()
            self._hb_stop = threading.Event()
            self._hb_thread = threading.Thread(target=self._hb_loop,
                                               daemon=True)
            self._hb_thread.start()

    def _hb_loop(self):
        while not self._hb_stop.wait(self.ttl / 3.0):
            try:
                self.heartbeat()
            except OSError:
                pass

    def unregister(self):
        if self._hb_stop is not None:
            # stop and JOIN before removing the file — an in-flight
            # heartbeat write after the remove would resurrect the lease
            self._hb_stop.set()
            self._hb_thread.join()
        try:
            os.remove(self._hb_path())
        except FileNotFoundError:
            pass

    def heartbeat(self):
        with open(self._hb_path(), 'w') as f:
            json.dump({'host': self.host, 'ts': time.time()}, f)

    def hosts(self):
        """Fresh members, sorted for stable rank assignment."""
        now = time.time()
        out = []
        for name in os.listdir(self.dir):
            if not name.startswith('hb_'):
                continue
            try:
                with open(os.path.join(self.dir, name)) as f:
                    rec = json.load(f)
                if now - rec['ts'] < self.ttl:
                    out.append(rec['host'])
            except (ValueError, OSError):
                continue
        return sorted(out)

    def membership_changed(self):
        self.heartbeat()
        cur = frozenset(self.hosts())
        changed = self._last_view is not None and cur != self._last_view
        self._last_view = cur
        return changed

    def wait_for_stable(self, window=3.0, timeout=120.0):
        """Wait until membership stops changing (scale event settled)."""
        deadline = time.time() + timeout
        stable_since = time.time()
        view = frozenset(self.hosts())
        while time.time() < deadline:
            self.heartbeat()
            cur = frozenset(self.hosts())
            if cur != view:
                view = cur
                stable_since = time.time()
            elif time.time() - stable_since > window:
                self._last_view = view
                return True
            time.sleep(0.5)
        return False
