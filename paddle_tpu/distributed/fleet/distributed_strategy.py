"""DistributedStrategy (reference: fleet/base/distributed_strategy.py:105
backed by framework/distributed_strategy.proto:159).

Typed python config (SURVEY.md §5.6 mapping: one config system instead of
protobuf+gflags). Field names match the reference so fleet user code ports
verbatim; each field maps to a sharding/compile decision in strategy.py.
"""
import copy

__all__ = ['DistributedStrategy']


class _Cfg(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        # collective strategies (proto field parity)
        self.amp = False
        self.amp_configs = _Cfg(init_loss_scaling=65536.0, use_pure_fp16=False,
                                use_bf16=True, custom_white_list=[],
                                custom_black_list=[])
        self.recompute = False
        self.recompute_configs = _Cfg(checkpoints=[])
        self.gradient_merge = False
        self.gradient_merge_configs = _Cfg(k_steps=1, avg=True)
        self.sharding = False
        self.sharding_configs = _Cfg(stage=1, sharding_degree=1,
                                     segment_broadcast_MB=32,
                                     hybrid_dp=False, offload=False)
        self.pipeline = False
        self.pipeline_configs = _Cfg(accumulate_steps=1, micro_batch_size=1,
                                     schedule_mode='1F1B')
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Cfg(tensor_parallel_degree=1)
        self.sequence_parallel = False
        self.sequence_parallel_configs = _Cfg(sequence_parallel_degree=1,
                                              mode='ring')
        self.hybrid_configs = _Cfg(dp_degree=-1, mp_degree=1, pp_degree=1,
                                   sharding_degree=1, sp_degree=1,
                                   ep_degree=1)
        self.lamb = False
        self.lamb_configs = _Cfg(lamb_weight_decay=0.01)
        self.lars = False
        self.lars_configs = _Cfg(lars_coeff=0.001, lars_weight_decay=0.0005)
        self.dgc = False
        self.dgc_configs = _Cfg(rampup_begin_step=0, rampup_step=1,
                                sparsity=0.999, momentum=0.9)
        self.localsgd = False
        self.localsgd_configs = _Cfg(k_steps=1)
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = _Cfg(init_k_steps=1,
                                              begin_step=1)
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = True
        self.sync_batch_norm = False
        self.fuse_all_reduce_ops = True
        self.gradient_scale_configs = _Cfg(scale_strategy='avg')
        # parameter-server strategies
        self.a_sync = False
        self.a_sync_configs = _Cfg(k_steps=0, max_merge_var_num=1,
                                   send_queue_size=16, independent_recv_thread=False,
                                   thread_pool_size=1, send_wait_times=1,
                                   runtime_split_send_recv=False, launch_barrier=True,
                                   heter_worker_device_guard='cpu')
        self.auto = False
        self.elastic = False
        # execution/build strategy passthrough
        self.build_strategy = None
        self.execution_strategy = None

    def to_dict(self):
        return {k: copy.deepcopy(v) for k, v in self.__dict__.items()}

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return 'DistributedStrategy(enabled=%s)' % on

    # strategy.py consumption helper
    def _zero_stage(self):
        if self.sharding:
            return int(self.sharding_configs.get('stage', 1))
        return 0
