"""fleet.utils (reference: fleet/utils/: recompute, fs, hybrid_parallel_util)."""
import os
import shutil

__all__ = ['recompute', 'LocalFS', 'HDFSClient']


def recompute(function, *args, **kwargs):
    """Activation recomputation (reference: fleet/utils/recompute.py:63
    RecomputeFunction). TPU-native: jax.checkpoint(remat) — XLA rematerializes
    in backward, RNG handled by jax's per-trace key plumbing."""
    import jax
    from ...framework.core import Tensor, run_op
    preserve = kwargs.pop('preserve_rng_state', True)
    tensor_args = [a for a in args if isinstance(a, Tensor)]

    def fn(*arrays):
        it = iter(arrays)
        call_args = [Tensor(next(it), stop_gradient=False)
                     if isinstance(a, Tensor) else a for a in args]
        out = function(*call_args, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out

    remat_fn = jax.checkpoint(fn)
    return run_op('recompute', remat_fn, *tensor_args)


class LocalFS:
    """reference: fleet/utils/fs.py LocalFS."""

    def ls_dir(self, path):
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for name in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        open(path, 'a').close()


class HDFSClient(LocalFS):
    """HDFS via shell pipes in the reference (framework/io/fs.cc); this env
    has no HDFS — gcsfuse/NFS-mounted paths go through the LocalFS API."""

    def __init__(self, hadoop_home=None, configs=None):
        pass
