"""fleet.utils (reference: fleet/utils/: recompute, fs, hybrid_parallel_util)."""
import os
import shutil

__all__ = ['recompute', 'LocalFS', 'HDFSClient']


def recompute(function, *args, **kwargs):
    """Activation recomputation (reference: fleet/utils/recompute.py:63
    RecomputeFunction). TPU-native: jax.checkpoint(remat) — XLA rematerializes
    the segment in backward instead of saving its activations.

    When `function` is a Layer, its parameters are passed as EXPLICIT vjp
    inputs (run_op only flows gradients to explicit inputs — closing over
    them would silently drop param grads in eager mode)."""
    import jax
    from ...framework.core import Tensor, run_op
    kwargs.pop('preserve_rng_state', True)
    tensor_args = [a for a in args if isinstance(a, Tensor)]

    if hasattr(function, 'named_parameters'):
        from ...framework import functional as func_mod
        named = list(function.named_parameters())
        pnames = [n for n, _ in named]
        ptensors = [p for _, p in named]
        buffers = func_mod.extract_buffers(function)
        bnames = list(buffers.keys())
        n_p = len(pnames)

        def layer_fn(*arrays):
            params = dict(zip(pnames, arrays[:n_p]))
            it = iter(arrays[n_p:])
            call_args = [Tensor(next(it), stop_gradient=False)
                         if isinstance(a, Tensor) else a for a in args]
            out, new_buf = func_mod.functional_call(
                function, params, buffers, args=call_args, kwargs=kwargs)
            outs = out if isinstance(out, tuple) else (out,)
            # buffer updates (BN running stats) ride along as extra
            # outputs; the caller writes them back into the live layer
            return tuple(outs) + tuple(new_buf[n] for n in bnames)

        def split_outs(flat):
            outs = flat[:len(flat) - len(bnames)]
            bmap = dict(function.named_buffers())
            for name, arr in zip(bnames, flat[len(flat) - len(bnames):]):
                arr = arr._data if isinstance(arr, Tensor) else arr
                if bmap.get(name) is not None:
                    bmap[name]._data = arr
            return outs[0] if len(outs) == 1 else tuple(outs)

        all_inputs = list(ptensors) + tensor_args
        if any(isinstance(t._data, jax.core.Tracer) for t in all_inputs):
            # inside an outer jax trace (TrainStep value_and_grad): call the
            # checkpointed fn DIRECTLY so the outer AD sees the remat
            # primitive — routing through run_op would jax.vjp it eagerly,
            # partial-evaluating the checkpoint into a plain
            # save-activations program (no memory win)
            flat = jax.checkpoint(layer_fn)(*[t._data for t in all_inputs])
            return split_outs(tuple(Tensor(o, stop_gradient=False)
                                    for o in flat))

        flat = run_op('recompute', jax.checkpoint(layer_fn),
                      *ptensors, *tensor_args)
        if not isinstance(flat, tuple):
            flat = (flat,)
        return split_outs(flat)

    def fn(*arrays):
        it = iter(arrays)
        call_args = [Tensor(next(it), stop_gradient=False)
                     if isinstance(a, Tensor) else a for a in args]
        out = function(*call_args, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out

    return run_op('recompute', jax.checkpoint(fn), *tensor_args)


class LocalFS:
    """reference: fleet/utils/fs.py LocalFS."""

    def ls_dir(self, path):
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for name in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        open(path, 'a').close()


class HDFSClient(LocalFS):
    """HDFS via shell pipes in the reference (framework/io/fs.cc); this env
    has no HDFS. DECLARED shim (VERDICT r3 item 9): it warns at
    construction that it is LocalFS-backed (gcsfuse/NFS-mounted paths go
    through the LocalFS API) and raises on genuine `hdfs://` URIs rather
    than silently treating them as local paths."""

    _GUARDED = ('ls_dir', 'mkdirs', 'is_exist', 'is_dir', 'is_file',
                'delete', 'mv', 'upload', 'download', 'touch')

    def __init__(self, hadoop_home=None, configs=None):
        import warnings
        warnings.warn(
            'HDFSClient is LocalFS-backed in this build: paths are served '
            'by the local filesystem (mount HDFS via NFS/gcsfuse); '
            'hdfs:// URIs raise', stacklevel=2)
        # wrap once: instance attributes shadow the LocalFS methods
        for name in self._GUARDED:
            setattr(self, name, self._guard(getattr(self, name)))

    @staticmethod
    def _check_scheme(path):
        if isinstance(path, str) and path.startswith('hdfs://'):
            raise NotImplementedError(
                'no HDFS connectivity in this build — mount the data '
                'locally (NFS/gcsfuse) and pass the mounted path; got %r'
                % path)
        return path

    @classmethod
    def _guard(cls, fn):
        def guarded(*args, **kwargs):
            for a in args:
                cls._check_scheme(a)
            for a in kwargs.values():
                cls._check_scheme(a)
            return fn(*args, **kwargs)
        return guarded
