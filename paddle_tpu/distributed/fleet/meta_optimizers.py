"""Meta-optimizer strategies as explicit-communication step transforms.

Reference parity (SURVEY.md §2.2): fleet/meta_optimizers/
{localsgd,dgc,fp16_allreduce,gradient_merge,lars,lamb}_optimizer.py rewrite
the static program to change WHAT is communicated and WHEN. The TPU-native
analog keeps the same property — communication visible in the program — by
running the data-parallel train step inside shard_map over the 'dp' mesh
axis, where psum/pmean calls are explicit:

  - plain DDP        : grads <- pmean(grads) every step
  - fp16_allreduce   : grads cast to bf16 for the pmean, back after
  - dgc              : top-k sparsified grads (momentum correction + error
                       feedback, Lin et al.) summed instead of dense grads
  - localsgd         : NO grad sync; per-device replicas diverge and params
                       are pmean'd every k_steps
  - gradient merge   : accumulate k micro-grads locally, sync+apply on the
                       k-th (composes with the modes above)

lars/lamb strategies swap the optimizer (optimizer/optimizers.py
LarsMomentum/Lamb); amp/recompute/sharding remain pjit-level concerns
(strategy.py / TrainStep).

The engine keeps params/opt-slots STACKED with a leading 'dp' axis sharded
over the mesh (each device owns its replica — required for localsgd
divergence); batch is sharded over the same axis.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ...framework import functional as func_mod
from ...framework import random as rng_mod
from ...framework.core import Tensor

__all__ = ['ShardMapDPStep', 'dgc_compress', 'select_optimizer']


def dgc_compress(g, momentum_buf, error_buf, momentum, sparsity):
    """Deep Gradient Compression (local side): momentum correction +
    error-feedback accumulation + top-k selection.

    Returns (dense_send, new_momentum, new_error): dense_send is the
    sparsified tensor (zeros off the top-k support) to be summed across
    ranks; the residual stays in error_buf.

    Reference: operators/dgc_op.cc + sparse_all_reduce_op_handle.cc.
    """
    u = momentum * momentum_buf + g          # momentum correction
    v = error_buf + u                        # error feedback accumulation
    flat = v.reshape(-1)
    k = max(int(flat.size * (1.0 - sparsity)), 1)
    thresh = lax.top_k(jnp.abs(flat), k)[0][-1]
    mask = (jnp.abs(v) >= thresh).astype(v.dtype)
    send = v * mask
    # masked-out residual carries over; masked-in entries reset
    new_error = v * (1 - mask)
    new_momentum = u * (1 - mask)
    return send, new_momentum, new_error


def select_optimizer(optimizer, strategy):
    """lars/lamb meta-optimizers: swap the inner optimizer when the
    strategy flag is set (reference lars_optimizer.py/lamb_optimizer.py
    _can_apply over Momentum/Adam)."""
    from ... import optimizer as opt_mod
    if strategy is None:
        return optimizer
    if getattr(strategy, 'lamb', False) and \
            not isinstance(optimizer, opt_mod.Lamb):
        cfg = strategy.lamb_configs
        return opt_mod.Lamb(
            learning_rate=optimizer._lr,
            lamb_weight_decay=cfg.get('lamb_weight_decay', 0.01),
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip)
    if getattr(strategy, 'lars', False) and \
            not isinstance(optimizer, opt_mod.LarsMomentum):
        cfg = strategy.lars_configs
        return opt_mod.LarsMomentum(
            learning_rate=optimizer._lr,
            momentum=getattr(optimizer, '_momentum', 0.9),
            lars_coeff=cfg.get('lars_coeff', 0.001),
            lars_weight_decay=cfg.get('lars_weight_decay', 0.0005),
            exclude_from_weight_decay=cfg.get('exclude_from_weight_decay',
                                              ()),
            epsilon=cfg.get('epsilon', 0.0),
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip)
    return optimizer


class ShardMapDPStep:
    """Explicit-collective data-parallel training step (see module doc).

    Restrictions (vs the pjit TrainStep): pure data parallelism (the mesh
    axis covers all devices used), uniform lr across params, no grad-clip
    hook inside the compressed paths (matches the reference, which clips
    before DGC only in the dense path), and buffers (e.g. BN stats) are
    frozen during stepping. In 'local' mode the live model object is only
    refreshed at param-sync steps — between syncs replicas legitimately
    diverge and have no single host-side value.
    """

    # DGC warm-up ladder (Lin et al. §3.3): dense before rampup_begin_step,
    # then increasingly sparse over rampup_step applied steps
    DGC_RAMP = (0.75, 0.9375, 0.984, 0.996)

    def __init__(self, model, loss_fn, optimizer, mesh=None, axis='dp',
                 mode='dense', k_steps=1, gm_k_steps=1, momentum=0.9,
                 sparsity=0.999, dtype_comm=jnp.bfloat16, adaptive=False,
                 rampup_begin_step=0, rampup_step=1):
        assert mode in ('dense', 'fp16', 'dgc', 'local')
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.axis = axis
        self.mode = mode
        self.k_steps = max(int(k_steps), 1)      # localsgd param-sync period
        self.gm_k = max(int(gm_k_steps), 1)      # gradient-merge period
        self.momentum = momentum
        self.sparsity = sparsity
        self.dtype_comm = dtype_comm
        # adaptive localsgd (reference adaptive_localsgd meta-optimizer):
        # host-side heuristic — widen the sync period while the synced loss
        # keeps improving, shrink it when it regresses
        self.adaptive = adaptive
        self._adapt_last_loss = None
        self.rampup_begin_step = int(rampup_begin_step)
        self.rampup_step = max(int(rampup_step), 1)
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), (axis,))
        self.mesh = mesh
        self.n_dev = mesh.shape[axis]
        self._trainable = {name: not p.stop_gradient
                           for name, p in model.named_parameters()}
        # state-dict key -> Parameter.name: _apply hints (e.g. LARS
        # exclude_from_weight_decay) match on the Parameter's .name, same
        # as TrainStep's engine
        self._pname = {name: p.name
                       for name, p in model.named_parameters()}
        self._micro = 0          # host-side micro-batch counter
        self._step = 0           # host-side applied-step counter
        self._state = None       # stacked device state
        self._compiled = {}

    # -- state --------------------------------------------------------------
    def _stack(self, tree):
        """Replicate a pytree with a leading dp axis, sharded over it."""
        sh = NamedSharding(self.mesh, P(self.axis))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(
                jnp.broadcast_to(a[None], (self.n_dev,) + a.shape), sh),
            tree)

    def _init_state(self):
        params = func_mod.extract_params(self.model)
        pmap = dict(self.model.named_parameters())
        slots = {name: dict(self.optimizer._get_slots(pmap[name]))
                 for name in params if self._trainable[name]}
        state = {'params': self._stack(params),
                 'slots': self._stack(slots)}
        train = {n: params[n] for n in params if self._trainable[n]}
        if self.mode == 'dgc':
            zeros = {n: jnp.zeros_like(a) for n, a in train.items()}
            state['dgc_u'] = self._stack(zeros)
            state['dgc_v'] = self._stack(zeros)
        if self.gm_k > 1:
            zeros = {n: jnp.zeros_like(a) for n, a in train.items()}
            state['acc'] = self._stack(zeros)
        return state

    def _write_back(self):
        """Sync rank-0 replica back into the live model (replicas are
        identical right after a sync step)."""
        params0 = jax.tree_util.tree_map(lambda a: a[0],
                                         self._state['params'])
        func_mod.write_back_params(self.model, params0)
        pmap = dict(self.model.named_parameters())
        slots0 = jax.tree_util.tree_map(lambda a: a[0],
                                        self._state['slots'])
        for name, s in slots0.items():
            self.optimizer._slots[id(pmap[name])] = dict(s)

    # -- step build ---------------------------------------------------------
    def _build(self, sync_params, apply_opt, sparsity=None):
        model, opt, loss_fn = self.model, self.optimizer, self.loss_fn
        trainable = self._trainable
        axis = self.axis
        mode = self.mode
        n_dev = self.n_dev
        buffers = func_mod.extract_buffers(model)

        def per_device(state, batch, lr, t, key):
            # state leaves arrive as [1, ...] shards: this device's replica
            state = jax.tree_util.tree_map(lambda a: a[0], state)
            inputs, labels = batch
            params = state['params']

            def compute_loss(train_params):
                all_params = dict(params)
                all_params.update(train_params)
                gen = rng_mod.default_generator()
                saved = gen._key
                gen._key = key
                try:
                    loss_arr, _ = func_mod.functional_call(
                        model, all_params, buffers, args=inputs,
                        training=True,
                        post_fn=func_mod.make_loss_post(loss_fn, labels))
                    return loss_arr
                finally:
                    gen._key = saved

            train_params = {k: v for k, v in params.items() if trainable[k]}
            loss, grads = jax.value_and_grad(compute_loss)(train_params)
            loss = lax.pmean(loss, axis)

            new_state = dict(state)
            # gradient merge: accumulate locally, only the k-th applies
            if self.gm_k > 1:
                grads = {n: state['acc'][n] + g for n, g in grads.items()}
                if not apply_opt:
                    new_state['acc'] = grads
                    return jax.tree_util.tree_map(lambda a: a[None],
                                                  new_state), loss
                grads = {n: g / self.gm_k for n, g in grads.items()}
                new_state['acc'] = {n: jnp.zeros_like(g)
                                    for n, g in grads.items()}

            # --- communication (explicit, visible in the jaxpr) ---------
            if mode == 'dense':
                grads = {n: lax.pmean(g, axis) for n, g in grads.items()}
            elif mode == 'fp16':
                grads = {n: lax.pmean(g.astype(self.dtype_comm),
                                      axis).astype(g.dtype)
                         for n, g in grads.items()}
            elif mode == 'dgc':
                if sparsity is None:
                    # warm-up phase: dense allreduce, buffers untouched
                    grads = {n: lax.pmean(g, axis)
                             for n, g in grads.items()}
                else:
                    new_u, new_v, synced = {}, {}, {}
                    for n, g in grads.items():
                        send, u, v = dgc_compress(
                            g, state['dgc_u'][n], state['dgc_v'][n],
                            self.momentum, sparsity)
                        synced[n] = lax.psum(send, axis) / n_dev
                        new_u[n] = u
                        new_v[n] = v
                    grads = synced
                    new_state['dgc_u'] = new_u
                    new_state['dgc_v'] = new_v
            # mode == 'local': no grad communication at all

            if apply_opt:
                new_params = dict(params)
                new_slots = dict(state['slots'])
                for n, g in grads.items():
                    opt._apply_param_name = self._pname[n]
                    p, s = opt._apply(params[n], g.astype(params[n].dtype),
                                      state['slots'][n], lr, t)
                    new_params[n] = p
                    new_slots[n] = s
                if sync_params:
                    # localsgd periodic model averaging
                    new_params = {n: lax.pmean(p, axis)
                                  for n, p in new_params.items()}
                    new_slots = jax.tree_util.tree_map(
                        lambda a: lax.pmean(a, axis), new_slots)
                new_state['params'] = new_params
                new_state['slots'] = new_slots

            return jax.tree_util.tree_map(lambda a: a[None], new_state), \
                loss

        state_spec = jax.tree_util.tree_map(lambda _: P(axis), self._state)
        batch_spec = P(axis)

        @jax.jit
        def step(state, batch, lr, t, key):
            return shard_map(
                per_device, mesh=self.mesh,
                in_specs=(state_spec, (batch_spec, batch_spec), P(), P(),
                          P()),
                out_specs=(state_spec, P()),
                check_rep=False)(state, batch, lr, t, key)

        return step

    def __call__(self, inputs, labels):
        if self._state is None:
            self._state = self._init_state()
        if not isinstance(inputs, (list, tuple)):
            inputs = (inputs,)
        if not isinstance(labels, (list, tuple)):
            labels = (labels,)
        ins = tuple(a._data if isinstance(a, Tensor) else jnp.asarray(a)
                    for a in inputs)
        labs = tuple(a._data if isinstance(a, Tensor) else jnp.asarray(a)
                     for a in labels)

        self._micro += 1
        apply_opt = (self._micro % self.gm_k) == 0
        will_step = self._step + (1 if apply_opt else 0)
        sync_params = (self.mode == 'local' and apply_opt
                       and (will_step % self.k_steps) == 0)
        sparsity = self._current_sparsity() if self.mode == 'dgc' else None
        key = (bool(sync_params), bool(apply_opt), sparsity)
        if key not in self._compiled:
            self._compiled[key] = self._build(sync_params, apply_opt,
                                              sparsity)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(will_step if apply_opt else self._step, jnp.int32)
        rng_key = rng_mod.next_key()
        new_state, loss = self._compiled[key](
            self._state, (ins, labs), lr, t, rng_key)
        self._state = new_state
        if apply_opt:
            self._step = will_step
            self.optimizer._step_count = self._step
        if self.mode != 'local' or sync_params:
            self._write_back()
        if self.adaptive and sync_params:
            # adaptive localsgd: longer local phases while the synced loss
            # improves, shorter when it regresses (host-side heuristic
            # analog of the reference's AdaptiveLocalSGDOptimizer)
            cur = float(jax.device_get(loss))
            if self._adapt_last_loss is not None:
                if cur < self._adapt_last_loss:
                    self.k_steps = min(self.k_steps * 2, 16)
                else:
                    self.k_steps = max(self.k_steps // 2, 1)
            self._adapt_last_loss = cur
        return Tensor(loss)

    def _current_sparsity(self):
        """DGC warm-up: None (dense) before rampup_begin_step, then climb
        the ramp ladder over rampup_step applied steps, ending at the
        target sparsity. A handful of distinct values keeps recompiles
        bounded."""
        applied = self._step
        if applied < self.rampup_begin_step:
            return None
        if self.rampup_step <= 1:
            return self.sparsity
        ladder = [s for s in self.DGC_RAMP if s < self.sparsity] + \
            [self.sparsity]
        seg = self.rampup_step / float(len(ladder))
        idx = min(int((applied - self.rampup_begin_step) / seg),
                  len(ladder) - 1)
        return ladder[idx]
