"""Fleet API (reference: fleet/base/fleet_base.py:139 init, :783
distributed_optimizer, :836 distributed_model, :1288 minimize).

TPU-native: fleet composes a Mesh (HybridCommunicateGroup), per-strategy
sharding specs (strategy.py), and a jitted TrainStep — the meta-optimizer
program-rewrite pipeline collapses into spec composition (SURVEY.md §7.1).
"""
import os

from .distributed_strategy import DistributedStrategy
from ..topology import (HybridCommunicateGroup, set_hybrid_communicate_group,
                        get_hybrid_communicate_group)
from ..env import get_rank, get_world_size, init_parallel_env
from .. import strategy as strategy_mod
from ...framework import functional as func_mod

__all__ = ['init', 'DistributedStrategy', 'distributed_optimizer',
           'distributed_model', 'get_hybrid_communicate_group',
           'worker_index', 'worker_num', 'is_worker', 'is_server', 'barrier_worker',
           'init_worker', 'init_server', 'run_server', 'stop_worker',
           'UserDefinedRoleMaker', 'PaddleCloudRoleMaker', 'minimize',
           'distributed_scaler', 'fleet_train_step', 'meta_parallel',
           'utils']

from .. import meta_parallel  # noqa: E402,F401
from . import utils  # noqa: E402,F401

_FLEET = {'initialized': False, 'strategy': None, 'hcg': None,
          'is_collective': True, 'model': None, 'optimizer': None,
          'train_step': None, 'role_maker': None}


class PaddleCloudRoleMaker:
    """reference: fleet/base/role_maker.py:946 — reads PADDLE_* env."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective

    def _worker_index(self):
        return get_rank()

    def _worker_num(self):
        return get_world_size()

    def _is_worker(self):
        return os.environ.get('TRAINING_ROLE', 'TRAINER') == 'TRAINER'

    def _is_server(self):
        return os.environ.get('TRAINING_ROLE', '') == 'PSERVER'


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, role='TRAINER', worker_num=1,
                 server_endpoints=None, **kwargs):
        super().__init__()
        self._cur = current_id
        self._n = worker_num

    def _worker_index(self):
        return self._cur

    def _worker_num(self):
        return self._n


def init(role_maker=None, is_collective=False, strategy=None, log_level='INFO'):
    strategy = strategy or DistributedStrategy()
    _FLEET['strategy'] = strategy
    _FLEET['is_collective'] = is_collective or role_maker is None
    _FLEET['role_maker'] = role_maker or PaddleCloudRoleMaker(is_collective)
    init_parallel_env()

    hc = strategy.hybrid_configs
    try:
        hcg = HybridCommunicateGroup(
            dp_degree=hc.get('dp_degree', -1),
            mp_degree=hc.get('mp_degree', 1),
            pp_degree=hc.get('pp_degree', 1),
            sharding_degree=hc.get('sharding_degree', 1),
            sp_degree=hc.get('sp_degree', 1),
            ep_degree=hc.get('ep_degree', 1))
    except ValueError:
        # degrees don't match the device count: fall back to pure DP
        hcg = HybridCommunicateGroup(dp_degree=-1)
    _FLEET['hcg'] = hcg
    set_hybrid_communicate_group(hcg)
    _FLEET['initialized'] = True


def _strategy_dict(s=None):
    s = s or _FLEET['strategy'] or DistributedStrategy()
    return {
        'zero_stage': s._zero_stage(),
        'tensor_parallel': s.tensor_parallel,
        'sequence_parallel': s.sequence_parallel,
        'amp': s.amp,
        'recompute': s.recompute,
        'gradient_merge_k': (s.gradient_merge_configs.get('k_steps', 1)
                             if s.gradient_merge else 1),
    }


def distributed_model(model):
    """reference fleet_base.py:836: wraps per hybrid config. Here: record the
    model and place its params onto the mesh per strategy."""
    _FLEET['model'] = model
    hcg = _FLEET['hcg']
    if hcg is not None and _FLEET['optimizer'] is not None:
        _prepare_train_step()
    return model


class _FleetOptimizer:
    """Wrapper returned by distributed_optimizer: step() runs the jitted
    sharded TrainStep when a model is registered, else plain step."""

    def __init__(self, inner, strategy):
        self._inner = inner
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self.__dict__['_inner'], name)

    def step(self):
        self._inner.step()

    def clear_grad(self):
        self._inner.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program, parameters,
                                    no_grad_set)


def distributed_optimizer(optimizer, strategy=None):
    if strategy is not None:
        _FLEET['strategy'] = strategy
    _FLEET['optimizer'] = optimizer
    return _FleetOptimizer(optimizer, _FLEET['strategy'])


def _prepare_train_step():
    """distributed_model's placement step (reference fleet_base.py:836
    broadcasts/places initial params when the model is wrapped): put every
    parameter onto the fleet mesh under the strategy's shardings NOW, so
    the first fleet_train_step compiles against pre-placed arrays and
    large models never materialize fully replicated. Optimizer slots are
    NOT touched here — they must be created after placement (zeros_like
    of the sharded param; see place_opt_slots), which fleet_train_step
    does."""
    model = _FLEET['model']
    hcg = _FLEET['hcg']
    if model is None or hcg is None:
        return
    cfg = strategy_mod.build_shardings(model, strategy_mod._NullOpt(),
                                       hcg.mesh, _strategy_dict())
    strategy_mod.place_params(model, cfg['param_shardings'])


def fleet_train_step(model, loss_fn, optimizer, strategy=None, hcg=None):
    """Build the sharded jitted TrainStep for (model, loss, optimizer) under
    the fleet strategy — the executable artifact of fleet.minimize.

    Strategy routing (reference meta-optimizer selection,
    base/strategy_compiler.py): localsgd/dgc/fp16_allreduce need explicit
    collectives, so they build the shard_map engine
    (meta_optimizers.ShardMapDPStep) over the dp axis; everything else
    (dp/mp/sharding/amp/recompute/gradient_merge) composes in the pjit
    TrainStep. lars/lamb swap the optimizer first.
    """
    from . import meta_optimizers as mo

    hcg = hcg or _FLEET['hcg']
    if hcg is None:
        init(is_collective=True, strategy=strategy)
        hcg = _FLEET['hcg']
    s = strategy if isinstance(strategy, DistributedStrategy) \
        else _FLEET['strategy'] or DistributedStrategy()
    optimizer = mo.select_optimizer(optimizer, s)

    # one strategy object governs BOTH the step build and the shardings —
    # deriving them from different objects caused pytree mismatches
    sdict = _strategy_dict(s)
    gm_k = sdict['gradient_merge_k']
    wants_explicit = s.localsgd or s.adaptive_localsgd or s.dgc or \
        s.fp16_allreduce
    if wants_explicit:
        pure_dp = hcg.mesh.size == hcg.get_data_parallel_world_size()
        if not pure_dp:
            raise ValueError(
                'localsgd/dgc/fp16_allreduce run on a pure data-parallel '
                'mesh (mp/pp/sharding degree 1); got %s' % (hcg.mesh,))
        adaptive = False
        if s.localsgd or s.adaptive_localsgd:
            mode = 'local'
            if s.adaptive_localsgd:
                adaptive = True
                k = s.adaptive_localsgd_configs.get('init_k_steps', 1)
            else:
                k = s.localsgd_configs.get('k_steps', 1)
        elif s.dgc:
            mode = 'dgc'
            k = 1
        else:
            mode = 'fp16'
            k = 1
        from jax.sharding import Mesh as _Mesh
        import numpy as _np
        dp_mesh = _Mesh(_np.asarray(hcg.mesh.devices).reshape(-1), ('dp',))
        return mo.ShardMapDPStep(
            model, loss_fn, optimizer, mesh=dp_mesh, axis='dp', mode=mode,
            k_steps=k, gm_k_steps=gm_k, adaptive=adaptive,
            momentum=s.dgc_configs.get('momentum', 0.9),
            sparsity=s.dgc_configs.get('sparsity', 0.999),
            rampup_begin_step=s.dgc_configs.get('rampup_begin_step', 0),
            rampup_step=s.dgc_configs.get('rampup_step', 1))

    # sequence parallel -> sp attention routing over the 'sp' mesh axis
    # (ring by default; SURVEY §5.7 beyond-reference capability). The state
    # is scoped to the TrainStep (sp_scope) so eval/generation calls
    # between steps keep ordinary attention.
    sp_state = None
    sp_deg = hcg.get_sequence_parallel_world_size()
    if sdict['sequence_parallel'] and sp_deg > 1:
        from .. import sp as sp_mod
        shape = dict(hcg.mesh.shape)
        batch_axes = tuple(a for a in ('dp', 'sharding')
                           if shape.get(a, 1) > 1)
        sp_state = sp_mod.make_sp_state(
            hcg.mesh, axis='sp',
            mode=s.sequence_parallel_configs.get('mode', 'ring'),
            batch_axes=batch_axes,
            head_axis='mp' if shape.get('mp', 1) > 1 else None)

    # pipeline parallel -> GPipe schedule over the 'pp' mesh axis
    # (distributed/pipeline.py), scoped to the step like sp
    pp_state = None
    pp_deg = hcg.get_pipe_parallel_world_size()
    if pp_deg > 1:
        from .. import pipeline as pp_mod
        # strategy.pipeline=True engages pipeline_configs: accumulate_steps
        # and schedule_mode ('1F1B' -> interleaved schedule with loss in
        # the last stage, 'F-then-B' -> GPipe). Without the flag the
        # default GPipe schedule with n_micro=pp runs (hybrid_configs only).
        schedule = 'gpipe'
        acc = 1
        if s.pipeline:
            acc = s.pipeline_configs.get('accumulate_steps', 1)
            mode = s.pipeline_configs.get('schedule_mode', '1F1B')
            schedule = '1f1b' if str(mode).upper() == '1F1B' else 'gpipe'
        # an explicit accumulate_steps is honored as-is (>= pp); the
        # 2*pp floor applies only as the 1F1B DEFAULT (the regime where
        # its O(pp) stash wins)
        if acc > 1:
            n_micro = max(pp_deg, acc)
        else:
            n_micro = 2 * pp_deg if schedule == '1f1b' else pp_deg
        pp_state = pp_mod.make_pp_state(hcg.mesh, n_stages=pp_deg,
                                        n_micro=n_micro,
                                        remat=bool(sdict['recompute']),
                                        schedule=schedule)
        # lets the GPipe fallback (TrainStep) undo the 1F1B default
        pp_state['n_micro_defaulted'] = acc <= 1

    # amp -> O2 compute-dtype policy inside the step (reference fleet
    # AMPOptimizer); bf16 is TPU-native, fp16 only on explicit request
    amp_dtype = None
    if sdict['amp']:
        pure_fp16 = s.amp_configs.get('use_pure_fp16', False) and \
            not s.amp_configs.get('use_bf16', True)
        amp_dtype = 'float16' if pure_fp16 else 'bfloat16'
        if s.amp_configs.get('custom_white_list') or \
                s.amp_configs.get('custom_black_list'):
            import warnings
            warnings.warn(
                'fleet amp runs the O2 pure-%s policy inside the jitted '
                'step; custom_white_list/custom_black_list apply only to '
                'the eager paddle.amp.auto_cast path and are ignored here'
                % amp_dtype)
    sdict['amp_dtype'] = amp_dtype

    # (dropout composes with sp since r4: non-attention dropout partitions
    # under GSPMD, attention-prob dropout rides sp-aware folded keys in
    # distributed/sp.py sp_attention)

    # recompute -> per-block remat when the model declares segments
    # (enable_recompute), else whole-forward remat in the step. Always set
    # two-way: a True left by an earlier fleet_train_step on the same
    # model must not leak into a recompute=False build.
    remat = False
    if hasattr(model, 'enable_recompute'):
        model.enable_recompute(bool(sdict['recompute']))
    elif sdict['recompute']:
        remat = True

    # vocab-parallel fused CE (reference: c_softmax_with_cross_entropy,
    # operators/collective/): under plain tensor parallelism constrain
    # the fused-loss logits tiles to [rows over dp/sharding, vocab over
    # mp] so GSPMD computes the CE vocab-parallel (local max/sum + small
    # all-reduce) instead of gathering the vocab axis per device — the
    # r4 HLO evidence showed gathered f32[rows, vocab] tiles dominating
    # CE-region memory (769 -> 435 MB peak temp at BERT dims dp2 x mp4).
    # Restricted to sp/pp == 1: under sp the flattened rows mix
    # sp-sharded sequence, under pp the loss runs inside the pipeline
    # engine — both have their own layouts.
    fce_sharding = None
    mshape = dict(hcg.mesh.shape)
    if mshape.get('mp', 1) > 1 and mshape.get('sp', 1) <= 1 \
            and mshape.get('pp', 1) <= 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        rows = tuple(a for a in ('dp', 'sharding') if mshape.get(a, 1) > 1)
        fce_sharding = NamedSharding(
            hcg.mesh, P(rows if rows else None, 'mp'))

    cfg = strategy_mod.build_shardings(model, optimizer, hcg.mesh, sdict)
    strategy_mod.place_params(model, cfg['param_shardings'])
    strategy_mod.place_opt_slots(model, optimizer, cfg['out_shardings'][2])
    step = func_mod.TrainStep(
        model, loss_fn, optimizer,
        out_shardings=cfg['out_shardings'],
        mesh=hcg.mesh,
        batch_sharding=cfg['batch_sharding'],
        k_steps=gm_k,
        grad_merge_avg=s.gradient_merge_configs.get('avg', True)
        if s.gradient_merge else True,
        amp_dtype=amp_dtype,
        remat=remat,
        sp_state=sp_state,
        pp_state=pp_state,
        init_loss_scaling=s.amp_configs.get('init_loss_scaling', 65536.0),
        ls_growth_interval=s.amp_configs.get('incr_every_n_steps', 2000),
        fce_sharding=fce_sharding)
    return step


def minimize(loss, startup_program=None, parameter_list=None,
             no_grad_set=None):
    opt = _FLEET['optimizer']
    return opt.minimize(loss)


def distributed_scaler(scaler):
    return scaler


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_worker():
    return _FLEET['role_maker']._is_worker() if _FLEET['role_maker'] else True


def is_server():
    return _FLEET['role_maker']._is_server() if _FLEET['role_maker'] else False


def is_first_worker():
    return get_rank() == 0


def worker_endpoints(to_string=False):
    eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '127.0.0.1:6170').split(',')
    return ','.join(eps) if to_string else eps


def server_endpoints(to_string=False):
    eps = os.environ.get('PADDLE_PSERVERS_IP_PORT_LIST', '').split(',')
    return ','.join(eps) if to_string else eps


def barrier_worker():
    """reference fleet_base.py barrier_worker: in PS mode, rendezvous all
    workers through the service's BarrierTable (the reference the_one_ps
    reserves a table id for this — configure it via
    PADDLE_FLEET_BARRIER_TABLE_ID); collective mode under
    single-controller SPMD has no cross-process eager phase to order, so
    it is a no-op there by design."""
    from ..ps import runtime as ps_runtime
    client = ps_runtime.get_client()
    tid = os.environ.get('PADDLE_FLEET_BARRIER_TABLE_ID')
    if client is not None and tid is not None:
        client.barrier(int(tid), worker_id=worker_index())


def init_worker():
    """PS-mode worker init (reference the_one_ps.py:486): starts the
    embedding-service client when a PS strategy is active."""
    from ..ps import runtime as ps_runtime
    ps_runtime.init_worker(_FLEET)


def init_server(*args, **kwargs):
    from ..ps import runtime as ps_runtime
    ps_runtime.init_server(_FLEET, *args, **kwargs)


def run_server():
    from ..ps import runtime as ps_runtime
    ps_runtime.run_server(_FLEET)


def stop_worker():
    from ..ps import runtime as ps_runtime
    ps_runtime.stop_worker(_FLEET)


def save_inference_model(*args, **kwargs):
    from ...static import save_inference_model as _s
    return _s(*args, **kwargs)


def save_persistables(executor, dirname, main_program=None, mode=0):
    """reference fleet save_persistables: PS mode saves the server-side
    tables through the service; otherwise the registered fleet model's
    state_dict is written under `dirname` (the persistables of the
    single-controller job)."""
    from ..ps import runtime as ps_runtime
    client = ps_runtime.get_client()
    if client is not None:
        # sparse side: every service table listed for this job
        tids = os.environ.get('PADDLE_FLEET_PS_TABLE_IDS', '0')
        for tid in tids.split(','):
            client.save(int(tid), os.path.join(dirname,
                                               'table_%s' % tid.strip()))
        return
    model = _FLEET['model']
    if model is None:
        raise RuntimeError('save_persistables: no fleet model registered '
                           '(call fleet.distributed_model first) and no '
                           'PS service is running')
    from ... import save as paddle_save
    os.makedirs(dirname, exist_ok=True)
    paddle_save(model.state_dict(),
                os.path.join(dirname, 'persistables.pdparams'))
