"""DataParallel + init_parallel_env (reference: python/paddle/distributed/
parallel.py, fluid/dygraph/parallel.py DataParallel, imperative/reducer.cc).

TPU-native: there is no Reducer/bucket machinery — gradient sync is the psum
XLA inserts when the train step is jitted with batch sharded over 'dp' and
params replicated. DataParallel therefore marks the model and hands the real
work to the strategy compiler (strategy.py); its eager behavior is identity
(single-controller SPMD has no per-process eager allreduce to do).
"""
from .env import init_parallel_env, ParallelEnv, get_rank, get_world_size  # noqa: F401


class DataParallel:
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        from ..nn.layer.layers import Layer
        if not isinstance(layers, Layer):
            raise TypeError('DataParallel expects a paddle Layer, got %s'
                            % type(layers).__name__)
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        layers._is_data_parallel = True
        self._dp_marked = True
        # register with fleet so a later fleet.fleet_train_step /
        # distributed_optimizer picks this model up (paddle users wrap
        # with DataParallel OR fleet.distributed_model — same effect here)
        from . import fleet as fleet_mod
        if getattr(fleet_mod, '_FLEET', None) is not None and \
                fleet_mod._FLEET.get('model') is None:
            fleet_mod._FLEET['model'] = layers

    def no_sync(self):
        """paddle DataParallel.no_sync parity: under SPMD the gradient
        all-reduce is part of the compiled step (there is no per-layer
        eager sync to suppress), so this context only exists so ported
        training loops run unchanged."""
        import contextlib
        return contextlib.nullcontext()

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__dict__['_layers'], name)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    @property
    def layers(self):
        return self._layers
