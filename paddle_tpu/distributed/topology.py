"""Process/device topology (reference: fleet/base/topology.py:36
CommunicateTopology + :117 HybridCommunicateGroup, 4-D [data, pipe, sharding,
model] mesh).

TPU-native: the topology IS a jax.sharding.Mesh. Axes (outer->inner):
  dp (data), pp (pipeline), sharding (ZeRO), ep (experts), mp (tensor),
  sp (sequence). sp and ep are beyond-reference (SURVEY.md §5.7 and §2.2
  note their absence; the capability bar includes them). Axis order puts mp/sp
innermost so tensor/sequence collectives ride the fastest ICI links.
"""
import collections
import os

import numpy as np
import jax
from jax.sharding import Mesh

_AXES = ('dp', 'pp', 'sharding', 'ep', 'mp', 'sp')


def _dcn_aware_order(devices):
    """Order devices (slice_index, process_index, id) so the mesh reshape
    keeps the INNER axes (mp/sp/ep/sharding/pp) inside one ICI slice and
    only the outermost dp axis crosses DCN slice boundaries — the
    TPU-native analog of the reference's NVLink-vs-IB multi-ring
    hierarchy (nccl_helper.h:190 NCCLCommunicator). Single-slice and CPU
    devices have no slice_index; the sort is then a stable no-op.
    Full design: docs/dcn_multislice.md."""
    return sorted(devices,
                  key=lambda d: (getattr(d, 'slice_index', 0) or 0,
                                 getattr(d, 'process_index', 0) or 0,
                                 getattr(d, 'id', 0) or 0))


class CommunicateTopology:
    def __init__(self, hybrid_group_names=('data', 'pipe', 'sharding', 'model'),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple('Coordinate',
                                                 self._parallel_names)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **args):
        idx = [args[name] for name in self._parallel_names]
        return int(np.ravel_multi_index(idx, self._dims))

    def get_coord(self, rank):
        return self.coordinate(*np.unravel_index(rank, self._dims))


class HybridCommunicateGroup:
    """Builds the global device mesh. Parity surface: get_data_parallel_rank
    etc. (topology.py:123-136); the jax Mesh is exposed for the strategy
    compiler."""

    def __init__(self, dp_degree=1, mp_degree=1, pp_degree=1,
                 sharding_degree=1, sp_degree=1, ep_degree=1, devices=None):
        if devices is None:
            devices = _dcn_aware_order(jax.devices())
        n = len(devices)
        degrees = {'dp': dp_degree, 'pp': pp_degree,
                   'sharding': sharding_degree, 'mp': mp_degree,
                   'sp': sp_degree, 'ep': ep_degree}
        specified = int(np.prod([max(1, d) for d in degrees.values()]))
        if dp_degree in (0, -1, None):
            rest = int(np.prod([max(1, degrees[a]) for a in
                                ('pp', 'sharding', 'ep', 'mp', 'sp')]))
            degrees['dp'] = max(1, n // rest)
        total = int(np.prod([max(1, degrees[a]) for a in _AXES]))
        if total != n:
            raise ValueError(
                "product of parallel degrees %s != device count %d"
                % (degrees, n))
        self._degrees = degrees
        shape = tuple(max(1, degrees[a]) for a in _AXES)
        mesh_devices = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(mesh_devices, _AXES)
        self.nranks = n
        self.global_rank = 0

    # -- per-axis parity accessors (reference names) ------------------------
    def get_data_parallel_world_size(self):
        return self._degrees['dp']

    def get_model_parallel_world_size(self):
        return self._degrees['mp']

    def get_pipe_parallel_world_size(self):
        return self._degrees['pp']

    def get_sharding_parallel_world_size(self):
        return self._degrees['sharding']

    def get_sequence_parallel_world_size(self):
        return self._degrees['sp']

    def get_expert_parallel_world_size(self):
        return self._degrees['ep']

    def get_expert_parallel_group(self):
        return Group('ep', self._degrees['ep'])

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def topology(self):
        return CommunicateTopology(
            ('data', 'pipe', 'sharding', 'model'),
            (self._degrees['dp'], self._degrees['pp'],
             self._degrees['sharding'], self._degrees['mp']))

    # group objects for collective API parity
    def get_data_parallel_group(self):
        return Group('dp', self._degrees['dp'])

    def get_model_parallel_group(self):
        return Group('mp', self._degrees['mp'])

    def get_pipe_parallel_group(self):
        return Group('pp', self._degrees['pp'])

    def get_sharding_parallel_group(self):
        return Group('sharding', self._degrees['sharding'])

    def get_check_parallel_group(self):
        return Group(None, self.nranks)


class Group:
    """Communicator handle: on TPU a group IS a mesh axis name (replaces
    ring_id -> NCCLComm registry, platform/collective_helper.h:68)."""

    def __init__(self, axis_name, nranks, ranks=None, gid=0):
        self.axis_name = axis_name
        self.nranks = nranks
        self.ranks = ranks if ranks is not None else list(range(nranks))
        self.id = gid
        self.rank = 0

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return 'Group(axis=%s, nranks=%d)' % (self.axis_name, self.nranks)


_GLOBAL_HCG = [None]


def set_hybrid_communicate_group(hcg):
    _GLOBAL_HCG[0] = hcg


def get_hybrid_communicate_group():
    return _GLOBAL_HCG[0]


def default_mesh(axis='dp', devices=None):
    """Single-axis mesh over all devices (pure-DP default)."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))
