"""Model compression (slim): quantization-aware training and post-training
quantization.

Reference: python/paddle/fluid/contrib/slim/ — quantization_pass.py (static
QAT graph rewrite), imperative/qat.py (ImperativeQuantAware),
post_training_quantization.py, cal_kl_threshold.py. On TPU the static-graph
rewrite and the imperative wrapper collapse into one mechanism (layer
swapping; XLA compiles either way), so one API serves both modes.
"""
from .cal_kl_threshold import cal_kl_threshold
from .ptq import ImperativePTQ, PostTrainingQuantization
from .qat import ImperativeQuantAware
from .quant_layers import (FakeQuantAbsMax, FakeQuantMovingAverageAbsMax,
                           QuantedConv2D, QuantedLinear,
                           fake_quant_dequant_abs_max,
                           fake_quant_dequant_channel_wise,
                           fake_quant_dequant_with_scale)
from .weight_only import (WeightOnlyLinear, quantize_weight_only,
                          streamed_bytes)

__all__ = [
    'ImperativeQuantAware', 'PostTrainingQuantization', 'ImperativePTQ',
    'cal_kl_threshold', 'QuantedLinear', 'QuantedConv2D', 'FakeQuantAbsMax',
    'FakeQuantMovingAverageAbsMax', 'fake_quant_dequant_abs_max',
    'fake_quant_dequant_channel_wise', 'fake_quant_dequant_with_scale',
    'WeightOnlyLinear', 'quantize_weight_only', 'streamed_bytes',
]
