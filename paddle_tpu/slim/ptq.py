"""Post-training quantization.

Parity: python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py:124 (PostTrainingQuantization: run calibration
batches, sample activation ranges with abs_max/avg/mse/KL, then emit a
quantized model) and imperative/ptq.py (ImperativePTQ). TPU-native: the
"program + executor + scope surgery" pipeline collapses to forward hooks on
the live Layer — observers collect ranges, then quantizable layers are
swapped for fake-quant wrappers with frozen calibrated scales.
"""
import numpy as np

from .. import nn
from ..framework.core import Tensor
from .cal_kl_threshold import cal_kl_threshold
from .qat import ImperativeQuantAware
from .quant_layers import (QUANT_LAYER_MAP, FakeQuantMovingAverageAbsMax,
                           QuantedConv2D, QuantedLinear,
                           resolve_quant_types)

__all__ = ['PostTrainingQuantization', 'ImperativePTQ']

_ALGOS = ('abs_max', 'avg', 'mse', 'KL', 'hist')


class _Observer:
    """Collects activation range stats for one layer's input."""

    def __init__(self, algo, bits, hist_bins=2048, hist_percent=0.99999):
        self.algo = algo
        self.bits = bits
        self.hist_bins = hist_bins
        self.hist_percent = hist_percent
        self.abs_max = 0.0
        self.batch_maxes = []
        self.samples = []
        self.hist = None
        self.hist_range = 0.0
        self._mse_rng = np.random.RandomState(0)

    def _rebin(self, new_range):
        """Proportionally redistribute hist counts from [0, hist_range)
        into [0, new_range) so batches with growing ranges merge correctly
        (the reference re-bins before merging too)."""
        old = self.hist
        bins = self.hist_bins
        out = np.zeros(bins, np.float64)
        ratio = self.hist_range / new_range
        for i in range(bins):
            if old[i] == 0:
                continue
            lo = i * ratio
            hi = (i + 1) * ratio
            j0, j1 = int(lo), min(int(np.ceil(hi)), bins)
            width = hi - lo
            for j in range(j0, j1):
                overlap = min(hi, j + 1) - max(lo, j)
                if overlap > 0:
                    out[j] += old[i] * overlap / width
        self.hist = out
        self.hist_range = new_range

    def observe(self, arr):
        arr = np.asarray(arr, np.float32)
        amax = float(np.max(np.abs(arr))) if arr.size else 0.0
        self.abs_max = max(self.abs_max, amax)
        self.batch_maxes.append(amax)
        if self.algo == 'mse':
            # subsample to bound memory (collisions are harmless here, so a
            # plain randint draw beats an O(n) no-replacement permutation)
            flat = arr.reshape(-1)
            if flat.size > 1 << 16:
                # persistent rng: each batch samples different positions
                idx = self._mse_rng.randint(0, flat.size, 1 << 16)
                flat = flat[idx]
            self.samples.append(flat)
        elif self.algo in ('KL', 'hist'):
            rng_hi = max(self.abs_max, 1e-8)
            if self.hist is None:
                self.hist = np.zeros(self.hist_bins, np.float64)
                self.hist_range = rng_hi
            elif rng_hi > self.hist_range:
                self._rebin(rng_hi)
            h, _ = np.histogram(np.abs(arr), bins=self.hist_bins,
                                range=(0.0, self.hist_range))
            self.hist += h

    def scale(self):
        if self.algo == 'abs_max':
            return self.abs_max
        if self.algo == 'avg':
            return float(np.mean(self.batch_maxes)) if self.batch_maxes \
                else 0.0
        if self.algo == 'mse':
            return self._mse_scale()
        if self.algo == 'KL':
            if self.hist is None:
                return self.abs_max
            bin_width = self.hist_range / self.hist_bins
            return cal_kl_threshold(self.hist, bin_width, self.bits)
        if self.algo == 'hist':
            if self.hist is None:
                return self.abs_max
            cum = np.cumsum(self.hist) / max(np.sum(self.hist), 1)
            idx = int(np.searchsorted(cum, self.hist_percent))
            return (idx + 0.5) * self.hist_range / self.hist_bins
        raise ValueError(self.algo)

    def _mse_scale(self):
        if not self.samples:
            return self.abs_max
        x = np.concatenate(self.samples)
        qmax = 2 ** (self.bits - 1) - 1
        best, best_s = None, self.abs_max
        for frac in np.linspace(0.3, 1.0, 36):
            s = self.abs_max * frac
            if s <= 0:
                continue
            xq = np.clip(np.round(x / s * qmax), -qmax, qmax) * s / qmax
            mse = float(np.mean((x - xq) ** 2))
            if best is None or mse < best:
                best, best_s = mse, s
        return best_s


class PostTrainingQuantization:
    """Calibrate a Layer on sample data and return a fake-quantized model.

    Differences from the reference ctor are deliberate (no executor/scope on
    TPU): pass the live model + a data source. `data_loader` yields either
    arrays/Tensors (fed as the single input) or tuples/lists (fed
    positionally; a trailing label entry is allowed and dropped on feed
    error — match of the reference's feed-list behavior).
    """

    def __init__(self, model=None, data_loader=None, batch_nums=10,
                 algo='abs_max', hist_percent=0.99999, bins=2048,
                 quantizable_op_type=('Conv2D', 'Linear'),
                 weight_bits=8, activation_bits=8,
                 weight_quantize_type='channel_wise_abs_max',
                 onnx_format=False, **_compat):
        if algo not in _ALGOS:
            raise ValueError('algo must be one of %s' % (_ALGOS,))
        if model is None or data_loader is None:
            raise ValueError('model and data_loader are required')
        if weight_quantize_type not in ('abs_max', 'channel_wise_abs_max'):
            raise ValueError('weight_quantize_type must be abs_max or '
                             'channel_wise_abs_max')
        self._model = model
        self._loader = data_loader
        self._batch_nums = batch_nums
        self._algo = algo
        self._bins = bins
        self._hist_percent = hist_percent
        self._types = resolve_quant_types(quantizable_op_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._wq_type = weight_quantize_type
        self._scales = {}

    def _target_layers(self):
        classes = tuple(QUANT_LAYER_MAP[t][0] for t in self._types)
        for name, sub in self._model.named_sublayers():
            if type(sub) in classes and not getattr(sub, 'skip_quant', False):
                yield name, sub

    def quantize(self):
        # 1. observe activation ranges via pre-hooks
        observers, removes = {}, []
        for name, sub in self._target_layers():
            obs = _Observer(self._algo, self._abits, self._bins,
                            self._hist_percent)
            observers[name] = obs

            def hook(layer, inputs, _obs=obs):
                x = inputs[0]
                _obs.observe(x._data if isinstance(x, Tensor) else x)
                return None
            removes.append(sub.register_forward_pre_hook(hook))

        # decide feed arity up front (no retry — a retry after a mid-model
        # TypeError would double-count observations on early layers).
        # Count ALL positional params (optional ones included: a loader may
        # legitimately supply them); only the surplus beyond that — e.g. a
        # trailing label — is dropped.
        import inspect
        n_feed = None
        try:
            sig = inspect.signature(self._model.forward)
            ps = [p for p in sig.parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
            if not any(p.kind == p.VAR_POSITIONAL
                       for p in sig.parameters.values()):
                n_feed = len(ps)
        except (TypeError, ValueError):
            pass

        self._model.eval()
        seen = 0
        try:
            for batch in self._loader:
                args = batch if isinstance(batch, (tuple, list)) else (batch,)
                if n_feed is not None and len(args) > n_feed:
                    args = args[:n_feed]  # drop trailing label entries
                self._model(*args)
                seen += 1
                if self._batch_nums and seen >= self._batch_nums:
                    break
        finally:
            # never leave observer hooks on the user's live model
            for r in removes:
                r.remove()
        if seen == 0:
            raise RuntimeError('data_loader yielded no calibration batches')

        # 2. swap in quanted layers with frozen calibrated scales
        quanter = ImperativeQuantAware(
            quantizable_layer_type=self._types,
            weight_quantize_type=self._wq_type,
            activation_quantize_type='moving_average_abs_max',
            weight_bits=self._wbits, activation_bits=self._abits)
        quanter.quantize(self._model)
        import jax.numpy as jnp
        for name, sub in self._model.named_sublayers():
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                # the wrapper replaced the original at the same name path
                obs = observers.get(name)
                if obs is None:
                    continue
                s = float(obs.scale())
                self._scales[name] = s
                aq = sub._act_quanter
                if isinstance(aq, FakeQuantMovingAverageAbsMax):
                    aq.scale._data = jnp.asarray(s, jnp.float32)
                    aq.initialized._data = jnp.ones([], jnp.int32)
        self._model.eval()
        return self._model

    @property
    def scales(self):
        return dict(self._scales)

    def save_quantized_model(self, save_model_path, input_spec=None,
                             **config):
        from .. import jit
        jit.save(self._model, save_model_path, input_spec=input_spec,
                 **config)


ImperativePTQ = PostTrainingQuantization
