"""KL-divergence calibration threshold.

Parity: python/paddle/fluid/contrib/slim/quantization/cal_kl_threshold.py
(the TensorRT-style entropy calibration: pick the clip threshold whose
quantized distribution has minimal KL divergence from the original
histogram).
"""

import numpy as np

__all__ = ['cal_kl_threshold']


def _expand_quantized_bins(quantized_bins, reference_bins):
    """Spread each quantized bin's mass uniformly over its nonzero source
    bins (zero-count source bins stay zero). Vectorized: the search loop
    calls this ~hist_bins/2 times per layer."""
    n_ref = len(reference_bins)
    n_q = len(quantized_bins)
    num_merged = n_ref // n_q if n_q else 0
    if num_merged == 0:
        return np.zeros(n_ref, np.float64)
    # group index per reference bin; the last group absorbs the remainder
    groups = np.minimum(np.arange(n_ref) // num_merged, n_q - 1)
    nonzero = np.asarray(reference_bins) != 0
    nz_per_group = np.bincount(groups[nonzero], minlength=n_q)
    with np.errstate(divide='ignore', invalid='ignore'):
        avg = np.where(nz_per_group > 0,
                       np.asarray(quantized_bins) / np.maximum(nz_per_group,
                                                               1), 0.0)
    return np.where(nonzero, avg[groups], 0.0)


def _safe_kl(reference, candidate):
    """KL(reference || candidate) over matching bins, skipping zeros."""
    total = float(np.sum(reference))
    if total <= 0:
        return float('inf')
    p_pos = reference > 0
    if np.any(p_pos & (candidate <= 0)):
        return float('inf')
    p = reference[p_pos]
    q = candidate[p_pos]
    return float(np.sum(p * np.log(p / q))) / total


def cal_kl_threshold(hist, bin_width, bits):
    """Return the activation clip threshold for `hist` (histogram of |x|).

    hist: counts over [0, abs_max); bin_width: abs_max/len(hist);
    bits: target bit width (8 → 127 positive quant levels, matching the
    reference's 2**(bits-1)-1).
    """
    assert hist.ndim == 1
    hist_bins = len(hist)
    starting_iter = hist_bins // 2
    quant_range = 2 ** (bits - 1) - 1

    p_sum = float(np.sum(hist))
    if p_sum <= 0 or hist_bins <= quant_range:
        return bin_width * hist_bins

    min_kl = float('inf')
    best_i = hist_bins
    for i in range(starting_iter, hist_bins + 1):
        reference = hist[:i].astype(np.float64).copy()
        # outliers beyond i clip into the last bin
        reference[-1] += float(np.sum(hist[i:]))
        if reference[-1] == 0 or quant_range >= i:
            continue
        # quantize reference into quant_range merged bins
        num_merged = i // quant_range
        used = num_merged * quant_range
        q = reference[:used].reshape(quant_range, num_merged).sum(axis=1)
        q[-1] += float(np.sum(reference[used:]))
        candidate = _expand_quantized_bins(q, reference)
        kl = _safe_kl(reference, candidate)
        if kl < min_kl:
            min_kl = kl
            best_i = i
    return (best_i + 0.5) * bin_width
