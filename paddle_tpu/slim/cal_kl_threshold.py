"""KL-divergence calibration threshold.

Parity: python/paddle/fluid/contrib/slim/quantization/cal_kl_threshold.py
(the TensorRT-style entropy calibration: pick the clip threshold whose
quantized distribution has minimal KL divergence from the original
histogram).
"""
import math

import numpy as np

__all__ = ['cal_kl_threshold']


def _expand_quantized_bins(quantized_bins, reference_bins):
    """Spread each quantized bin's mass uniformly over its source bins
    (zero-count source bins stay zero)."""
    expanded = np.zeros(len(reference_bins), np.float64)
    num_merged = len(reference_bins) // len(quantized_bins) \
        if len(quantized_bins) else 0
    if num_merged == 0:
        return expanded
    j_start = 0
    for idx, q in enumerate(quantized_bins):
        j_end = len(reference_bins) if idx == len(quantized_bins) - 1 \
            else j_start + num_merged
        zero_count = int(np.count_nonzero(
            np.asarray(reference_bins[j_start:j_end]) == 0))
        num_bins = j_end - j_start
        nonzero = num_bins - zero_count
        avg = q / nonzero if nonzero else 0.0
        for j in range(j_start, j_end):
            expanded[j] = 0.0 if reference_bins[j] == 0 else avg
        j_start = j_end
    return expanded


def _safe_kl(reference, candidate):
    """KL(reference || candidate) over matching bins, skipping zeros."""
    total = float(np.sum(reference))
    if total <= 0:
        return float('inf')
    kl = 0.0
    for p, q in zip(reference, candidate):
        if p > 0:
            kl += math.inf if q <= 0 else p * math.log(p / q)
            if kl == math.inf:
                break
    return kl / total


def cal_kl_threshold(hist, bin_width, bits):
    """Return the activation clip threshold for `hist` (histogram of |x|).

    hist: counts over [0, abs_max); bin_width: abs_max/len(hist);
    bits: target bit width (8 → 127 positive quant levels, matching the
    reference's 2**(bits-1)-1).
    """
    assert hist.ndim == 1
    hist_bins = len(hist)
    starting_iter = hist_bins // 2
    quant_range = 2 ** (bits - 1) - 1

    p_sum = float(np.sum(hist))
    if p_sum <= 0 or hist_bins <= quant_range:
        return bin_width * hist_bins

    min_kl = float('inf')
    best_i = hist_bins
    for i in range(starting_iter, hist_bins + 1):
        reference = hist[:i].astype(np.float64).copy()
        # outliers beyond i clip into the last bin
        reference[-1] += float(np.sum(hist[i:]))
        if reference[-1] == 0 or quant_range >= i:
            continue
        # quantize reference into quant_range merged bins
        num_merged = i // quant_range
        used = num_merged * quant_range
        q = reference[:used].reshape(quant_range, num_merged).sum(axis=1)
        q[-1] += float(np.sum(reference[used:]))
        candidate = _expand_quantized_bins(q, reference)
        kl = _safe_kl(reference, candidate)
        if kl < min_kl:
            min_kl = kl
            best_i = i
    return (best_i + 0.5) * bin_width
