"""Fake-quant primitives and quantized layer wrappers.

TPU-native equivalent of the reference's fake_quantize ops + quant layers
(paddle/fluid/operators/fake_quantize_op.cc, python/paddle/fluid/contrib/
slim/quantization/imperative/quant_layers usage in qat.py). Quantization is
simulated (quantize-dequantize) with a straight-through estimator so QAT
trains on TPU inside jit; scales live as Layer buffers so they ride the
functional_call state path like BN running stats.
"""
import functools

import jax
import jax.numpy as jnp

from .. import nn
from ..framework.core import Tensor, run_op
from ..nn import functional as F

__all__ = [
    'fake_quant_dequant_abs_max', 'fake_quant_dequant_channel_wise',
    'fake_quant_dequant_with_scale', 'FakeQuantAbsMax',
    'FakeQuantMovingAverageAbsMax', 'QuantedLinear', 'QuantedConv2D',
    'QUANT_LAYER_MAP',
]

_EPS = 1e-9


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _ste_round_clip(y, qmax):
    return jnp.round(jnp.clip(y, -qmax, qmax))


def _ste_fwd(y, qmax):
    return _ste_round_clip(y, qmax), jnp.abs(y) <= qmax


def _ste_bwd(qmax, in_range, g):
    # straight-through inside [-qmax, qmax] (inclusive), zero outside —
    # lax.clip would split gradient 0.5/0.5 at exact boundaries
    return (jnp.where(in_range, g, 0.0),)


_ste_round_clip.defvjp(_ste_fwd, _ste_bwd)


def fake_quant_dequant_with_scale(x, scale, bits=8):
    """Quantize-dequantize against a given scale (per-tensor or broadcast).

    Gradient is straight-through inside the clip range, zero outside
    (reference fake_quantize_dequantize_moving_average_abs_max behavior).
    """
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(jnp.asarray(scale, x.dtype), _EPS)
    return _ste_round_clip(x / s * qmax, qmax) * s / qmax


def fake_quant_dequant_abs_max(x, bits=8):
    """Dynamic per-tensor abs-max quant-dequant (reference 'abs_max')."""
    scale = jnp.max(jnp.abs(x))
    return fake_quant_dequant_with_scale(x, jax.lax.stop_gradient(scale),
                                         bits)


def fake_quant_dequant_channel_wise(w, bits=8, axis=0):
    """Per-output-channel abs-max (reference 'channel_wise_abs_max')."""
    red = tuple(i for i in range(w.ndim) if i != axis)
    scale = jnp.max(jnp.abs(w), axis=red, keepdims=True)
    return fake_quant_dequant_with_scale(w, jax.lax.stop_gradient(scale),
                                         bits)


class FakeQuantAbsMax(nn.Layer):
    """Weight quanter: dynamic abs-max each call (no state)."""

    def __init__(self, bits=8, channel_wise=False, axis=0):
        super().__init__()
        self._bits = bits
        self._channel_wise = channel_wise
        self._axis = axis

    def forward(self, x):
        # through run_op so the eager tape records the STE vjp and grads
        # reach the (possibly Parameter) input
        if self._channel_wise:
            return run_op(
                'fake_quant_channel_wise',
                lambda a: fake_quant_dequant_channel_wise(
                    a, self._bits, self._axis), x)
        return run_op('fake_quant_abs_max',
                      lambda a: fake_quant_dequant_abs_max(a, self._bits), x)


class FakeQuantMovingAverageAbsMax(nn.Layer):
    """Activation quanter: EMA of abs-max during training, frozen scale in
    eval (reference 'moving_average_abs_max', moving_rate=0.9)."""

    def __init__(self, bits=8, moving_rate=0.9):
        super().__init__()
        self._bits = bits
        self._rate = moving_rate
        self.register_buffer('scale', Tensor(jnp.zeros([])))
        self.register_buffer('initialized', Tensor(jnp.zeros([], jnp.int32)))

    def forward(self, x):
        arr = x._data if isinstance(x, Tensor) else x
        if self.training:
            cur = jax.lax.stop_gradient(jnp.max(jnp.abs(arr))
                                        .astype(jnp.float32))
            inited = self.initialized._data > 0
            prev = self.scale._data
            new = jnp.where(inited, self._rate * prev + (1 - self._rate) * cur,
                            cur)
            self.scale._data = new
            self.initialized._data = jnp.ones([], jnp.int32)
            scale = new
        else:
            scale = jnp.where(self.scale._data > 0, self.scale._data,
                              jnp.max(jnp.abs(arr)).astype(jnp.float32))
        scale = jax.lax.stop_gradient(scale)
        return run_op(
            'fake_quant_moving_avg',
            lambda a: fake_quant_dequant_with_scale(
                a, scale.astype(a.dtype), self._bits), x)


def _make_weight_quanter(quantize_type, bits, axis):
    return FakeQuantAbsMax(bits=bits,
                           channel_wise=quantize_type == 'channel_wise_abs_max',
                           axis=axis)


def _make_act_quanter(quantize_type, bits, moving_rate):
    if quantize_type == 'moving_average_abs_max':
        return FakeQuantMovingAverageAbsMax(bits=bits,
                                            moving_rate=moving_rate)
    return FakeQuantAbsMax(bits=bits)


class QuantedLinear(nn.Layer):
    """Linear with fake-quantized input and weight (qat.py QuantizedLinear)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max',
                 moving_rate=0.9):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        # paddle Linear weight is [in, out]: per-channel axis is 1
        self._weight_quanter = _make_weight_quanter(weight_quantize_type,
                                                    weight_bits, axis=1)
        self._act_quanter = _make_act_quanter(activation_quantize_type,
                                              activation_bits, moving_rate)

    def forward(self, x):
        xq = self._act_quanter(x)
        wq = self._weight_quanter(self.weight)
        return F.linear(xq, wq, self.bias)


class QuantedConv2D(nn.Layer):
    """Conv2D with fake-quantized input and weight (qat.py QuantizedConv2D)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max',
                 moving_rate=0.9):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self._stride = layer._stride
        self._padding = layer._padding
        self._dilation = layer._dilation
        self._groups = layer._groups
        self._data_format = getattr(layer, '_data_format', 'NCHW')
        # conv weight is [out, in/g, kh, kw]: per-channel axis 0
        self._weight_quanter = _make_weight_quanter(weight_quantize_type,
                                                    weight_bits, axis=0)
        self._act_quanter = _make_act_quanter(activation_quantize_type,
                                              activation_bits, moving_rate)

    def forward(self, x):
        xq = self._act_quanter(x)
        wq = self._weight_quanter(self.weight)
        return F.conv2d(xq, wq, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


QUANT_LAYER_MAP = {
    'Linear': (nn.Linear, QuantedLinear),
    'Conv2D': (nn.Conv2D, QuantedConv2D),
}

# the reference's static-graph op names, accepted as aliases by both QAT
# and PTQ constructors
QUANT_TYPE_ALIASES = {
    'conv2d': 'Conv2D', 'depthwise_conv2d': 'Conv2D',
    'linear': 'Linear', 'mul': 'Linear', 'matmul': 'Linear',
}


def resolve_quant_types(types):
    """Normalize user-provided quantizable layer/op types to
    QUANT_LAYER_MAP keys; raises ValueError on unknown names."""
    out = []
    for t in types:
        key = t if isinstance(t, str) else t.__name__
        key = QUANT_TYPE_ALIASES.get(key, key)
        if key not in QUANT_LAYER_MAP:
            raise ValueError('unsupported quantizable type %r '
                             '(supported: %s + aliases %s)'
                             % (t, sorted(QUANT_LAYER_MAP),
                                sorted(QUANT_TYPE_ALIASES)))
        out.append(key)
    return tuple(dict.fromkeys(out))
