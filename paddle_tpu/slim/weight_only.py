"""Weight-only int8 quantization for the serving/decode path.

Different animal from the QDQ fake-quant in quant_layers.py: here the
weights are STORED as int8 and dequantized inside the compiled program, so
each decode step streams half the weight bytes from HBM. Cached
autoregressive decode is weight-streaming-bound (see
bench_extra.bench_gpt_decode's roofline), so halving the streamed bytes
raises the decode throughput ceiling ~2x on the quantized fraction of the
weights. The dequant (convert + per-channel scale multiply) happens in
VMEM and fuses into the matmul operand read under XLA.

Reference counterpart: the inference engine's int8 paths — TensorRT INT8
calibration (/root/reference/paddle/fluid/inference/tensorrt/
trt_int8_calibrator.cc) and the MKLDNN quantizer
(/root/reference/paddle/fluid/inference/api/mkldnn_quantizer.cc) — which
likewise quantize a trained model for serving without retraining. The
TPU-native form keeps activations in the compute dtype (weight-only):
decode activations are tiny [batch, hidden] rows, so activation
quantization buys no bandwidth and costs accuracy.

Scales are per-output-channel symmetric abs-max over the [in, out] weight
(same choice as quant_layers' channel-wise axis=1), held as buffers so
they cross the functional_call/jit boundary with the rest of the state.
"""
import jax.numpy as jnp

from .. import nn
from ..framework.core import Tensor
from ..nn import functional as F

__all__ = ['WeightOnlyLinear', 'quantize_weight_only', 'streamed_bytes']

_EPS = 1e-8


def _quantize_int8(w):
    """Per-output-channel symmetric int8: w[in, out] -> (q int8, scale f32)."""
    wf = jnp.asarray(w, jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=0) / 127.0, _EPS)
    q = jnp.clip(jnp.round(wf / scale[None, :]), -127, 127).astype(jnp.int8)
    return q, scale


class WeightOnlyLinear(nn.Layer):
    """Linear whose weight lives as int8 + per-channel scale buffers.

    Built FROM a trained nn.Linear (same swap-in pattern as
    slim.QuantedLinear). Inference-only: the int8 buffer is not a
    Parameter, so nothing here is trainable — training through it raises
    rather than silently freezing the weight.
    """

    def __init__(self, layer):
        super().__init__()
        w = layer.weight._data
        self._in_features = layer._in_features
        self._out_features = layer._out_features
        # compute dtype follows the source weight (bf16 on TPU serving)
        self._compute_dtype = w.dtype
        q, scale = _quantize_int8(w)
        self.register_buffer('qweight', Tensor(q))
        self.register_buffer('weight_scale', Tensor(scale))
        self.bias = layer.bias
        # inherit the source layer's mode: a model already in eval() must
        # stay servable after the swap without another .eval() call
        self.training = layer.training

    def forward(self, x):
        if self.training:
            raise RuntimeError(
                'WeightOnlyLinear is inference-only (int8 weights are not '
                'trainable) — call model.eval(), or quantize after training')
        w = (self.qweight._data.astype(self._compute_dtype) *
             self.weight_scale._data.astype(self._compute_dtype))
        return F.linear(x, Tensor(w), self.bias)

    def extra_repr(self):
        return 'in_features=%d, out_features=%d, int8-weight' % (
            self._in_features, self._out_features)


def streamed_bytes(model):
    """Bytes of model state one decode step streams from HBM: all params
    plus weight-carrying buffers (int8 qweights count 1 byte/element,
    their scales count too). This is the denominator of the decode
    roofline `steps/s <= HBM_BW / streamed_bytes` used by bench_extra's
    decode and serving rungs — defined here so the quantized and
    full-precision models are measured by one rule.
    """
    total = 0
    for _, p in model.named_parameters():
        total += int(p._data.nbytes)
    for _, b in model.named_buffers():
        if b is not None:
            total += int(b._data.nbytes)
    return float(total)


def quantize_weight_only(model, exclude=None):
    """Swap every nn.Linear sublayer for WeightOnlyLinear, in place.

    exclude: optional predicate (qualified_name, layer) -> bool; True
    keeps that Linear in full precision (e.g. a final logits head whose
    accuracy budget is tighter). Returns the number of layers swapped.

    Embeddings stay full precision by design: a gather reads only the
    touched rows, so there is no bandwidth to win, and the tied-head
    matmul (GPT wte reuse) shares the same storage.
    """
    if type(model) is nn.Linear:
        # the root layer cannot be swapped in place — the caller's own
        # reference IS the Linear, and rebinding it is outside our reach.
        # Returning 0 here used to look like "nothing to quantize";
        # refuse loudly instead (unless the exclude predicate keeps the
        # root fp on purpose, which really is a no-op).
        if exclude is not None and exclude('', model):
            return 0
        raise ValueError(
            'quantize_weight_only cannot swap a bare root nn.Linear in '
            'place — wrap it yourself: model = WeightOnlyLinear(model)')
    # snapshot the walk first: swapping children while the generator is
    # mid-descent would make it recurse into the replacement layers
    sites = []          # (parent, key, child) for every Linear occurrence
    excluded = set()    # id(child): exclusion is by layer IDENTITY — if
    #                     ANY alias of a shared Linear is excluded, every
    #                     alias stays fp (a partial swap would silently
    #                     break the sharing)
    for pname, parent in list(model.named_sublayers(include_self=True)):
        for key, child in list(parent._sub_layers.items()):
            if type(child) is nn.Linear:
                sites.append((parent, key, child))
                qual = '%s.%s' % (pname, key) if pname else key
                if exclude is not None and exclude(qual, child):
                    excluded.add(id(child))
    swapped = 0
    done = {}  # id(original) -> replacement: a shared Linear stays shared
    for parent, key, child in sites:
        if id(child) in excluded:
            continue
        rep = done.get(id(child))
        if rep is None:
            rep = done[id(child)] = WeightOnlyLinear(child)
            swapped += 1
        parent._sub_layers[key] = rep
    return swapped
