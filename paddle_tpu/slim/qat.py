"""Imperative quantization-aware training.

Parity: python/paddle/fluid/contrib/slim/quantization/imperative/qat.py:40
(ImperativeQuantAware.quantize walks the model and swaps quantizable layers
for fake-quant wrappers; save_quantized_model exports for inference).
"""
from .. import nn
from .quant_layers import QUANT_LAYER_MAP, resolve_quant_types

__all__ = ['ImperativeQuantAware']


class ImperativeQuantAware:
    def __init__(self, quantizable_layer_type=('Conv2D', 'Linear'),
                 weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max',
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_preprocess_layer=None, act_preprocess_layer=None,
                 weight_quantize_layer=None, act_quantize_layer=None):
        types = resolve_quant_types(quantizable_layer_type)
        if weight_quantize_type not in ('abs_max', 'channel_wise_abs_max'):
            raise ValueError('weight_quantize_type must be abs_max or '
                             'channel_wise_abs_max')
        if activation_quantize_type not in ('abs_max',
                                            'moving_average_abs_max'):
            raise ValueError('activation_quantize_type must be abs_max or '
                             'moving_average_abs_max')
        if any(l is not None for l in (weight_preprocess_layer,
                                       act_preprocess_layer,
                                       weight_quantize_layer,
                                       act_quantize_layer)):
            raise NotImplementedError(
                'custom preprocess/quantize layers are not supported yet; '
                'use weight_quantize_type/activation_quantize_type')
        self._types = types
        self._wq_type = weight_quantize_type
        self._aq_type = activation_quantize_type
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate

    def _wrap(self, layer, memo):
        for tname in self._types:
            cls, quanted = QUANT_LAYER_MAP[tname]
            if type(layer) is cls:
                if getattr(layer, 'skip_quant', False):
                    return layer
                # a layer shared at several model paths gets ONE wrapper
                # (so e.g. PTQ scale assignment covers every path)
                if id(layer) not in memo:
                    memo[id(layer)] = quanted(
                        layer, weight_bits=self._wbits,
                        activation_bits=self._abits,
                        weight_quantize_type=self._wq_type,
                        activation_quantize_type=self._aq_type,
                        moving_rate=self._rate)
                return memo[id(layer)]
        return layer

    def quantize(self, model):
        """In-place: swap quantizable sublayers for fake-quant wrappers.
        Returns the model (reference returns None; returning it is a strict
        superset)."""
        if not isinstance(model, nn.Layer):
            raise TypeError('quantize expects a paddle Layer')
        memo = {}
        for layer in model.sublayers(include_self=True):
            for name, sub in list(layer._sub_layers.items()):
                layer._sub_layers[name] = self._wrap(sub, memo)
        return model

    def save_quantized_model(self, layer, path, input_spec=None, **config):
        from .. import jit
        layer.eval()
        jit.save(layer, path, input_spec=input_spec, **config)
