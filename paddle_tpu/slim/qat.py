"""Imperative quantization-aware training.

Parity: python/paddle/fluid/contrib/slim/quantization/imperative/qat.py:40
(ImperativeQuantAware.quantize walks the model and swaps quantizable layers
for fake-quant wrappers; save_quantized_model exports for inference).
"""
from .. import nn
from .quant_layers import QUANT_LAYER_MAP

__all__ = ['ImperativeQuantAware']


class ImperativeQuantAware:
    def __init__(self, quantizable_layer_type=('Conv2D', 'Linear'),
                 weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max',
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_preprocess_layer=None, act_preprocess_layer=None,
                 weight_quantize_layer=None, act_quantize_layer=None):
        for t in quantizable_layer_type:
            key = t if isinstance(t, str) else t.__name__
            if key not in QUANT_LAYER_MAP:
                raise ValueError('unsupported quantizable layer type %r '
                                 '(supported: %s)'
                                 % (t, sorted(QUANT_LAYER_MAP)))
        if weight_quantize_type not in ('abs_max', 'channel_wise_abs_max'):
            raise ValueError('weight_quantize_type must be abs_max or '
                             'channel_wise_abs_max')
        if activation_quantize_type not in ('abs_max',
                                            'moving_average_abs_max'):
            raise ValueError('activation_quantize_type must be abs_max or '
                             'moving_average_abs_max')
        self._types = tuple(t if isinstance(t, str) else t.__name__
                            for t in quantizable_layer_type)
        self._wq_type = weight_quantize_type
        self._aq_type = activation_quantize_type
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate

    def _wrap(self, layer):
        for tname in self._types:
            cls, quanted = QUANT_LAYER_MAP[tname]
            if type(layer) is cls:
                if getattr(layer, 'skip_quant', False):
                    return layer
                return quanted(layer, weight_bits=self._wbits,
                               activation_bits=self._abits,
                               weight_quantize_type=self._wq_type,
                               activation_quantize_type=self._aq_type,
                               moving_rate=self._rate)
        return layer

    def quantize(self, model):
        """In-place: swap quantizable sublayers for fake-quant wrappers.
        Returns the model (reference returns None; returning it is a strict
        superset)."""
        if not isinstance(model, nn.Layer):
            raise TypeError('quantize expects a paddle Layer')
        for layer in model.sublayers(include_self=True):
            for name, sub in list(layer._sub_layers.items()):
                layer._sub_layers[name] = self._wrap(sub)
        return model

    def save_quantized_model(self, layer, path, input_spec=None, **config):
        from .. import jit
        layer.eval()
        jit.save(layer, path, input_spec=input_spec, **config)
