"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas/pjit.

Import as `import paddle_tpu as paddle` — the public surface mirrors
python/paddle/__init__.py of the reference (~v2.1).
"""

__version__ = '0.1.0'

# framework core
from .framework.core import Tensor, Parameter, to_tensor  # noqa: F401
from .framework.core import no_grad_guard as no_grad  # noqa: F401
from .framework.core import enable_grad_guard as enable_grad  # noqa: F401
from .framework.core import is_grad_enabled, set_grad_enabled  # noqa: F401
from .framework.dtype import set_default_dtype, get_default_dtype  # noqa: F401
from .framework.device import (set_device, get_device, device_count,  # noqa: F401
                               is_compiled_with_cuda, is_compiled_with_xpu,
                               is_compiled_with_npu, is_compiled_with_rocm,
                               get_cudnn_version, CPUPlace, CUDAPlace,
                               CUDAPinnedPlace, XPUPlace, NPUPlace)
from .framework.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .framework.random import (get_rng_state as get_cuda_rng_state,  # noqa: F401
                               set_rng_state as set_cuda_rng_state)

# dtype singletons (paddle.float32 etc.)
float16 = 'float16'
bfloat16 = 'bfloat16'
float32 = 'float32'
float64 = 'float64'
int8 = 'int8'
int16 = 'int16'
int32 = 'int32'
int64 = 'int64'
uint8 = 'uint8'
bool = 'bool'
complex64 = 'complex64'
complex128 = 'complex128'

# the wide tensor function surface
from .tensor import *  # noqa: F401,F403
from .tensor import einsum  # noqa: F401
from .tensor.logic import is_tensor  # noqa: F401

# subpackages
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import autograd  # noqa: F401
from . import metric  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import distribution  # noqa: F401
from . import regularizer  # noqa: F401
from . import distributed  # noqa: F401
from . import incubate  # noqa: F401
from . import hub  # noqa: F401
from . import onnx  # noqa: F401
from . import profiler  # noqa: F401
from . import slim  # noqa: F401
from . import utils  # noqa: F401
from . import dataset  # noqa: F401
from . import sysconfig  # noqa: F401
from . import monitor  # noqa: F401
from . import data  # noqa: F401

from .nn.layer.layers import ParamAttr  # noqa: F401
from .framework.io_save import save, load  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi import summary, flops  # noqa: F401
from .hapi import callbacks  # noqa: F401  (paddle.callbacks namespace)
from .framework import device  # noqa: F401  (paddle.device module)
# make `import paddle_tpu.callbacks` / `.device` statement forms work too
import sys as _sys
_sys.modules[__name__ + '.callbacks'] = callbacks
_sys.modules[__name__ + '.device'] = device
from .batch import batch  # noqa: F401
from .autograd import grad  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401

# paddle.disable_static / enable_static shims: we are always "dygraph" at the
# API level; static mode is jit-compilation under the hood (see jit/static).
_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static(place=None):
    _static_mode[0] = False


def in_dynamic_mode():
    return not _static_mode[0]


def get_flags(flags):
    from .framework import flags as F
    return F.get_flags(flags)


def set_flags(flags):
    from .framework import flags as F
    F.set_flags(flags)


def set_printoptions(**kwargs):
    import numpy as np
    np.set_printoptions(**{k: v for k, v in kwargs.items()
                           if k in ('precision', 'threshold', 'edgeitems',
                                    'linewidth')})


class version:
    full_version = __version__
    major, minor, patch = 0, 1, 0
    rc = 0
    istaged = True
    commit = 'tpu-native'

    @staticmethod
    def show():
        print('paddle_tpu', version.full_version)

# eager/dygraph mode facades: this framework is always-eager with jit
# compilation (SURVEY §7.1) — the reference's mode switch is a constant
VarBase = Tensor


def in_dygraph_mode():
    return True


def enable_dygraph(place=None):
    pass


def disable_dygraph():
    pass


enable_imperative = enable_dygraph
disable_imperative = disable_dygraph


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .static import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def crop_tensor(x, shape=None, offsets=None, name=None):
    from .tensor.manipulation import crop
    return crop(x, shape=shape, offsets=offsets)


def monkey_patch_variable():  # no-op: Tensor methods are always patched
    pass


def monkey_patch_math_varbase():
    pass


from .framework.dtype import DTypeStr as dtype  # noqa: F401,E402
