"""Profiler (reference: paddle/fluid/platform/profiler.h RecordEvent +
python/paddle/fluid/profiler.py).

TPU-native: jax.profiler (XPlane -> Perfetto/TensorBoard) replaces
CUPTI+timeline.py; RecordEvent maps to TraceAnnotation so op names stay
readable in traces (SURVEY.md §5.1).
"""
import contextlib
import time

import jax

from ..monitor import tracing as _tracing

__all__ = ['RecordEvent', 'profiler', 'start_profiler', 'stop_profiler',
           'Profiler', 'ProfilerTarget', 'ProfilerState',
           'export_chrome_tracing', 'load_profiler_result', 'merge_traces']


class RecordEvent:
    """RAII trace annotation (platform/profiler.h:127 parity).

    Dual-sink: the name lands in the device trace as a
    jax.profiler.TraceAnnotation AND in the host tracer as a span, so
    the same region shows up in Perfetto next to XLA ops and in the
    flight recorder / /debug/traces view."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None
        self._span = None

    def __enter__(self):
        self._span = _tracing.default_tracer().start_span(self.name)
        self._span.__enter__()
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        if self._span is not None:
            self._span.__exit__(*(exc or (None, None, None)))
            self._span = None
        return False

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__(None, None, None)


_active_dir = [None]


def start_profiler(state='All', tracer_option='Default',
                   log_dir='/tmp/paddle_tpu_profile'):
    # mark active only AFTER start_trace succeeds, so a failed start
    # (bad dir, trace already running) leaves no stale state behind and
    # the paired stop_profiler stays a no-op
    jax.profiler.start_trace(log_dir)
    _active_dir[0] = log_dir


def stop_profiler(sorted_key=None, profile_path=None):
    """Idempotent: safe to call repeatedly, or without a start."""
    if _active_dir[0] is None:
        return
    _active_dir[0] = None
    jax.profiler.stop_trace()


@contextlib.contextmanager
def profiler(state='All', sorted_key=None,
             profile_path='/tmp/paddle_tpu_profile', tracer_option='Default'):
    start_profiler(state, tracer_option, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class ProfilerTarget:
    CPU = 'cpu'
    GPU = 'gpu'
    TPU = 'tpu'


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class Profiler:
    """paddle.profiler.Profiler-style context over jax.profiler."""

    def __init__(self, targets=None, scheduler=None,
                 on_trace_ready=None, timer_only=False,
                 log_dir=None):
        import os
        # launcher/spawn seat a per-rank trace dir so a distributed run's
        # traces land rank-separated, ready for merge_traces
        self.log_dir = (log_dir
                        or os.environ.get('PADDLE_TRAINER_TRACE_DIR')
                        or '/tmp/paddle_tpu_profile')
        self.timer_only = timer_only
        self._on_trace_ready = on_trace_ready
        self._times = []
        self._t0 = None
        self._tracing = False     # a device trace is actually running

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        self._t0 = time.time()
        if not self.timer_only:
            # the handler may redirect log_dir (export_chrome_tracing),
            # so it must run BEFORE the trace starts
            if self._on_trace_ready is not None:
                self._on_trace_ready(self)
            jax.profiler.start_trace(self.log_dir)
            self._tracing = True

    def stop(self):
        # only stop a trace this profiler actually started: stop()
        # without start(), after a failed start(), or called twice must
        # not raise (and must not kill someone else's trace)
        if self._tracing:
            self._tracing = False
            jax.profiler.stop_trace()

    def step(self, num_samples=None):
        now = time.time()
        if self._t0 is not None:
            self._times.append(now - self._t0)
        self._t0 = now

    def step_info(self, unit=None):
        if not self._times:
            return ''
        avg = sum(self._times[-10:]) / len(self._times[-10:])
        return 'avg step time: %.4fs' % avg

    def summary(self, **kwargs):
        print(self.step_info())


def export_chrome_tracing(dir_name, worker_name=None):
    """Reference tools/timeline.py output parity: jax traces are XPlane
    protos consumable by TensorBoard/Perfetto; this returns an
    on_trace_ready callback that redirects the profiler's output dir.
    The Profiler invokes it at start(), before tracing begins, so the
    trace files land under `dir_name` when the profiler stops."""
    def handler(prof):
        prof.log_dir = dir_name
    return handler


def merge_traces(rank_dirs, out_path, rank_names=None):
    """Merge per-rank chrome-tracing outputs into ONE timeline with
    per-rank lanes (reference: tools/CrossStackProfiler/ — merges
    per-trainer timelines into a cluster view).

    rank_dirs: ordered per-rank trace dirs (each a jax.profiler/Profiler
    log_dir, holding *.trace.json[.gz] chrome traces). out_path: merged
    chrome-tracing JSON, loadable in Perfetto/chrome://tracing. Every
    rank's processes are remapped into a disjoint pid range and labeled
    'rank N: <process>', so lanes group by rank.
    """
    import gzip
    import json
    import os

    _PID_STRIDE = 1 << 20
    merged = []
    total = 0
    for rank, d in enumerate(rank_dirs):
        label = (rank_names[rank] if rank_names else 'rank %d' % rank)
        events = []
        for f in load_profiler_result(d):
            if f.endswith('.trace.json.gz'):
                try:
                    with gzip.open(f, 'rt') as fh:
                        data = json.load(fh)
                except (OSError, EOFError, ValueError):
                    continue  # truncated trace (run killed mid-write)
            elif f.endswith(('.trace.json', '.json')):
                with open(f) as fh:
                    try:
                        data = json.load(fh)
                    except ValueError:
                        continue
            else:
                continue
            evs = data.get('traceEvents', data) if isinstance(data, dict) \
                else data
            if isinstance(evs, list):
                events.extend(e for e in evs if isinstance(e, dict))
        pnames = {e.get('pid'): e.get('args', {}).get('name')
                  for e in events
                  if e.get('ph') == 'M' and e.get('name') == 'process_name'}
        # collision-free remap: sequential index per distinct source pid
        pid_map = {}

        def _remap(pid):
            if pid not in pid_map:
                pid_map[pid] = rank * _PID_STRIDE + len(pid_map)
            return pid_map[pid]

        seen_pids = set()
        for e in events:
            e = dict(e)
            pid = e.get('pid', 0)
            e['pid'] = _remap(pid)
            if e.get('ph') == 'M' and e.get('name') == 'process_name':
                orig = e.get('args', {}).get('name') or str(pid)
                e['args'] = {'name': '%s: %s' % (label, orig)}
            seen_pids.add((pid, e['pid']))
            merged.append(e)
        for orig_pid, new_pid in seen_pids:
            if orig_pid not in pnames:
                merged.append({'ph': 'M', 'name': 'process_name',
                               'pid': new_pid,
                               'args': {'name': '%s: pid %s'
                                        % (label, orig_pid)}})
            merged.append({'ph': 'M', 'name': 'process_sort_index',
                           'pid': new_pid, 'args': {'sort_index': rank}})
        total += len(events)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, 'w') as fh:
        json.dump({'traceEvents': merged,
                   'metadata': {'merged_ranks': len(rank_dirs),
                                'source_events': total}}, fh)
    return out_path


def load_profiler_result(path):
    """List the trace artifacts produced under `path` (xplane.pb /
    trace.json.gz per host), for tooling that post-processes traces."""
    import os
    out = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            if f.endswith(('.xplane.pb', '.trace.json.gz', '.json')):
                out.append(os.path.join(root, f))
    return sorted(out)
