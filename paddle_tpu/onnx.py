"""paddle.onnx.export parity (reference: python/paddle/onnx/export.py, thin
wrapper over paddle2onnx). TPU-native stance: the interchange format is
StableHLO (saved by jit.save); ONNX export emits the StableHLO artifact with
an .onnx-adjacent manifest so downstream tooling can convert offline."""
import os


def export(layer, path, input_spec=None, opset_version=9, **configs):
    from . import jit
    jit.save(layer, path, input_spec=input_spec)
    manifest = path + '.onnx.manifest'
    with open(manifest, 'w') as f:
        f.write('format: stablehlo\nsource: paddle_tpu.jit.save\n'
                'note: convert offline with onnx-mlir / stablehlo-to-onnx\n')
    return path
