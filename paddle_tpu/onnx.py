"""paddle.onnx.export — REAL ONNX export (reference: python/paddle/
onnx/export.py, a thin paddle2onnx wrapper).

TPU-native stance: the model's forward is traced to a jaxpr and converted
primitive-by-primitive into an ONNX graph, serialized as a hand-encoded
ONNX protobuf (no onnx/paddle2onnx dependency in this environment — the
wire format is written directly). Weights become initializers; any
subcomputation with no dynamic inputs is constant-folded at export time
(iota/masks/position ids just become constants). Primitives outside the
supported set raise NotImplementedError with guidance — never a silent
manifest.
"""
import struct

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['export']


# -- minimal protobuf writer (ONNX wire format) ------------------------------

def _varint(n):
    out = b''
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _key(field, wire):
    return _varint((field << 3) | wire)


def _f_int(field, value):
    return _key(field, 0) + _varint(int(value))


def _f_bytes(field, data):
    if isinstance(data, str):
        data = data.encode()
    return _key(field, 2) + _varint(len(data)) + bytes(data)


def _f_float(field, value):
    return _key(field, 5) + struct.pack('<f', float(value))


def _f_packed_ints(field, values):
    body = b''.join(_varint(v) for v in values)
    return _key(field, 2) + _varint(len(body)) + body


# ONNX TensorProto.DataType
_DTYPES = {
    np.dtype(np.float32): 1, np.dtype(np.uint8): 2, np.dtype(np.int8): 3,
    np.dtype(np.int16): 5, np.dtype(np.int32): 6, np.dtype(np.int64): 7,
    np.dtype(np.bool_): 9, np.dtype(np.float16): 10,
    np.dtype(np.float64): 11, np.dtype(np.uint32): 12,
    np.dtype(np.uint64): 13,
}


def _onnx_dtype(dt):
    dt = np.dtype(dt)
    if dt not in _DTYPES:
        raise NotImplementedError('ONNX export: unsupported dtype %s' % dt)
    return _DTYPES[dt]


def _tensor_proto(name, arr):
    arr = np.ascontiguousarray(arr)
    body = b''.join(_f_int(1, d) for d in arr.shape)
    body += _f_int(2, _onnx_dtype(arr.dtype))
    body += _f_bytes(8, name)
    body += _f_bytes(9, arr.tobytes())
    return body


def _value_info(name, shape, dtype):
    shape_body = b''.join(
        _f_bytes(1, _f_int(1, d)) for d in shape)   # dim { dim_value: d }
    tensor_type = _f_int(1, _onnx_dtype(dtype)) + _f_bytes(2, shape_body)
    type_proto = _f_bytes(1, tensor_type)
    return _f_bytes(1, name) + _f_bytes(2, type_proto)


def _attr(name, value):
    body = _f_bytes(1, name)
    if isinstance(value, bool):
        body += _f_int(3, int(value)) + _f_int(20, 2)
    elif isinstance(value, int):
        body += _f_int(3, value) + _f_int(20, 2)           # INT
    elif isinstance(value, float):
        body += _f_float(2, value) + _f_int(20, 1)          # FLOAT
    elif isinstance(value, str):
        body += _f_bytes(4, value) + _f_int(20, 3)          # STRING
    elif isinstance(value, (list, tuple)) and all(
            isinstance(v, int) for v in value):
        body += _f_packed_ints(8, value) + _f_int(20, 7)    # INTS
    else:
        raise NotImplementedError('attr %r=%r' % (name, value))
    return body


def _node(op_type, inputs, outputs, attrs=None, name=''):
    body = b''.join(_f_bytes(1, i) for i in inputs)
    body += b''.join(_f_bytes(2, o) for o in outputs)
    if name:
        body += _f_bytes(3, name)
    body += _f_bytes(4, op_type)
    for k, v in (attrs or {}).items():
        body += _f_bytes(5, _attr(k, v))
    return body


def _model_proto(graph_body, opset_version):
    body = _f_int(1, 8)                        # ir_version
    body += _f_bytes(2, 'paddle_tpu')          # producer_name
    body += _f_bytes(7, graph_body)            # graph
    opset = _f_bytes(1, '') + _f_int(2, opset_version)
    body += _f_bytes(8, opset)                 # opset_import
    return body


# -- jaxpr -> ONNX graph -----------------------------------------------------

class _Converter:
    def __init__(self, opset):
        self.opset = opset
        self.nodes = []            # serialized NodeProto bytes
        self.initializers = {}     # name -> np array
        self.const_vals = {}       # var name -> known numpy value
        self.names = {}            # jaxpr Var -> onnx name
        self.counter = 0

    def fresh(self, hint='t'):
        self.counter += 1
        return '%s_%d' % (hint, self.counter)

    def name_of(self, var):
        from jax.extend.core import Literal
        if isinstance(var, Literal):
            arr = np.asarray(var.val)
            nm = self.fresh('const')
            self.add_const(nm, arr)
            return nm
        if var not in self.names:
            self.names[var] = self.fresh('v')
        return self.names[var]

    def add_const(self, name, arr):
        arr = np.asarray(arr)
        if arr.dtype == np.int64 and arr.dtype not in _DTYPES:
            arr = arr.astype(np.int64)
        self.initializers[name] = arr
        self.const_vals[name] = arr

    def emit(self, op_type, inputs, outputs, attrs=None):
        self.nodes.append(_node(op_type, inputs, outputs, attrs,
                                name=self.fresh(op_type)))

    def is_known(self, names):
        return all(n in self.const_vals for n in names)

    # -- primitive handlers --

    def convert(self, jaxpr, in_names, consts=()):
        for var, nm in zip(jaxpr.invars, in_names):
            self.names[var] = nm
        for cvar, cval in zip(jaxpr.constvars, consts):
            nm = self.fresh('w')
            self.add_const(nm, np.asarray(cval))
            self.names[cvar] = nm
        for eqn in jaxpr.eqns:
            self.eqn(eqn)
        return [self.name_of(v) for v in jaxpr.outvars]

    def eqn(self, eqn):
        prim = eqn.primitive.name
        ins = [self.name_of(v) for v in eqn.invars]
        outs = [self.name_of(v) for v in eqn.outvars]

        # constant folding: every input statically known -> evaluate now
        if self.is_known(ins) and prim not in ('pjit', 'jit'):
            try:
                vals = eqn.primitive.bind(
                    *[jnp.asarray(self.const_vals[n]) for n in ins],
                    **eqn.params)
            except Exception:
                vals = None
            if vals is not None:
                vals = vals if isinstance(vals, (list, tuple)) else [vals]
                for o, v in zip(outs, vals):
                    self.add_const(o, np.asarray(v))
                return

        handler = getattr(self, '_p_' + prim, None)
        if handler is None:
            raise NotImplementedError(
                'ONNX export: primitive %r is not supported; supported '
                'primitives: %s. For full-fidelity export use '
                'paddle.jit.save (StableHLO).' % (
                    prim, sorted(m[3:] for m in dir(self)
                                 if m.startswith('_p_'))))
        handler(eqn, ins, outs)

    # elementwise
    def _binop(self, op, ins, outs):
        self.emit(op, ins, outs)

    def _p_add(self, e, i, o):
        self._binop('Add', i, o)

    def _p_sub(self, e, i, o):
        self._binop('Sub', i, o)

    def _p_mul(self, e, i, o):
        self._binop('Mul', i, o)

    def _p_div(self, e, i, o):
        self._binop('Div', i, o)

    def _p_max(self, e, i, o):
        self._binop('Max', i, o)

    def _p_min(self, e, i, o):
        self._binop('Min', i, o)

    def _p_pow(self, e, i, o):
        self._binop('Pow', i, o)

    def _p_rem(self, e, i, o):
        # lax.rem is C-style truncated remainder == ONNX Mod with fmod=1
        # (fmod=0 is invalid for floats and follows the divisor's sign)
        self.emit('Mod', i, o, {'fmod': 1})

    def _p_neg(self, e, i, o):
        self.emit('Neg', i, o)

    def _p_exp(self, e, i, o):
        self.emit('Exp', i, o)

    def _p_log(self, e, i, o):
        self.emit('Log', i, o)

    def _p_tanh(self, e, i, o):
        self.emit('Tanh', i, o)

    def _p_logistic(self, e, i, o):
        self.emit('Sigmoid', i, o)

    def _p_erf(self, e, i, o):
        self.emit('Erf', i, o)

    def _p_sqrt(self, e, i, o):
        self.emit('Sqrt', i, o)

    def _p_rsqrt(self, e, i, o):
        t = self.fresh('sqrt')
        self.emit('Sqrt', i, [t])
        one = self.fresh('one')
        self.add_const(one, np.ones((), self._np_dtype(e.outvars[0])))
        self.emit('Div', [one, t], o)

    def _p_abs(self, e, i, o):
        self.emit('Abs', i, o)

    def _p_square(self, e, i, o):
        self.emit('Mul', [i[0], i[0]], o)

    def _p_cbrt(self, e, i, o):
        third = self.fresh('third')
        self.add_const(third, np.asarray(1.0 / 3.0,
                                         self._np_dtype(e.outvars[0])))
        self.emit('Pow', [i[0], third], o)

    def _p_sign(self, e, i, o):
        self.emit('Sign', i, o)

    def _p_floor(self, e, i, o):
        self.emit('Floor', i, o)

    def _p_ceil(self, e, i, o):
        self.emit('Ceil', i, o)

    def _p_is_finite(self, e, i, o):
        inf = self.fresh('isinf')
        nan = self.fresh('isnan')
        self.emit('IsInf', i, [inf])
        self.emit('IsNaN', i, [nan])
        either = self.fresh('or')
        self.emit('Or', [inf, nan], [either])
        self.emit('Not', [either], o)

    def _p_integer_pow(self, e, i, o):
        y = e.params['y']
        p = self.fresh('pow')
        self.add_const(p, np.asarray(float(y), np.float32))
        self.emit('Pow', [i[0], p], o)

    def _np_dtype(self, var):
        return np.dtype(var.aval.dtype)

    # comparisons / selection
    def _p_eq(self, e, i, o):
        self.emit('Equal', i, o)

    def _p_ne(self, e, i, o):
        t = self.fresh('eq')
        self.emit('Equal', i, [t])
        self.emit('Not', [t], o)

    def _p_lt(self, e, i, o):
        self.emit('Less', i, o)

    def _p_le(self, e, i, o):
        self.emit('LessOrEqual', i, o)

    def _p_gt(self, e, i, o):
        self.emit('Greater', i, o)

    def _p_ge(self, e, i, o):
        self.emit('GreaterOrEqual', i, o)

    def _p_and(self, e, i, o):
        self.emit('And', i, o)

    def _p_or(self, e, i, o):
        self.emit('Or', i, o)

    def _p_not(self, e, i, o):
        self.emit('Not', i, o)

    def _p_select_n(self, e, i, o):
        if len(i) != 3:
            raise NotImplementedError('select_n with %d cases' % (len(i) - 1))
        # select_n(pred, on_false, on_true) -> Where(pred, on_true, on_false)
        self.emit('Where', [i[0], i[2], i[1]], o)

    def _p_stop_gradient(self, e, i, o):
        self.emit('Identity', i, o)

    def _p_copy(self, e, i, o):
        self.emit('Identity', i, o)

    # shape ops
    def _p_reshape(self, e, i, o):
        shp = self.fresh('shape')
        self.add_const(shp, np.asarray(e.params['new_sizes'], np.int64))
        self.emit('Reshape', [i[0], shp], o)

    def _p_squeeze(self, e, i, o):
        ax = self.fresh('axes')
        self.add_const(ax, np.asarray(e.params['dimensions'], np.int64))
        self.emit('Squeeze', [i[0], ax], o)

    def _p_expand_dims(self, e, i, o):
        ax = self.fresh('axes')
        self.add_const(ax, np.asarray(e.params['dimensions'], np.int64))
        self.emit('Unsqueeze', [i[0], ax], o)

    def _p_transpose(self, e, i, o):
        self.emit('Transpose', i, o,
                  {'perm': [int(p) for p in e.params['permutation']]})

    def _p_broadcast_in_dim(self, e, i, o):
        shape = [int(s) for s in e.params['shape']]
        bdims = [int(d) for d in e.params['broadcast_dimensions']]
        in_shape = e.invars[0].aval.shape
        cur = i[0]
        if len(in_shape) != len(shape):
            interm = [1] * len(shape)
            for src, dst in enumerate(bdims):
                interm[dst] = int(in_shape[src])
            shp = self.fresh('shape')
            self.add_const(shp, np.asarray(interm, np.int64))
            t = self.fresh('rshp')
            self.emit('Reshape', [cur, shp], [t])
            cur = t
        shp2 = self.fresh('shape')
        self.add_const(shp2, np.asarray(shape, np.int64))
        self.emit('Expand', [cur, shp2], o)

    def _p_concatenate(self, e, i, o):
        self.emit('Concat', i, o, {'axis': int(e.params['dimension'])})

    def _p_slice(self, e, i, o):
        starts = [int(s) for s in e.params['start_indices']]
        ends = [int(s) for s in e.params['limit_indices']]
        strides = e.params['strides']
        axes = list(range(len(starts)))
        names = []
        for hint, vals in (('starts', starts), ('ends', ends),
                           ('axes', axes),
                           ('steps', [int(s) for s in strides]
                            if strides else [1] * len(starts))):
            nm = self.fresh(hint)
            self.add_const(nm, np.asarray(vals, np.int64))
            names.append(nm)
        self.emit('Slice', [i[0]] + names, o)

    def _p_rev(self, e, i, o):
        # lax.rev via Slice with negative steps
        dims = [int(d) for d in e.params['dimensions']]
        shape = e.invars[0].aval.shape
        starts = [int(shape[d]) - 1 for d in dims]
        ends = [-(2 ** 31)] * len(dims)
        steps = [-1] * len(dims)
        names = []
        for hint, vals in (('starts', starts), ('ends', ends),
                           ('axes', dims), ('steps', steps)):
            nm = self.fresh(hint)
            self.add_const(nm, np.asarray(vals, np.int64))
            names.append(nm)
        self.emit('Slice', [i[0]] + names, o)

    def _p_pad(self, e, i, o):
        cfg = e.params['padding_config']
        if any(interior for _, _, interior in cfg):
            raise NotImplementedError('interior padding in ONNX export')
        pads = [lo for lo, _, _ in cfg] + [hi for _, hi, _ in cfg]
        pname = self.fresh('pads')
        self.add_const(pname, np.asarray(pads, np.int64))
        self.emit('Pad', [i[0], pname, i[1]], o)

    def _p_convert_element_type(self, e, i, o):
        self.emit('Cast', i, o,
                  {'to': _onnx_dtype(e.params['new_dtype'])})

    # reductions
    def _reduce(self, op, e, i, o, extra=None):
        axes = self.fresh('axes')
        self.add_const(axes, np.asarray(e.params['axes'], np.int64))
        attrs = {'keepdims': 0}
        attrs.update(extra or {})
        if self.opset >= 18 or op == 'ReduceSum':
            self.emit(op, [i[0], axes], o, attrs)
        else:
            attrs['axes'] = [int(a) for a in e.params['axes']]
            self.emit(op, [i[0]], o, attrs)

    def _p_reduce_sum(self, e, i, o):
        self._reduce('ReduceSum', e, i, o)

    def _p_reduce_max(self, e, i, o):
        self._reduce('ReduceMax', e, i, o)

    def _p_reduce_min(self, e, i, o):
        self._reduce('ReduceMin', e, i, o)

    def _p_reduce_prod(self, e, i, o):
        self._reduce('ReduceProd', e, i, o)

    def _p_reduce_and(self, e, i, o):
        c = self.fresh('cast')
        self.emit('Cast', [i[0]], [c], {'to': 6})
        r = self.fresh('red')
        self._reduce('ReduceMin', e, [c], [r])
        self.emit('Cast', [r], o, {'to': 9})

    def _p_argmax(self, e, i, o):
        axes = e.params['axes']
        # ONNX ArgMax is spec-fixed to int64; cast to the jaxpr's
        # index_dtype so the graph output type matches its value_info
        raw = self.fresh('argmax')
        self.emit('ArgMax', [i[0]], [raw],
                  {'axis': int(axes[0]), 'keepdims': 0})
        self.emit('Cast', [raw], o,
                  {'to': _onnx_dtype(e.outvars[0].aval.dtype)})

    # linear algebra
    def _p_dot_general(self, e, i, o):
        ((lc, rc), (lb, rb)) = e.params['dimension_numbers']
        lhs, rhs = e.invars[0].aval, e.invars[1].aval
        ln, rn = lhs.ndim, rhs.ndim
        lc, rc, lb, rb = list(lc), list(rc), list(lb), list(rb)
        # canonical MatMul: batch dims leading & aligned, contract
        # lhs[-1] with rhs[-2] (or rhs[0] when rhs is 2-D)
        if (len(lc) == 1 and len(rc) == 1
                and lb == list(range(len(lb)))
                and rb == list(range(len(rb)))
                and lc[0] == ln - 1
                and rc[0] == (rn - 2 if rn >= 2 else 0)):
            self.emit('MatMul', i, o)
            return
        if (len(lc) == 1 and len(rc) == 1 and not lb and not rb
                and ln == 2 and rn == 2):
            # transpose whichever side contracts on the wrong axis
            a, b = i
            if lc[0] == 0:
                t = self.fresh('tr')
                self.emit('Transpose', [a], [t], {'perm': [1, 0]})
                a = t
            if rc[0] == 1:
                t = self.fresh('tr')
                self.emit('Transpose', [b], [t], {'perm': [1, 0]})
                b = t
            self.emit('MatMul', [a, b], o)
            return
        nb = len(lb)
        if (len(lc) == 1 and len(rc) == 1
                and lb == list(range(nb)) and rb == list(range(nb))
                and ln == nb + 2 and rn == nb + 2
                and lc[0] >= nb and rc[0] >= nb):
            # batched attention-style contraction: move the contracting
            # dim to lhs[-1] / rhs[-2] with Transpose, then MatMul
            a, b = i
            if lc[0] != ln - 1:
                perm = list(range(nb)) + \
                    [d for d in range(nb, ln) if d != lc[0]] + [lc[0]]
                t = self.fresh('tr')
                self.emit('Transpose', [a], [t], {'perm': perm})
                a = t
            if rc[0] != rn - 2:
                free = [d for d in range(nb, rn) if d != rc[0]]
                perm = list(range(nb)) + [rc[0]] + free
                t = self.fresh('tr')
                self.emit('Transpose', [b], [t], {'perm': perm})
                b = t
            self.emit('MatMul', [a, b], o)
            return
        raise NotImplementedError(
            'ONNX export: dot_general with dimension_numbers %r'
            % (e.params['dimension_numbers'],))

    def _p_gather(self, e, i, o):
        dn = e.params['dimension_numbers']
        slice_sizes = e.params['slice_sizes']
        operand = e.invars[0].aval
        # embedding-style take along axis 0:
        if (list(dn.start_index_map) == [0]
                and list(dn.collapsed_slice_dims) == [0]
                and list(slice_sizes[1:]) == list(operand.shape[1:])):
            idx_aval = e.invars[1].aval
            idx = i[1]
            if idx_aval.shape and idx_aval.shape[-1] == 1:
                sq = self.fresh('sq')
                ax = self.fresh('axes')
                self.add_const(ax, np.asarray([-1], np.int64))
                self.emit('Squeeze', [idx, ax], [sq])
                idx = sq
            self.emit('Gather', [i[0], idx], o, {'axis': 0})
            return
        raise NotImplementedError(
            'ONNX export: general gather (only embedding-style take on '
            'axis 0 is supported)')

    def _p_iota(self, e, i, o):
        # static shape: emit as constant
        arr = np.asarray(jnp.broadcast_to(
            jnp.arange(e.params['shape'][e.params['dimension']],
                       dtype=e.params['dtype']),
            e.params['shape']))
        self.add_const(o[0], arr)

    # conv / pooling
    def _p_conv_general_dilated(self, e, i, o):
        dn = e.params['dimension_numbers']
        if (dn.lhs_spec != tuple(range(len(dn.lhs_spec)))
                or dn.out_spec != tuple(range(len(dn.out_spec)))
                or dn.rhs_spec != tuple(range(len(dn.rhs_spec)))):
            raise NotImplementedError(
                'ONNX export: conv layout %r (need NCHW/OIHW)' % (dn,))
        if any(int(d) != 1 for d in e.params.get('lhs_dilation', ())):
            raise NotImplementedError(
                'ONNX export: lhs_dilation (transposed/input-dilated conv) '
                'is not supported — use ConvTranspose layers via jit.save')
        if int(e.params.get('batch_group_count', 1)) != 1:
            raise NotImplementedError(
                'ONNX export: batch_group_count != 1 is not supported')
        pads = e.params['padding']
        attrs = {
            'strides': [int(s) for s in e.params['window_strides']],
            'dilations': [int(d) for d in e.params['rhs_dilation']],
            'pads': [int(p[0]) for p in pads] + [int(p[1]) for p in pads],
            'group': int(e.params['feature_group_count']),
        }
        self.emit('Conv', i, o, attrs)

    def _pool_guard(self, p):
        wd = p['window_dimensions']
        if wd[0] != 1 or wd[1] != 1:
            raise NotImplementedError('pooling over batch/channel dims')
        if any(int(d) != 1 for d in p.get('base_dilation', ())) or \
                any(int(d) != 1 for d in p.get('window_dilation', ())):
            raise NotImplementedError(
                'ONNX export: dilated reduce_window is not supported')
        if any(int(a) or int(b) for a, b in p['padding'][:2]):
            raise NotImplementedError(
                'ONNX export: reduce_window padding on batch/channel dims')

    def _p_reduce_window_max(self, e, i, o):
        p = e.params
        self._pool_guard(p)
        wd = p['window_dimensions']
        pads = p['padding']
        self.emit('MaxPool', [i[0]], o, {
            'kernel_shape': [int(w) for w in wd[2:]],
            'strides': [int(s) for s in p['window_strides'][2:]],
            'pads': [int(pp[0]) for pp in pads[2:]]
                    + [int(pp[1]) for pp in pads[2:]],
        })

    def _p_reduce_window_sum(self, e, i, o):
        p = e.params
        self._pool_guard(p)
        wd = p['window_dimensions']
        pads = p['padding']
        t = self.fresh('avg')
        self.emit('AveragePool', [i[0]], [t], {
            'kernel_shape': [int(w) for w in wd[2:]],
            'strides': [int(s) for s in p['window_strides'][2:]],
            'pads': [int(pp[0]) for pp in pads[2:]]
                    + [int(pp[1]) for pp in pads[2:]],
            'count_include_pad': 1,
        })
        scale = self.fresh('wsz')
        self.add_const(scale, np.asarray(
            float(np.prod([int(w) for w in wd])),
            self._np_dtype(e.outvars[0])))
        self.emit('Mul', [t, scale], o)

    # structural: inline sub-jaxprs. The outer eqn's outvars are aliased
    # to the inner result names (no Identity nodes, const-ness preserved)
    def _inline(self, e, i, o, closed):
        inner = self.convert(closed.jaxpr, i, getattr(closed, 'consts', ()))
        for src, outer_var in zip(inner, e.outvars):
            self.names[outer_var] = src

    def _p_pjit(self, e, i, o):
        self._inline(e, i, o, e.params['jaxpr'])

    _p_jit = _p_pjit  # jax 0.9 primitive name

    def _p_closed_call(self, e, i, o):
        self._inline(e, i, o, e.params['call_jaxpr'])

    def _p_custom_jvp_call(self, e, i, o):
        self._inline(e, i, o, e.params['call_jaxpr'])

    def _p_custom_vjp_call(self, e, i, o):
        cj = e.params.get('call_jaxpr') or e.params.get('fun_jaxpr')
        self._inline(e, i, o, cj)

    def _p_remat2(self, e, i, o):
        from jax.extend.core import ClosedJaxpr
        self._inline(e, i, o, ClosedJaxpr(e.params['jaxpr'], ()))

    def _p_checkpoint(self, e, i, o):
        self._p_remat2(e, i, o)


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Convert `layer`'s eval-mode forward to ONNX and write <path>.onnx.

    input_spec: list of InputSpec/Tensors describing the inputs (required —
    ONNX graphs are shape-typed). Raises NotImplementedError when the
    forward uses a primitive outside the supported conversion set.
    """
    from .framework import functional as func_mod
    from .static.input_spec import InputSpec

    if not input_spec:
        raise ValueError('paddle.onnx.export requires input_spec')
    specs = []
    for i, s in enumerate(input_spec):
        if isinstance(s, InputSpec):
            specs.append(s)
        else:
            specs.append(InputSpec.from_tensor(s))
    for i, s in enumerate(specs):
        if any(d is None or int(d) < 0 for d in s.shape):
            raise ValueError(
                'ONNX export requires fully static input shapes (the graph '
                'bakes shape constants); input_spec[%d] has %r — export one '
                'model per concrete shape' % (i, tuple(s.shape)))
    shaped = [jax.ShapeDtypeStruct(tuple(int(d) for d in s.shape),
                                   np.dtype(s.dtype)) for s in specs]

    params = func_mod.extract_params(layer)
    buffers = func_mod.extract_buffers(layer)

    def pure(*arrays):
        out, _ = func_mod.functional_call(layer, params, buffers,
                                          args=arrays, training=False)
        return out

    was_training = layer.training
    layer.eval()
    try:
        closed = jax.make_jaxpr(pure)(*shaped)
    finally:
        if was_training:
            layer.train()

    in_names = [specs[i].name or 'input_%d' % i
                for i in range(len(shaped))]
    conv = _Converter(opset_version)
    out_names = conv.convert(closed.jaxpr, in_names, closed.consts)

    # outputs that were fully constant-folded become initializer-backed
    # Identity outputs
    final_outs = []
    for idx, (nm, var) in enumerate(zip(out_names, closed.jaxpr.outvars)):
        onm = 'output_%d' % idx
        if nm in conv.const_vals and nm not in conv.initializers:
            conv.add_const(nm, conv.const_vals[nm])
        conv.emit('Identity', [nm], [onm])
        final_outs.append((onm, tuple(var.aval.shape),
                           np.dtype(var.aval.dtype)))

    graph = b''.join(_f_bytes(1, n) for n in conv.nodes)
    graph += _f_bytes(2, 'paddle_tpu_graph')
    for nm, arr in conv.initializers.items():
        graph += _f_bytes(5, _tensor_proto(nm, arr))
    for nm, s in zip(in_names, shaped):
        graph += _f_bytes(11, _value_info(nm, s.shape, s.dtype))
    for onm, shape, dtype in final_outs:
        graph += _f_bytes(12, _value_info(onm, shape, dtype))

    model = _model_proto(graph, opset_version)
    out_path = path if path.endswith('.onnx') else path + '.onnx'
    with open(out_path, 'wb') as f:
        f.write(model)
    return out_path
