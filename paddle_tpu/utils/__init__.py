"""paddle.utils parity (cpp_extension, misc helpers)."""
from . import cpp_extension  # noqa: F401

__all__ = ['cpp_extension', 'try_import', 'require_version', 'deprecated',
           'run_check', 'download', 'unique_name',
           'profiler', 'ProfilerOptions', 'get_profiler']


def try_import(module_name, err_msg=None):
    """reference utils/lazy_import.py try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or ('%s is required: %s'
                                      % (module_name, e)))


def require_version(min_version, max_version=None):
    """reference utils/install_check-style version gate over THIS
    framework's version."""
    from .. import __version__

    def key(v):
        return tuple(int(x) for x in str(v).split('.')[:3])
    if key(__version__) < key(min_version):
        raise Exception('paddle_tpu >= %s required, found %s'
                        % (min_version, __version__))
    if max_version is not None and key(__version__) > key(max_version):
        raise Exception('paddle_tpu <= %s required, found %s'
                        % (max_version, __version__))
    return True


def deprecated(update_to='', since='', reason=''):
    """reference utils/deprecated decorator."""
    import functools
    import warnings

    def decorate(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            warnings.warn(
                '%s is deprecated since %s%s%s'
                % (fn.__name__, since or 'this release',
                   ', use %s instead' % update_to if update_to else '',
                   '. %s' % reason if reason else ''),
                DeprecationWarning)
            return fn(*args, **kwargs)
        return wrapper
    return decorate


def run_check():
    """reference utils/install_check.run_check: one real forward/backward
    on the active backend."""
    import numpy as np
    from .. import to_tensor, optimizer
    from .. import nn
    lin = nn.Linear(4, 2)
    x = to_tensor(np.ones((2, 4), np.float32))
    loss = lin(x).sum()
    loss.backward()
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    opt.step()
    print('paddle_tpu is installed successfully!')
    return True


def download(url, path=None, md5sum=None):
    raise RuntimeError('this environment has no network egress — place '
                       'the file locally and pass its path '
                       '(reference utils/download.get_path_from_url)')


class unique_name:
    """reference fluid unique_name namespace (generate/guard)."""
    _counters = {}

    @staticmethod
    def generate(key):
        c = unique_name._counters.get(key, 0)
        unique_name._counters[key] = c + 1
        return '%s_%d' % (key, c)


from .. import profiler as _profiler_mod  # noqa: E402
Profiler = _profiler_mod.Profiler if hasattr(_profiler_mod, 'Profiler') \
    else None


class profiler:
    """paddle.utils.profiler shim (reference utils/profiler.py wraps the
    fluid profiler): maps onto the jax-backed paddle_tpu.profiler."""

    class ProfilerOptions:
        _DEFAULTS = {'batch_range': [10, 10], 'state': 'All',
                     'sorted_key': 'total', 'tracer_option': 'Default',
                     'profile_path': '/tmp/profile',
                     'exit_on_finished': True, 'timer_only': True}

        def __init__(self, options=None):
            self._options = dict(self._DEFAULTS)
            self._options.update(options or {})

        def __getitem__(self, name):
            if name not in self._options:
                raise ValueError('ProfilerOptions does not have an option '
                                 'named %s' % name)
            return self._options[name]

    @staticmethod
    def get_profiler(*a, **k):
        from .. import profiler as _p
        return _p

    @staticmethod
    def start_profiler(*a, **k):
        from .. import profiler as _p
        return _p.start_profiler(*a, **k)

    @staticmethod
    def stop_profiler(*a, **k):
        from .. import profiler as _p
        return _p.stop_profiler(*a, **k)


ProfilerOptions = profiler.ProfilerOptions
get_profiler = profiler.get_profiler
