"""paddle.utils parity (cpp_extension, misc helpers)."""
from . import cpp_extension  # noqa: F401

__all__ = ['cpp_extension']
