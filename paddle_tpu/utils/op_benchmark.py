"""Op micro-benchmark harness + perf regression gate (reference:
operators/benchmark/op_tester.cc config-driven op timing,
tools/test_op_benchmark.sh + check_op_benchmark_result.py CI gate).

Usage:
    python -m paddle_tpu.utils.op_benchmark --out ops.json
    python -m paddle_tpu.utils.op_benchmark --out new.json \
        --baseline ops.json --threshold 0.15   # fails on >15% regressions

Each config is (name, builder) where builder() returns (fn, args): fn is
jitted once, timed over `repeat` runs with block_until_ready — the XLA
replacement for op_tester's per-op timing loop. The default suite covers
the ops the bench model leans on (matmul/flash-attention/layernorm/CE),
so a kernel regression is localizable without rerunning the full model
bench (VERDICT r2 missing #4).
"""
import argparse
import json
import time

import numpy as np

__all__ = ['OP_CONFIGS', 'run_benchmarks', 'compare', 'main']


def _matmul(m=1024, k=1024, n=1024, dtype='bfloat16'):
    import jax.numpy as jnp
    a = jnp.asarray(np.random.RandomState(0).randn(m, k), dtype)
    b = jnp.asarray(np.random.RandomState(1).randn(k, n), dtype)
    return lambda a, b: a @ b, (a, b)


def _flash_attention(b=4, h=12, n=512, d=64, causal=True):
    import jax.numpy as jnp
    from ..ops import flash_attention as fa
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, n, d) * 0.2, jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, n, d) * 0.2, jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, n, d) * 0.2, jnp.bfloat16)
    return (lambda q, k, v: fa.flash_attention_bhnd(q, k, v, causal=causal),
            (q, k, v))


def _sdpa_ref(b=4, h=12, n=512, d=64):
    import jax.numpy as jnp
    from ..ops.flash_attention import _ref_bhnd
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, n, d) * 0.2, jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, n, d) * 0.2, jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, n, d) * 0.2, jnp.bfloat16)
    return (lambda q, k, v: _ref_bhnd(q, k, v, True, d ** -0.5), (q, k, v))


def _layernorm(b=16, n=512, h=768):
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(np.random.RandomState(0).randn(b, n, h), jnp.bfloat16)
    g = jnp.ones((h,), jnp.bfloat16)
    bb = jnp.zeros((h,), jnp.bfloat16)

    def ln(x, g, b2):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g + b2
    return ln, (x, g, bb)


def _softmax_ce(b=16, n=512, v=30528):
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(b * n, v) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, v, b * n), jnp.int32)

    def ce(logits, labels):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(lp, labels[:, None], 1))
    return ce, (logits, labels)


def _conv2d(b=32, c=64, hw=56, k=3, co=64):
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, c, hw, hw) * 0.1, jnp.bfloat16)
    w = jnp.asarray(rng.randn(co, c, k, k) * 0.1, jnp.bfloat16)

    def conv(x, w):
        return jax.lax.conv_general_dilated(x, w, (1, 1), 'SAME')
    return conv, (x, w)


def _embedding(v=30528, h=768, b=16, n=512):
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(v, h) * 0.02, jnp.bfloat16)
    ids = jnp.asarray(rng.randint(0, v, (b, n)), jnp.int32)
    return lambda t, i: t[i], (table, ids)


OP_CONFIGS = [
    ('matmul_1k_bf16', _matmul),
    ('flash_attention_b4h12n512d64', _flash_attention),
    ('sdpa_reference_b4h12n512d64', _sdpa_ref),
    ('layernorm_16x512x768', _layernorm),
    ('softmax_ce_16x512_v30k', _softmax_ce),
    ('conv2d_32x64x56', _conv2d),
    ('embedding_30k_768', _embedding),
]


def run_benchmarks(configs=None, repeat=20, warmup=3):
    import jax
    results = []
    for name, builder in (configs or OP_CONFIGS):
        try:
            fn, args = builder()
            jfn = jax.jit(fn)
            for _ in range(warmup):
                out = jfn(*args)
            jax.tree_util.tree_map(
                lambda a: a.block_until_ready()
                if hasattr(a, 'block_until_ready') else a, out)
            t0 = time.perf_counter()
            for _ in range(repeat):
                out = jfn(*args)
            jax.tree_util.tree_map(
                lambda a: a.block_until_ready()
                if hasattr(a, 'block_until_ready') else a, out)
            dt = (time.perf_counter() - t0) / repeat
            results.append({'op': name, 'mean_ms': round(dt * 1e3, 4),
                            'ok': True})
        except Exception as e:
            results.append({'op': name, 'ok': False, 'error': repr(e)[:300]})
    return results


def compare(baseline, current, threshold=0.15):
    """check_op_benchmark_result.py analog: list of regressions where
    current mean_ms exceeds baseline by more than `threshold` fraction."""
    base = {r['op']: r for r in baseline if r.get('ok')}
    regressions = []
    for r in current:
        if not r.get('ok'):
            continue
        b = base.get(r['op'])
        if b and r['mean_ms'] > b['mean_ms'] * (1.0 + threshold):
            regressions.append({
                'op': r['op'], 'baseline_ms': b['mean_ms'],
                'current_ms': r['mean_ms'],
                'regression': round(r['mean_ms'] / b['mean_ms'] - 1.0, 3)})
    return regressions


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default=None)
    ap.add_argument('--baseline', default=None)
    ap.add_argument('--threshold', type=float, default=0.15)
    ap.add_argument('--repeat', type=int, default=20)
    args = ap.parse_args(argv)

    results = run_benchmarks(repeat=args.repeat)
    print(json.dumps(results, indent=1))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(results, f)
    if args.baseline:
        with open(args.baseline) as f:
            base = json.load(f)
        regs = compare(base, results, args.threshold)
        if regs:
            print('PERF REGRESSIONS:', json.dumps(regs, indent=1))
            return 1
        print('perf gate: OK')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
