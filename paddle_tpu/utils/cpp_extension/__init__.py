"""C++ custom-op extensions: JIT compile, load, and register.

Parity: python/paddle/utils/cpp_extension/ (load(), CppExtension,
BuildExtension over setuptools) + framework/custom_operator.cc:511,867
(LoadOpMetaInfoAndRegisterOp). TPU-native twist: the host C++ kernel is
wired into jax via pure_callback (works inside jit) and an optional grad
kernel becomes the op's custom VJP — no framework rebuild, no protobuf.
"""
from .extension_utils import (CppExtension, CUDAExtension, BuildExtension,
                              get_include_dir, load, load_op_library, setup)

__all__ = ['load', 'load_op_library', 'setup', 'CppExtension',
           'CUDAExtension', 'BuildExtension', 'get_include_dir']
