// Public C++ custom-op API (header-only).
//
// TPU-native counterpart of the reference's extension surface:
//   paddle/fluid/extension/include/ext_op_meta_info.h:502 (PD_BUILD_OP ->
//   OpMetaInfoMap) and framework/custom_operator.cc:511,867 (runtime .so
//   load + registration).
//
// Design: custom kernels run on the HOST (C++), and the python side wires
// them into jit programs via jax.pure_callback with an optional grad
// kernel as the custom VJP. The .so self-describes through a C ABI the
// loader enumerates (pd_num_ops / pd_op_* / pd_run), so no python codegen
// or recompilation of the framework is needed — same contract as the
// reference's dynamic op registration, minus protobuf.
//
// Author-facing usage:
//
//   #include "pd_extension.h"
//   static int relu_fwd(const PDTensor* ins, int n_in,
//                       PDTensor* outs, int n_out) {
//     const float* x = (const float*)ins[0].data;
//     float* y = (float*)outs[0].data;
//     for (int64_t i = 0; i < pd_numel(&ins[0]); i++)
//       y[i] = x[i] > 0 ? x[i] : 0;
//     return 0;
//   }
//   PD_BUILD_OP(custom_relu, 1, 1, relu_fwd);
//   PD_BUILD_GRAD_OP(custom_relu, 2, 1, relu_bwd);  // ins: (x, dy) -> dx
//
#ifndef PD_EXTENSION_H_
#define PD_EXTENSION_H_

#include <cstdint>
#include <cstring>
#include <vector>

#define PD_MAX_DIMS 8

// dtype codes (must match python loader)
enum PDDtype : int32_t {
  PD_FLOAT32 = 0,
  PD_FLOAT64 = 1,
  PD_INT32 = 2,
  PD_INT64 = 3,
};

typedef struct PDTensor {
  void* data;
  int64_t ndim;
  int64_t shape[PD_MAX_DIMS];
  int32_t dtype;
} PDTensor;

static inline int64_t pd_numel(const PDTensor* t) {
  int64_t n = 1;
  for (int64_t i = 0; i < t->ndim; i++) n *= t->shape[i];
  return n;
}

// kernel: fill outs[i].data (buffers pre-allocated by the caller per the
// inferred shapes). Return 0 on success.
typedef int (*PDKernelFn)(const PDTensor* ins, int n_ins, PDTensor* outs,
                          int n_outs);

// optional shape inference: given input shapes, fill output shapes.
// Default (null) = every output takes input 0's shape/dtype.
typedef int (*PDInferFn)(const PDTensor* ins, int n_ins, PDTensor* outs,
                         int n_outs);

namespace pd_ext {

struct OpRec {
  const char* name;
  int n_inputs;
  int n_outputs;
  PDKernelFn fwd;
  PDInferFn infer;
  int grad_n_inputs;
  int grad_n_outputs;
  PDKernelFn bwd;
};

inline std::vector<OpRec>& registry() {
  static std::vector<OpRec> ops;
  return ops;
}

inline OpRec* find(const char* name) {
  for (auto& r : registry())
    if (!strcmp(r.name, name)) return &r;
  return nullptr;
}

struct Registrar {
  Registrar(const char* name, int n_in, int n_out, PDKernelFn fn,
            PDInferFn infer = nullptr) {
    OpRec* r = find(name);
    if (!r) {
      registry().push_back(OpRec{name, n_in, n_out, fn, infer, 0, 0,
                                 nullptr});
    } else {
      r->n_inputs = n_in;
      r->n_outputs = n_out;
      r->fwd = fn;
      r->infer = infer;
    }
  }
};

struct GradRegistrar {
  GradRegistrar(const char* name, int n_in, int n_out, PDKernelFn fn) {
    OpRec* r = find(name);
    if (!r) {
      registry().push_back(OpRec{name, 0, 0, nullptr, nullptr, n_in, n_out,
                                 fn});
      r = &registry().back();
    } else {
      r->grad_n_inputs = n_in;
      r->grad_n_outputs = n_out;
      r->bwd = fn;
    }
  }
};

}  // namespace pd_ext

#define PD_CONCAT_(a, b) a##b
#define PD_CONCAT(a, b) PD_CONCAT_(a, b)

// PD_BUILD_OP(name, n_inputs, n_outputs, kernel_fn[, infer_fn])
#define PD_BUILD_OP(op, n_in, n_out, ...)                                  \
  static ::pd_ext::Registrar PD_CONCAT(__pd_reg_, op){#op, n_in, n_out,    \
                                                      __VA_ARGS__};
#define PD_BUILD_OP_INFER(op, n_in, n_out, fn, infer)                      \
  static ::pd_ext::Registrar PD_CONCAT(__pd_reg_, op){#op, n_in, n_out,    \
                                                      fn, infer};

// grad kernel inputs are (forward inputs..., grad_outputs...) and its
// outputs are grads w.r.t. the forward inputs (reference grad-op contract)
#define PD_BUILD_GRAD_OP(op, n_in, n_out, fn)                              \
  static ::pd_ext::GradRegistrar PD_CONCAT(__pd_greg_, op){#op, n_in,      \
                                                           n_out, fn};

// ---- C ABI the python loader consumes -------------------------------------
extern "C" {

inline int pd_num_ops() { return (int)pd_ext::registry().size(); }

inline const char* pd_op_name(int i) {
  auto& ops = pd_ext::registry();
  return (i >= 0 && i < (int)ops.size()) ? ops[i].name : nullptr;
}

// meta[0]=n_inputs meta[1]=n_outputs meta[2]=has_infer
// meta[3]=grad_n_inputs meta[4]=grad_n_outputs meta[5]=has_grad
inline int pd_op_meta(int i, int64_t* meta) {
  auto& ops = pd_ext::registry();
  if (i < 0 || i >= (int)ops.size()) return -1;
  const auto& r = ops[i];
  meta[0] = r.n_inputs;
  meta[1] = r.n_outputs;
  meta[2] = r.infer != nullptr;
  meta[3] = r.grad_n_inputs;
  meta[4] = r.grad_n_outputs;
  meta[5] = r.bwd != nullptr;
  return 0;
}

inline int pd_infer_shape(int i, const PDTensor* ins, int n_ins,
                          PDTensor* outs, int n_outs) {
  auto& ops = pd_ext::registry();
  if (i < 0 || i >= (int)ops.size()) return -1;
  const auto& r = ops[i];
  if (r.infer) return r.infer(ins, n_ins, outs, n_outs);
  for (int o = 0; o < n_outs; o++) {
    outs[o].ndim = ins[0].ndim;
    memcpy(outs[o].shape, ins[0].shape, sizeof(ins[0].shape));
    outs[o].dtype = ins[0].dtype;
  }
  return 0;
}

inline int pd_run(int i, int is_grad, const PDTensor* ins, int n_ins,
                  PDTensor* outs, int n_outs) {
  auto& ops = pd_ext::registry();
  if (i < 0 || i >= (int)ops.size()) return -1;
  const auto& r = ops[i];
  PDKernelFn fn = is_grad ? r.bwd : r.fwd;
  if (!fn) return -2;
  return fn(ins, n_ins, outs, n_outs);
}

}  // extern "C"

// odr-use the inline C-ABI functions so every extension TU emits them as
// (weak, default-visibility) symbols that dlsym can find
namespace pd_ext {
__attribute__((used)) static void* const kExportKeep[] = {
    (void*)&pd_num_ops,     (void*)&pd_op_name, (void*)&pd_op_meta,
    (void*)&pd_infer_shape, (void*)&pd_run};
}  // namespace pd_ext

#endif  // PD_EXTENSION_H_
