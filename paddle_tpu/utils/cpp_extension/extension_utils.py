"""Build/load machinery for C++ custom ops (see package docstring)."""
import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

__all__ = ['load', 'load_op_library', 'setup', 'CppExtension',
           'CUDAExtension', 'BuildExtension', 'get_include_dir']

_DTYPE_CODES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}
_DTYPE_TO_CODE = {np.dtype(v): k for k, v in _DTYPE_CODES.items()}
_PD_MAX_DIMS = 8


class PDTensor(ctypes.Structure):
    _fields_ = [('data', ctypes.c_void_p),
                ('ndim', ctypes.c_int64),
                ('shape', ctypes.c_int64 * _PD_MAX_DIMS),
                ('dtype', ctypes.c_int32)]


def get_include_dir():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'include')


def _compile(sources, name, extra_cflags=None, extra_ldflags=None,
             extra_include_paths=None, build_directory=None, verbose=False):
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), 'paddle_tpu_extensions')
    os.makedirs(build_dir, exist_ok=True)
    key = hashlib.sha256()
    # hash the framework header too: an ABI change (PDTensor layout,
    # pd_op_meta contract) must invalidate cached .so artifacts
    header_files = [os.path.join(get_include_dir(), 'pd_extension.h')]
    for p in (extra_include_paths or []):
        if os.path.isdir(p):
            header_files += sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(('.h', '.hpp')))
    for s in list(sources) + header_files:
        with open(s, 'rb') as f:
            key.update(f.read())
    key.update(' '.join((extra_cflags or []) + (extra_ldflags or []))
               .encode())
    out = os.path.join(build_dir, '%s_%s.so' % (name, key.hexdigest()[:12]))
    if os.path.exists(out):
        return out
    cmd = ['g++', '-O2', '-shared', '-fPIC', '-std=c++17',
           '-I', get_include_dir()]
    for p in (extra_include_paths or []):
        cmd += ['-I', p]
    cmd += (extra_cflags or []) + ['-o', out] + list(sources) + \
        (extra_ldflags or [])
    if verbose:
        print('compiling:', ' '.join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError('extension compile failed:\n%s' % proc.stderr)
    return out


def _as_pd_tensor(arr):
    t = PDTensor()
    t.data = arr.ctypes.data if arr.size else None
    t.ndim = arr.ndim
    for i, d in enumerate(arr.shape):
        t.shape[i] = d
    t.dtype = _DTYPE_TO_CODE[arr.dtype]
    return t


class _LoadedOp:
    """One custom op: callable over Tensors/arrays, jit-safe, differentiable
    when a grad kernel was registered."""

    def __init__(self, lib, idx, name, meta):
        import jax

        self._lib = lib
        self._idx = idx
        self.name = name
        (self.n_inputs, self.n_outputs, self._has_infer,
         self.grad_n_inputs, self.grad_n_outputs, self._has_grad) = \
            [int(m) for m in meta]

        def host_call(is_grad, *arrays):
            arrays = [np.ascontiguousarray(a) for a in arrays]
            ins = (PDTensor * len(arrays))(*[_as_pd_tensor(a)
                                            for a in arrays])
            n_out = self.grad_n_outputs if is_grad else self.n_outputs
            out_metas = (PDTensor * n_out)()
            # infer shapes (forward uses pd_infer_shape; grad outputs are
            # grads of forward inputs, so they take those shapes)
            if is_grad:
                out_arrays = [np.empty(arrays[i].shape, arrays[i].dtype)
                              for i in range(n_out)]
            else:
                rc = lib.pd_infer_shape(idx, ins, len(arrays), out_metas,
                                        n_out)
                if rc != 0:
                    raise RuntimeError('pd_infer_shape(%s) failed rc=%d'
                                       % (name, rc))
                out_arrays = []
                for m in out_metas:
                    shape = tuple(m.shape[i] for i in range(m.ndim))
                    out_arrays.append(
                        np.empty(shape, _DTYPE_CODES[m.dtype]))
            outs = (PDTensor * n_out)(*[_as_pd_tensor(a)
                                        for a in out_arrays])
            rc = lib.pd_run(idx, 1 if is_grad else 0, ins, len(arrays),
                            outs, n_out)
            if rc != 0:
                raise RuntimeError('custom op %s%s failed rc=%d'
                                   % (name, ' (grad)' if is_grad else '',
                                      rc))
            return tuple(out_arrays)

        self._host_call = host_call

        single_out = self.n_outputs == 1

        def fwd_arrays(*arrays):
            # single-output ops return a bare array (run_op's backward
            # passes a leaf cotangent for one output, tuple otherwise)
            out_shapes = self._infer_shapes(arrays)
            structs = tuple(jax.ShapeDtypeStruct(s, d)
                            for s, d in out_shapes)
            out = jax.pure_callback(
                lambda *a: host_call(False, *a), structs, *arrays,
                vmap_method='sequential')
            return out[0] if single_out else out

        # ALWAYS wrap in custom_vjp: pure_callback has no JVP rule, so a
        # bare wrapper would crash at jax.vjp time (i.e. during any
        # forward with grad-requiring inputs) even if no gradient is ever
        # pulled. Without a grad kernel the error fires only on backward.
        @jax.custom_vjp
        def op_fn(*arrays):
            return fwd_arrays(*arrays)

        def vjp_fwd(*arrays):
            return fwd_arrays(*arrays), arrays

        has_grad = self._has_grad
        op_name = self.name

        def vjp_bwd(res, cts):
            if not has_grad:
                raise NotImplementedError(
                    'custom op %s has no grad kernel registered '
                    '(PD_BUILD_GRAD_OP missing)' % op_name)
            cts_t = (cts,) if single_out else tuple(cts)
            structs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                            for a in res)
            grads = jax.pure_callback(
                lambda *a: host_call(True, *a), structs,
                *(tuple(res) + cts_t), vmap_method='sequential')
            return tuple(grads)

        op_fn.defvjp(vjp_fwd, vjp_bwd)
        self._fn = op_fn

    def _infer_shapes(self, arrays):
        """Host-side shape inference over ShapeDtypeStructs/arrays."""
        metas_in = (PDTensor * len(arrays))()
        for i, a in enumerate(arrays):
            metas_in[i].data = None
            metas_in[i].ndim = len(a.shape)
            for j, d in enumerate(a.shape):
                metas_in[i].shape[j] = d
            metas_in[i].dtype = _DTYPE_TO_CODE[np.dtype(a.dtype)]
        metas_out = (PDTensor * self.n_outputs)()
        rc = self._lib.pd_infer_shape(self._idx, metas_in, len(arrays),
                                      metas_out, self.n_outputs)
        if rc != 0:
            raise RuntimeError('pd_infer_shape(%s) failed rc=%d'
                               % (self.name, rc))
        return [(tuple(m.shape[i] for i in range(m.ndim)),
                 _DTYPE_CODES[m.dtype]) for m in metas_out]

    def __call__(self, *args):
        from ...framework.core import Tensor, run_op
        tensors = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
        if len(tensors) != self.n_inputs:
            raise ValueError('%s expects %d inputs, got %d'
                             % (self.name, self.n_inputs, len(tensors)))
        return run_op('custom_' + self.name, self._fn, *tensors)


class _Module:
    """Namespace holding the ops of one loaded extension."""

    def __init__(self, name, ops):
        self.__name__ = name
        self._ops = {op.name: op for op in ops}
        for op in ops:
            setattr(self, op.name, op)

    def op_names(self):
        return sorted(self._ops)


def load_op_library(so_path, name=None):
    """dlopen an already-built extension and register its ops.

    Parity: paddle.utils.cpp_extension.load_op_library /
    framework/custom_operator.cc LoadOpMetaInfoAndRegisterOp.
    """
    lib = ctypes.CDLL(so_path)
    lib.pd_num_ops.restype = ctypes.c_int
    lib.pd_op_name.restype = ctypes.c_char_p
    lib.pd_op_name.argtypes = [ctypes.c_int]
    lib.pd_op_meta.restype = ctypes.c_int
    lib.pd_op_meta.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_int64)]
    lib.pd_infer_shape.restype = ctypes.c_int
    lib.pd_infer_shape.argtypes = [ctypes.c_int, ctypes.POINTER(PDTensor),
                                   ctypes.c_int, ctypes.POINTER(PDTensor),
                                   ctypes.c_int]
    lib.pd_run.restype = ctypes.c_int
    lib.pd_run.argtypes = [ctypes.c_int, ctypes.c_int,
                           ctypes.POINTER(PDTensor), ctypes.c_int,
                           ctypes.POINTER(PDTensor), ctypes.c_int]
    ops = []
    for i in range(lib.pd_num_ops()):
        op_name = lib.pd_op_name(i).decode()
        meta = (ctypes.c_int64 * 6)()
        lib.pd_op_meta(i, meta)
        n_in, n_out = int(meta[0]), int(meta[1])
        g_in, g_out, has_grad = int(meta[3]), int(meta[4]), bool(meta[5])
        if has_grad and (g_in != n_in + n_out or g_out != n_in):
            # the VJP supplies (fwd inputs..., cotangents...) and expects
            # one grad per fwd input — catch arity mismatches at load time
            # instead of as an OOB read inside the native kernel
            raise RuntimeError(
                'grad kernel of %s declares %d inputs/%d outputs; expected '
                '%d inputs (fwd inputs + fwd outputs) and %d outputs (one '
                'grad per fwd input)'
                % (op_name, g_in, g_out, n_in + n_out, n_in))
        ops.append(_LoadedOp(lib, i, op_name, list(meta)))
    if not ops:
        raise RuntimeError('%s exports no custom ops (PD_BUILD_OP missing?)'
                           % so_path)
    return _Module(name or os.path.basename(so_path), ops)


def load(name, sources, extra_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None,
         build_directory=None, verbose=False):
    """JIT-compile `sources` against pd_extension.h and return a module
    whose attributes are the registered ops (paddle cpp_extension.load
    parity; extra_cuda_cflags accepted and ignored — host C++ only here)."""
    so = _compile(sources, name, extra_cflags=extra_cflags,
                  extra_ldflags=extra_ldflags,
                  extra_include_paths=extra_include_paths,
                  build_directory=build_directory, verbose=verbose)
    return load_op_library(so, name=name)


# ---- setuptools-style surface ---------------------------------------------
class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


# accepted for API compatibility; compiles the same host C++ path
CUDAExtension = CppExtension


class BuildExtension:
    """Minimal build_ext stand-in: building produces the .so eagerly."""

    @staticmethod
    def with_options(**_):
        return BuildExtension


def setup(name, ext_modules=None, **kwargs):
    """Build each extension now and return the artifact paths (the
    reference's setuptools path writes an installable egg; here the build
    directory module is the product, loadable via load_op_library)."""
    outs = []
    for ext in (ext_modules or []):
        outs.append(_compile(ext.sources, name,
                             **{k: v for k, v in ext.kwargs.items()
                                if k in ('extra_cflags', 'extra_ldflags',
                                         'extra_include_paths',
                                         'build_directory', 'verbose')}))
    return outs
