"""Functionalization bridge: mutable Layers <-> pure jax functions.

This is the linchpin of the TPU design (SURVEY.md §7.4 hard-part #1): the
paddle-style API is stateful (Layers own Parameters, optimizers update
in-place, BN mutates running stats), but XLA wants pure functions over
pytrees. `functional_call` temporarily binds tracer arrays into the live
Parameter/buffer objects, runs the layer's ordinary forward, then harvests
mutated buffer values as explicit outputs — so ONE code path serves eager
and compiled execution (the reference needed two: dygraph + ProgramDesc).

`TrainStep` composes model + loss + optimizer into a single jitted
(params, opt_state, batch, rng) -> (params, opt_state, loss) function with
donated buffers — the XLA-native replacement for the reference's
executor-driven training loop, and the unit over which distributed
strategies apply shardings (distributed/strategy.py).
"""
import jax
import jax.numpy as jnp

from .core import Tensor
from . import random as rng_mod

__all__ = ['extract_params', 'extract_buffers', 'functional_call',
           'make_loss_post', 'TrainStep']


def _cast_like(tree, ref):
    """Cast each array in `tree` back to the dtype of the same-named entry
    in `ref`. Keeps stored state dtypes stable across a jitted update (the
    traced f32 lr intentionally promotes the arithmetic, but params,
    buffers, and optimizer slots must round-trip their dtypes or the
    lax.scan carry in multi_step mistypes)."""
    return {k: v.astype(ref[k].dtype)
            if hasattr(v, 'astype') and k in ref else v
            for k, v in tree.items()}


def extract_params(layer, trainable_only=False):
    """OrderedDict name -> jax array of the layer's parameters."""
    out = {}
    for name, p in layer.named_parameters():
        if trainable_only and p.stop_gradient:
            continue
        out[name] = p._data
    return out


def extract_buffers(layer):
    out = {}
    for name, b in layer.named_buffers():
        if b is not None:
            out[name] = b._data
    return out


def _bind(layer, params, buffers):
    """Swap arrays into live tensors; returns restore list."""
    saved = []
    pmap = dict(layer.named_parameters())
    bmap = dict(layer.named_buffers())
    for name, arr in params.items():
        t = pmap[name]
        saved.append((t, t._data))
        t._data = arr
    for name, arr in (buffers or {}).items():
        t = bmap.get(name)
        if t is None:
            continue
        saved.append((t, t._data))
        t._data = arr
    return saved, bmap


def functional_call(layer, params, buffers, args=(), kwargs=None,
                    training=None, post_fn=None):
    """Run layer.forward with `params`/`buffers` arrays bound in.

    Returns (outputs_as_arrays, new_buffers_dict). Safe under jit tracing:
    any buffer mutated by forward (e.g. BN running stats) comes back as a
    traced output instead of leaking a tracer into the live object.

    post_fn, when given, receives the forward's raw (Tensor) output and
    runs INSIDE the parameter binding; its result becomes the returned
    output. This is how a loss that references model parameters directly
    (e.g. a fused tied-embedding head, an L2 term over weights) sees the
    traced arrays rather than the live ones — referencing a live
    Parameter from an unbound loss would silently drop its gradient
    contribution.
    """
    kwargs = kwargs or {}
    prev_mode = layer.training
    if training is not None:
        layer.training = training
        for l in layer.sublayers(include_self=True):
            l.training = training
    saved, bmap = _bind(layer, params, buffers)
    try:
        targs = [Tensor(a, stop_gradient=False) if isinstance(
            a, (jnp.ndarray, jax.Array)) or hasattr(a, 'aval') else a
            for a in args]
        out = layer(*targs, **kwargs)
        if post_fn is not None:
            out = post_fn(out)
        new_buffers = {name: t._data for name, t in bmap.items()
                       if t is not None}

        def unwrap(o):
            if isinstance(o, Tensor):
                return o._data
            if isinstance(o, (list, tuple)):
                return type(o)(unwrap(x) for x in o)
            if isinstance(o, dict):
                return {k: unwrap(v) for k, v in o.items()}
            return o
        return unwrap(out), new_buffers
    finally:
        for t, arr in saved:
            t._data = arr
        if training is not None:
            layer.training = prev_mode
            for l in layer.sublayers(include_self=True):
                l.training = prev_mode


def make_loss_post(loss_fn, labels):
    """functional_call post_fn computing loss_fn(*outputs, *labels).

    Runs INSIDE the parameter binding (see functional_call): a loss that
    references model parameters — a fused tied-embedding head, weight
    penalties — must differentiate the traced arrays; calling it after
    the binding restores would silently drop those grad contributions.
    Shared by TrainStep and ShardMapDPStep so the unwrap/rewrap contract
    lives in one place.
    """
    def _loss_post(out):
        outs = out if isinstance(out, (list, tuple)) else (out,)
        t_outs = [Tensor(o._data if isinstance(o, Tensor) else o,
                         stop_gradient=False) for o in outs]
        t_labels = [Tensor(l) for l in labels]
        return loss_fn(*t_outs, *t_labels)
    return _loss_post


def write_back_params(layer, params):
    pmap = dict(layer.named_parameters())
    for name, arr in params.items():
        pmap[name]._data = arr


def write_back_buffers(layer, buffers):
    bmap = dict(layer.named_buffers())
    for name, arr in buffers.items():
        if name in bmap and bmap[name] is not None:
            bmap[name]._data = arr


class TrainStep:
    """Compiled training step: forward + backward + optimizer update fused
    into one XLA program.

    loss_fn(model_out..., *labels) -> scalar Tensor, built from paddle ops.
    Shardings (distributed strategies) are injected via `shard_fn`, a
    callback mapping (param_name, array) -> jax.sharding spec; see
    distributed/strategy.py.
    """

    def __init__(self, model, loss_fn, optimizer, donate=True,
                 in_shardings=None, out_shardings=None, mesh=None,
                 batch_sharding=None, grad_sync=None, k_steps=1,
                 grad_merge_avg=True, amp_dtype=None, remat=False,
                 sp_state=None, pp_state=None, init_loss_scaling=65536.0,
                 ls_growth_interval=2000, fce_sharding=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._jitted = None
        self._mesh = mesh
        # vocab-parallel fused-CE constraint (ops/fused_ce.logits_sharding),
        # entered around every trace/step by _sp_scope
        self._fce_sharding = fce_sharding
        self._in_shardings = in_shardings
        self._out_shardings = out_shardings
        self._batch_sharding = batch_sharding
        self._grad_sync = grad_sync
        self._donate = donate
        # AMP O2-style compute policy (reference fleet AMPOptimizer /
        # pure-fp16): params+float inputs cast to `amp_dtype` for fwd/bwd,
        # fp32 master params live in the optimizer update. fp16 engages
        # dynamic loss scaling (reference check_finite_and_unscale +
        # update_loss_scaling ops); bf16 has fp32's range and needs none.
        self._amp_dtype = (jnp.bfloat16 if amp_dtype in (True, 'bfloat16')
                           else jnp.float16 if amp_dtype == 'float16'
                           else amp_dtype)
        self._loss_scaling = self._amp_dtype == jnp.float16
        self._init_loss_scaling = float(init_loss_scaling)
        self._ls_growth_interval = int(ls_growth_interval)
        if self._loss_scaling and int(k_steps) > 1:
            raise NotImplementedError(
                'fp16 loss scaling is not composed with gradient merge '
                'yet; use bf16 amp (the TPU-native dtype) with '
                'gradient_merge')
        # global activation recompute (reference RecomputeOptimizer):
        # jax.checkpoint over the whole fwd — backward recomputes
        # activations instead of saving them
        self._remat = bool(remat)
        # sequence/pipeline-parallel routing states, active only inside
        # this step's trace/execution (distributed/sp.py sp_scope,
        # distributed/pipeline.py pp_scope)
        self._sp_state = sp_state
        self._pp_state = pp_state
        # gradient merge (reference GradientMergeOptimizer): accumulate
        # k_steps micro-batch grads, apply the optimizer on the k-th
        self._k_steps = int(k_steps)
        self._grad_merge_avg = grad_merge_avg
        self._param_names = list(extract_params(model).keys())
        self._trainable = {name: not p.stop_gradient
                           for name, p in model.named_parameters()}

    # -- optimizer state pytree --------------------------------------------
    def _opt_state(self):
        opt = self.optimizer
        pmap = dict(self.model.named_parameters())
        slots = {}
        for name in self._param_names:
            if self._trainable[name]:
                slots[name] = dict(opt._get_slots(pmap[name]))
        state = {'slots': slots,
                 'step': jnp.asarray(opt._step_count, jnp.int32)}
        if self._k_steps > 1:
            acc = getattr(self, '_gm_acc', None)
            # f32 accumulators for low-precision params (the reference's
            # fp16 gradient-merge accumulates in fp32): summing K same-
            # magnitude grads in bf16 loses ~log2(K) of its 8 mantissa bits
            from ..optimizer.optimizers import _is_low_precision
            state['acc'] = acc if acc is not None else {
                name: jnp.zeros(
                    pmap[name]._data.shape,
                    jnp.float32 if _is_low_precision(pmap[name]._data)
                    else pmap[name]._data.dtype)
                for name in slots}
            state['micro'] = getattr(
                self, '_gm_micro', jnp.zeros((), jnp.int32))
        if self._loss_scaling:
            state['loss_scale'] = getattr(
                self, '_ls_scale',
                jnp.asarray(self._init_loss_scaling, jnp.float32))
            state['growth'] = getattr(
                self, '_ls_growth', jnp.zeros((), jnp.int32))
        return state

    def _write_opt_state(self, state):
        opt = self.optimizer
        pmap = dict(self.model.named_parameters())
        for name, s in state['slots'].items():
            opt._slots[id(pmap[name])] = dict(s)
        # keep the step counter device-side: int(...) would block the host
        # on the step's completion, serializing the dispatch pipeline
        # (one forced round-trip per step through the TPU tunnel)
        opt._step_count = state['step']
        if self._k_steps > 1:
            self._gm_acc = state['acc']
            self._gm_micro = state['micro']
        if self._loss_scaling:
            self._ls_scale = state['loss_scale']
            self._ls_growth = state['growth']

    # -- the pure step ------------------------------------------------------
    def _build(self, sample_batch):
        model, opt, loss_fn = self.model, self.optimizer, self.loss_fn
        trainable = self._trainable
        grad_sync = self._grad_sync
        pmeta = dict(model.named_parameters())  # metadata: need_clip, lr, reg

        amp_dtype = self._amp_dtype
        loss_scaling = self._loss_scaling

        def _amp_cast(tree):
            return {k: (v.astype(amp_dtype)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in tree.items()}

        pp_state = self._pp_state
        use_1f1b = False
        if pp_state is not None and pp_state.get('schedule') == '1f1b':
            from ..distributed.pipeline_1f1b import supports_1f1b
            if supports_1f1b(model):
                use_1f1b = True
            else:
                # models without a pre/blocks/post split keep training —
                # GPipe is the schedule the generic pipeline path runs
                import warnings
                warnings.warn(
                    'pipeline schedule_mode=1F1B needs %s.pp_decompose() '
                    '(pre/blocks/post split); falling back to the GPipe '
                    'schedule' % type(model).__name__)
                self._pp_state = pp_state = dict(pp_state,
                                                 schedule='gpipe')
                if pp_state.get('n_micro_defaulted'):
                    # undo the 1F1B-only 2*pp default: GPipe's minimum
                    # n_micro is pp, and keeping 2*pp would tighten the
                    # batch divisibility constraint for no benefit
                    pp_state['n_micro'] = pp_state['n_stages']

        def pure_step(params, buffers, opt_state, batch, lr, key):
            inputs, labels = batch

            def compute_loss(train_params):
                all_params = dict(params)
                all_params.update(train_params)
                call_buffers = buffers
                call_inputs = inputs
                if amp_dtype is not None:
                    all_params = _amp_cast(all_params)
                    call_buffers = _amp_cast(buffers)
                    call_inputs = tuple(
                        a.astype(amp_dtype)
                        if jnp.issubdtype(a.dtype, jnp.floating) else a
                        for a in inputs)
                if use_1f1b:
                    # micro-level loss lives inside the pipelined region
                    # (pipeline_1f1b.py); loss_fn is forwarded into the
                    # model's pp_decompose post stage. The step key is
                    # seated around it so the schedule's dropout base key
                    # derives from the traced per-step key (and the split
                    # tracer cannot leak into the live generator)
                    from ..distributed.pipeline_1f1b import one_f_one_b_loss
                    with rng_mod.key_scope(key):
                        loss_val = one_f_one_b_loss(
                            model, all_params, call_inputs[0], labels[0],
                            self._pp_state,
                            loss_fn=loss_fn).astype(jnp.float32)
                    if loss_scaling:
                        return loss_val * opt_state['loss_scale'], \
                            ({}, loss_val)
                    return loss_val, {}
                with rng_mod.key_scope(key):
                    loss_arr, new_buf = functional_call(
                        model, all_params, call_buffers, args=call_inputs,
                        training=True,
                        post_fn=make_loss_post(loss_fn, labels))
                loss_val = loss_arr
                if amp_dtype is not None:
                    loss_val = loss_val.astype(jnp.float32)
                new_buf = _cast_like(new_buf, buffers)
                if loss_scaling:
                    # differentiate the SCALED loss so fp16 cotangents stay
                    # above the fp16 underflow floor; report the raw loss
                    return loss_val * opt_state['loss_scale'], \
                        (new_buf, loss_val)
                return loss_val, new_buf

            if self._remat:
                compute_loss = jax.checkpoint(compute_loss)
            train_params = {k: v for k, v in params.items() if trainable[k]}
            (loss, aux), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(train_params)
            if loss_scaling:
                new_buffers, loss = aux
                grads = {n: g / opt_state['loss_scale']
                         for n, g in grads.items()}
            else:
                new_buffers = aux
            if grad_sync is not None:
                grads = grad_sync(grads)

            # mirror Optimizer.step()'s full semantics in pure form:
            # grad clip -> (coupled) weight decay / regularizer ->
            # per-param lr -> update rule -> decoupled decay (AdamW)
            def apply_updates(gdict):
                if opt._grad_clip is not None:
                    names = list(gdict.keys())
                    pg = [(pmeta[n], Tensor(gdict[n])) for n in names]
                    clipped = opt._grad_clip(pg)
                    gdict = {n: (g._data if isinstance(g, Tensor) else g)
                             for n, (_, g) in zip(names, clipped)}
                coeff = opt._decay_coeff()
                decoupled = opt._apply_decoupled_decay()
                decay_fun = getattr(opt, '_apply_decay_param_fun', None)
                t = opt_state['step'] + 1
                new_slots = {}
                new_params = dict(params)
                for name, g in gdict.items():
                    old_slots = opt_state['slots'][name]
                    master = old_slots.get('master')
                    # multi_precision: the update rule runs on the f32
                    # master; the stored param is its rounded shadow
                    p = master if master is not None else params[name]
                    g = g.astype(p.dtype)
                    meta = pmeta[name]
                    if coeff and not decoupled:
                        g = g + coeff * p
                    if meta.regularizer is not None:
                        g = meta.regularizer._append(g, p)
                    plr = lr * meta.optimize_attr.get('learning_rate', 1.0)
                    if coeff and decoupled and \
                            (decay_fun is None or decay_fun(meta.name)):
                        p = p * (1.0 - plr * coeff)
                    opt._apply_param_name = meta.name
                    new_p, slots = opt._apply(p, g, old_slots, plr, t)
                    slots = _cast_like(slots, old_slots)
                    if master is not None:
                        slots['master'] = new_p.astype(jnp.float32)
                    new_params[name] = new_p.astype(params[name].dtype)
                    new_slots[name] = slots
                return new_params, new_slots, t

            K = self._k_steps
            if K == 1:
                if not loss_scaling:
                    new_params, new_slots, t = apply_updates(grads)
                    return new_params, new_buffers, \
                        {'slots': new_slots, 'step': t}, loss

                # dynamic loss scaling (reference operators/amp/
                # check_finite_and_unscale + update_loss_scaling): skip the
                # update on overflow, halve the scale; grow it after
                # `growth_interval` consecutive finite steps
                finite = jnp.asarray(True)
                for g in grads.values():
                    finite = jnp.logical_and(finite, jnp.isfinite(g).all())

                def do_apply(_):
                    return apply_updates(grads)

                def skip_apply(_):
                    return (dict(params),
                            {n: dict(opt_state['slots'][n]) for n in grads},
                            opt_state['step'])

                new_params, new_slots, t = jax.lax.cond(
                    finite, do_apply, skip_apply, None)
                scale = opt_state['loss_scale']
                growth = opt_state['growth']
                grown = growth + 1 >= self._ls_growth_interval
                new_scale = jnp.where(
                    finite,
                    jnp.where(grown, jnp.minimum(scale * 2.0, 2.0 ** 24),
                              scale),
                    jnp.maximum(scale * 0.5, 1.0))
                new_growth = jnp.where(finite & ~grown, growth + 1, 0)
                return new_params, new_buffers, \
                    {'slots': new_slots, 'step': t,
                     'loss_scale': new_scale, 'growth': new_growth}, loss

            # gradient merge: accumulate raw grads; clip/decay/update run
            # only on the k-th micro step (lax.cond keeps one XLA program)
            micro = opt_state['micro'] + 1
            new_acc = {n: opt_state['acc'][n] + grads[n].astype(
                opt_state['acc'][n].dtype) for n in grads}

            def do_apply(_):
                scale = 1.0 / K if self._grad_merge_avg else 1.0
                # no downcast here: apply_updates casts to the update
                # operand's dtype (the f32 master when one exists)
                eff = {n: a * scale for n, a in new_acc.items()}
                np_, ns_, t_ = apply_updates(eff)
                return (np_, ns_, t_,
                        {n: jnp.zeros_like(a) for n, a in new_acc.items()},
                        jnp.zeros((), jnp.int32))

            def skip(_):
                return (dict(params),
                        {n: dict(opt_state['slots'][n])
                         for n in new_acc},
                        opt_state['step'], new_acc, micro)

            new_params, new_slots, t, acc_out, micro_out = jax.lax.cond(
                micro >= K, do_apply, skip, None)
            return new_params, new_buffers, \
                {'slots': new_slots, 'step': t, 'acc': acc_out,
                 'micro': micro_out}, loss

        jit_kwargs = {}
        if self._donate:
            jit_kwargs['donate_argnums'] = (0, 2)
        if self._in_shardings is not None:
            jit_kwargs['in_shardings'] = self._in_shardings
        if self._out_shardings is not None:
            jit_kwargs['out_shardings'] = self._out_shardings
        self._pure_step = pure_step
        return jax.jit(pure_step, **jit_kwargs)

    def _lr_array(self):
        """Device-resident lr, re-uploaded only when the python value
        changes (a scheduler step) — not once per train step."""
        lr = self.optimizer.get_lr()
        cached = getattr(self, '_lr_cache', None)
        if cached is None or cached[0] != lr:
            self._lr_cache = (lr, jnp.asarray(lr, jnp.float32))
        return self._lr_cache[1]

    def _step_args(self, inputs, labels):
        """Normalize a host batch into pure_step's argument tuple."""
        if not isinstance(inputs, (list, tuple)):
            inputs = (inputs,)
        if not isinstance(labels, (list, tuple)):
            labels = (labels,)
        in_arrays = tuple(a._data if isinstance(a, Tensor) else jnp.asarray(a)
                          for a in inputs)
        lab_arrays = tuple(a._data if isinstance(a, Tensor) else jnp.asarray(a)
                           for a in labels)
        return in_arrays, lab_arrays

    def _sp_scope(self):
        import contextlib
        stack = contextlib.ExitStack()
        if self._sp_state is not None:
            from ..distributed.sp import sp_scope
            stack.enter_context(sp_scope(self._sp_state))
        if self._pp_state is not None:
            from ..distributed.pipeline import pp_scope
            stack.enter_context(pp_scope(self._pp_state))
        fce = self._fce_sharding
        if fce is not None:
            # vocab-parallel fused CE under tensor parallelism: constrain
            # the transient logits tiles (set by fleet_train_step)
            from ..ops.fused_ce import logits_sharding
            stack.enter_context(logits_sharding(fce))
        return stack

    def trace_jaxpr(self, inputs, labels):
        """str(jaxpr) of the pure step on this batch — lets tests assert a
        strategy flag actually transformed the program (the reference's
        program-transform assertions, test_fleet_*_meta_optimizer.py)."""
        in_arrays, lab_arrays = self._step_args(inputs, labels)
        with self._sp_scope():
            if self._jitted is None:
                self._jitted = self._build((in_arrays, lab_arrays))
            params = extract_params(self.model)
            buffers = extract_buffers(self.model)
            opt_state = self._opt_state()
            lr = self._lr_array()
            # make_jaxpr never executes the program: a peek at the current
            # key suffices (advancing the stream here would desync a
            # parity run that traces between steps)
            key = rng_mod.default_generator()._key
            jaxpr = jax.make_jaxpr(self._pure_step)(
                params, buffers, opt_state, (in_arrays, lab_arrays), lr, key)
        return str(jaxpr)

    def _build_multi(self):
        pure_step = self._pure_step
        jit_kwargs = {}
        if self._donate:
            jit_kwargs['donate_argnums'] = (0, 2)
        if self._out_shardings is not None:
            # same pytree as the single step: (params, buffers, opt_state,
            # loss) — the strategy's layout contract holds across the scan
            # (the loss entry's replicated spec covers the [K] losses too)
            jit_kwargs['out_shardings'] = self._out_shardings

        def multi(params, buffers, opt_state, batches, lr, keys):
            def body(carry, xs):
                p, b, o = carry
                batch, key = xs
                np_, nb, no, loss = pure_step(p, b, o, batch, lr, key)
                return (np_, nb, no), loss
            (p, b, o), losses = jax.lax.scan(
                body, (params, buffers, opt_state), (batches, keys))
            return p, b, o, losses
        return jax.jit(multi, **jit_kwargs)

    def multi_step(self, inputs, labels):
        """K training steps in ONE dispatch: `lax.scan` over the step body.

        Every input/label array carries a leading K axis. The device runs
        all K fwd+bwd+update iterations without returning to the host —
        the XLA-native analog of the reference's executor-driven
        multi-iteration `Run` (fluid Executor runs a whole program once
        per call), and the lever that amortizes per-dispatch latency on
        relayed/tunneled accelerators. Returns the K losses as a Tensor.
        """
        in_arrays, lab_arrays = self._step_args(inputs, labels)
        if self._batch_sharding is not None:
            # the per-step batch sharding shards dim 0 = batch; here dim 0
            # is the K scan axis, so prepend None to keep the batch dim
            # (now dim 1) on the dp axis
            bs = self._batch_sharding
            try:
                from jax.sharding import NamedSharding, PartitionSpec as P
                ks = NamedSharding(bs.mesh, P(None, *tuple(bs.spec)))
            except (AttributeError, TypeError):
                ks = bs
            in_arrays = tuple(jax.device_put(a, ks) for a in in_arrays)
            lab_arrays = tuple(jax.device_put(a, ks) for a in lab_arrays)
        k = in_arrays[0].shape[0]
        with self._sp_scope():
            if self._jitted is None:
                sample = (tuple(a[0] for a in in_arrays),
                          tuple(a[0] for a in lab_arrays))
                self._jitted = self._build(sample)
            if getattr(self, '_jitted_multi', None) is None:
                self._jitted_multi = self._build_multi()
            params = extract_params(self.model)
            buffers = extract_buffers(self.model)
            opt_state = self._opt_state()
            lr = self._lr_array()
            keys = jax.random.split(rng_mod.next_key(), k)
            new_params, new_buffers, new_opt_state, losses = \
                self._jitted_multi(params, buffers, opt_state,
                                   (in_arrays, lab_arrays), lr, keys)
        write_back_params(self.model, new_params)
        write_back_buffers(self.model, new_buffers)
        self._write_opt_state(new_opt_state)
        return Tensor(losses)

    def compiled_executable(self, inputs, labels):
        """Compile the step for this batch and return the jax Compiled
        object (without executing) — tests read its HLO text, input
        shardings, and memory_analysis() (peak temp bytes is the honest
        metric for 'does this transformation actually save memory';
        HLO-text tensor counts are only a proxy)."""
        in_arrays, lab_arrays = self._step_args(inputs, labels)
        if self._batch_sharding is not None:
            in_arrays = tuple(jax.device_put(a, self._batch_sharding)
                              for a in in_arrays)
            lab_arrays = tuple(jax.device_put(a, self._batch_sharding)
                               for a in lab_arrays)
        with self._sp_scope():
            if self._jitted is None:
                self._jitted = self._build((in_arrays, lab_arrays))
            params = extract_params(self.model)
            buffers = extract_buffers(self.model)
            opt_state = self._opt_state()
            lr = self._lr_array()
            key = rng_mod.default_generator()._key
            return self._jitted.lower(
                params, buffers, opt_state, (in_arrays, lab_arrays), lr,
                key).compile()

    def compiled_hlo(self, inputs, labels):
        """Optimized (post-SPMD-partitioning) HLO of the step, plus the
        compiled executable's input shardings for the params pytree.

        Returns (hlo_text, param_shardings dict). Tests assert the
        partitioner REALLY inserted the expected collectives and sharded
        the parameters at realistic dims — the TPU analog of the
        reference's program-transform assertions
        (test_fleet_*_meta_optimizer.py, SURVEY §4.2)."""
        compiled = self.compiled_executable(inputs, labels)
        hlo = compiled.as_text()
        try:
            pshard = compiled.input_shardings[0][0]
        except Exception:
            pshard = None
        return hlo, pshard

    def __call__(self, inputs, labels):
        """One step; returns the loss as a Tensor."""
        in_arrays, lab_arrays = self._step_args(inputs, labels)
        if self._batch_sharding is not None:
            in_arrays = tuple(jax.device_put(a, self._batch_sharding)
                              for a in in_arrays)
            lab_arrays = tuple(jax.device_put(a, self._batch_sharding)
                               for a in lab_arrays)
        with self._sp_scope():
            if self._jitted is None:
                self._jitted = self._build((in_arrays, lab_arrays))
            params = extract_params(self.model)
            buffers = extract_buffers(self.model)
            opt_state = self._opt_state()
            lr = self._lr_array()
            key = rng_mod.next_key()
            new_params, new_buffers, new_opt_state, loss = self._jitted(
                params, buffers, opt_state, (in_arrays, lab_arrays), lr, key)
        write_back_params(self.model, new_params)
        write_back_buffers(self.model, new_buffers)
        self._write_opt_state(new_opt_state)
        if isinstance(self.optimizer._lr, object) and hasattr(
                self.optimizer._lr, 'step') and not isinstance(
                self.optimizer._lr, (int, float)):
            pass  # LR scheduler stepping left to the user loop (paddle parity)
        return Tensor(loss)
