"""Per-op semantic version registry (VERDICT r3 missing #6; reference:
paddle/fluid/framework/op_version_registry.h REGISTER_OP_VERSION +
compatible_checkers).

The artifact format version (jit/__init__.py _FORMAT_VERSION) covers the
CONTAINER; this registry covers OP SEMANTICS: when an op's behavior
changes incompatibly (new default attr, different broadcasting, changed
output), its version is bumped here, saved artifacts embed the snapshot,
and loads check the saved versions against the running registry — an op
saved at a NEWER version than the runtime knows is refused (the artifact
relies on semantics this build predates), while an older version warns.
"""
import warnings

__all__ = ['register_op_version', 'get_op_version', 'snapshot',
           'check_compatible', 'OpVersionError']

# ops whose semantics have been revised since the first release get an
# explicit entry; everything else is implicitly version 1
_REGISTRY = {
    # r4: attention gained the blockwise (pure-XLA online-softmax) path;
    # numerics of the default path unchanged, routing attr added
    'scaled_dot_product_attention': 2,
    # r3: flash_attention strict-mode contract (fallbacks raise)
    'flash_attention': 2,
    # r2 -> r3: generate_proposals pixel_offset arithmetic fixed
    'generate_proposals': 2,
    'distribute_fpn_proposals': 2,
    'box_coder': 2,
}
_DEFAULT_VERSION = 1


class OpVersionError(RuntimeError):
    pass


def register_op_version(name, version):
    """REGISTER_OP_VERSION analog: record that `name`'s semantics are at
    `version` in this build."""
    _REGISTRY[name] = int(version)


def get_op_version(name):
    return _REGISTRY.get(name, _DEFAULT_VERSION)


def snapshot():
    """The dict an artifact embeds at save time."""
    return dict(_REGISTRY)


def check_compatible(saved, artifact=''):
    """Check a loaded artifact's op-version snapshot against the runtime.

    saved > runtime  -> OpVersionError (artifact needs newer semantics)
    saved < runtime  -> warning (runtime will apply CURRENT semantics;
                        the reference's version_cmp pass-through case)
    """
    if not saved:
        return
    for name, ver in saved.items():
        cur = get_op_version(name)
        if ver > cur:
            raise OpVersionError(
                'artifact %s uses op %r at version %d but this build '
                'implements version %d — upgrade the framework to load it'
                % (artifact or '<unnamed>', name, ver, cur))
        if ver < cur:
            warnings.warn(
                'artifact %s saved op %r at version %d; this build runs '
                'version %d semantics' % (artifact or '<unnamed>', name,
                                          ver, cur))
