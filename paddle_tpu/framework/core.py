"""Tensor facade + eager autograd tape.

This is the TPU-native replacement for the reference's dygraph stack:
  - VarBase / VariableWrapper  (paddle/fluid/imperative/layer.h:66)
  - Tracer::TraceOp            (paddle/fluid/imperative/tracer.cc:144)
  - BasicEngine backward       (paddle/fluid/imperative/basic_engine.cc:305)
  - GradientAccumulator        (paddle/fluid/imperative/gradient_accumulator.h)

Design: a `Tensor` wraps a jax.Array (or a jax tracer when inside a jit
trace). Eager ops run through `run_op`, which — when gradients are required —
obtains the op's VJP via `jax.vjp` and records a `GradNode` on the tape.
`Tensor.backward()` walks the node graph in reverse topological order,
accumulating cotangents, exactly like BasicEngine's dep-counted queue but
functional underneath: every node's backward is a pure jax function, so the
whole thing jits and fuses when wrapped (see framework/functional.py).

There is deliberately NO per-op kernel registry / ExecutionContext: XLA is the
kernel library, dispatch is jnp/lax. The "op table" the reference needs for
its registry (op name -> impl) lives in tensor/* as plain python functions.
"""
import threading
import weakref
import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod

__all__ = [
    'Tensor', 'Parameter', 'run_op', 'no_grad_guard', 'is_grad_enabled',
    'set_grad_enabled', 'to_tensor', 'as_jax', 'wrap_out',
]

# ---------------------------------------------------------------------------
# global tracer state
# ---------------------------------------------------------------------------

class _TracerState(threading.local):
    """Per-THREAD grad mode. A process-global flag races: two threads
    interleaving no_grad_guard enter/exit (serving replica drivers wrap
    every step in one) restore each other's saved value and can leave
    has_grad=False behind for the whole process. threading.local runs
    __init__ on first touch from each new thread, so every thread
    starts at the defaults below."""

    def __init__(self):
        self.has_grad = True
        self.inside_functional = False


_tracer = _TracerState()


def is_grad_enabled():
    return _tracer.has_grad


def set_grad_enabled(flag):
    _tracer.has_grad = bool(flag)


class no_grad_guard:
    """Context manager / decorator disabling the tape (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _tracer.has_grad
        _tracer.has_grad = False
        return self

    def __exit__(self, *exc):
        _tracer.has_grad = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with no_grad_guard():
                return fn(*a, **kw)
        return wrapper


class enable_grad_guard:
    def __enter__(self):
        self._prev = _tracer.has_grad
        _tracer.has_grad = True
        return self

    def __exit__(self, *exc):
        _tracer.has_grad = self._prev
        return False


# ---------------------------------------------------------------------------
# autograd tape
# ---------------------------------------------------------------------------

class GradNode:
    """One recorded op: holds the vjp closure + input edges.

    Mirrors the reference's GradOpNode (imperative/op_base.h) but the
    "grad kernel" is jax.vjp's closure instead of a registered grad op.
    """
    __slots__ = ('name', 'vjp_fn', 'inputs', 'out_avals', 'out_refs',
                 '_lazy', '__weakref__')

    def __init__(self, name, vjp_fn, inputs, out_avals, lazy=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs            # list[Tensor] (positional primals)
        self.out_avals = out_avals      # list[(shape, jnp dtype)]
        self.out_refs = []              # weakrefs to output tensors
        # (fn, arrays) when the vjp closure is materialized on demand: under
        # an outer functional trace (jit / value_and_grad) an eager jax.vjp
        # here would flatten any custom_vjp in `fn` into the outer trace —
        # pallas kernels then get JVP'd and die. Tracer inputs therefore
        # defer the vjp to backward time (which eager tape users pay only
        # if they actually call .backward() on a traced graph).
        self._lazy = lazy

    def materialize_vjp(self):
        if self.vjp_fn is None and self._lazy is not None:
            fn, arrays = self._lazy
            try:
                _, self.vjp_fn = jax.vjp(fn, *arrays)
                self._lazy = None  # don't retain primals twice
            except jax.errors.UnexpectedTracerError as e:
                raise RuntimeError(
                    'backward() through op %r whose inputs are stale '
                    'tracers: the Tensor was produced inside a jit/'
                    'TrainStep trace that has since ended. Differentiate '
                    'inside the traced function instead.' % self.name) from e
        return self.vjp_fn

    def release(self):
        self.vjp_fn = None
        self.inputs = ()
        self._lazy = None


def _topo_order(root_node):
    """Post-order DFS over GradNodes (iterative; graphs can be deep)."""
    order, visited = [], set()
    stack = [(root_node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            n = t._grad_node
            if n is not None and id(n) not in visited:
                stack.append((n, False))
    return order  # leaves first, root last


def _accumulate(a, b):
    if a is None:
        return b
    return a + b


def backward_engine(tensors, grad_tensors=None, retain_graph=False):
    """Reverse-mode sweep from `tensors` (paddle.autograd.backward parity)."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # node -> list of pending output cotangents
    pending = {}
    roots = []
    for t, g in zip(tensors, grad_tensors):
        node = t._grad_node
        if node is None:
            # leaf with stop_gradient=False: backward() just seeds .grad
            if not t.stop_gradient:
                seed = g._data if isinstance(g, Tensor) else (
                    jnp.ones(t.shape, t._data.dtype) if g is None else jnp.asarray(g))
                t._accumulate_grad(seed)
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    "tensor has shape %s" % (t.shape,))
            seed = jnp.ones(t.shape, t._data.dtype)
        else:
            seed = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        cots = pending.setdefault(id(node), [None] * len(node.out_avals))
        cots[t._node_out_idx] = _accumulate(cots[t._node_out_idx], seed)
        roots.append(node)

    if not roots:
        return

    # union topological order over all roots
    order, seen = [], set()
    for r in roots:
        for n in _topo_order(r):
            if id(n) not in seen:
                seen.add(id(n))
                order.append(n)
    node_set = seen

    for node in reversed(order):
        cots = pending.pop(id(node), None)
        if cots is None:
            continue
        if node.materialize_vjp() is None:
            raise RuntimeError(
                "trying to backward through the graph a second time (op %r): "
                "the saved intermediate results were freed. Pass "
                "retain_graph=True to the first backward call." % node.name)
        full = []
        for i, (shape, dt) in enumerate(node.out_avals):
            c = cots[i]
            full.append(jnp.zeros(shape, dt) if c is None else c)
        in_grads = node.vjp_fn(tuple(full) if len(full) > 1 else full[0])
        for t, g in zip(node.inputs, in_grads):
            if g is None or t.stop_gradient:
                continue
            producer = t._grad_node
            if producer is not None and id(producer) in node_set:
                # non-leaf: hooks transform the flowing gradient (paddle
                # register_hook semantics) before it propagates further
                if t._hooks:
                    gt = Tensor(g)
                    for h in list(t._hooks.values()):
                        out = h(gt)
                        if out is not None:
                            gt = out if isinstance(out, Tensor) else Tensor(out)
                    g = gt._data
                pc = pending.setdefault(id(producer), [None] * len(producer.out_avals))
                pc[t._node_out_idx] = _accumulate(pc[t._node_out_idx], g)
            else:
                t._accumulate_grad(g)
        if not retain_graph:
            node.release()


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------

def as_jax(x, dtype=None):
    """Unwrap Tensor / convert python scalar or ndarray to a jax value."""
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (jnp.ndarray, jax.Array)) or hasattr(x, 'aval'):
        return x
    return jnp.asarray(x, dtype=dtype_mod.to_jax_dtype(dtype) if dtype else None)


class Tensor:
    """Eager tensor: jax.Array + grad metadata.

    API parity target: paddle.Tensor (python/paddle/fluid/dygraph/
    varbase_patch_methods.py + math_op_patch.py). Methods for the wide tensor
    API are attached by paddle_tpu.tensor at import time (monkey-patch, same
    mechanism the reference uses).
    """
    __slots__ = ('_data', 'stop_gradient', '_grad', '_grad_node',
                 '_node_out_idx', 'persistable', 'name', '_hooks', '__weakref__')

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        self._data = as_jax(data, dtype)
        if dtype is not None:
            jd = dtype_mod.to_jax_dtype(dtype)
            if self._data.dtype != jd:
                self._data = self._data.astype(jd)
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._node_out_idx = 0
        self.persistable = False
        self.name = name or ''
        self._hooks = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        # DTypeStr: a str subclass so isinstance(x.dtype, paddle.dtype)
        # checks in ported reference code hold
        return dtype_mod.DTypeStr(dtype_mod.convert_dtype(self._data.dtype))

    @property
    def place(self):
        devs = getattr(self._data, 'devices', None)
        if devs is None:
            return 'traced'
        ds = devs() if callable(devs) else devs
        d = next(iter(ds))
        return "%s:%d" % (d.platform, d.id)

    @property
    def is_leaf(self):
        return self._grad_node is None

    # -- grad ---------------------------------------------------------------
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    def _accumulate_grad(self, g_array):
        if self._grad is None:
            self._grad = Tensor(g_array, stop_gradient=True)
        else:
            self._grad = Tensor(self._grad._data + g_array, stop_gradient=True)
        if self._hooks:
            for h in list(self._hooks.values()):
                out = h(self._grad)
                if out is not None:
                    self._grad = out if isinstance(out, Tensor) else Tensor(out)

    def backward(self, grad_tensor=None, retain_graph=False):
        backward_engine([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    _hook_counter = [0]

    def register_hook(self, hook):
        if self._hooks is None:
            self._hooks = {}
        Tensor._hook_counter[0] += 1
        hid = Tensor._hook_counter[0]
        self._hooks[hid] = hook

        class _Removable:
            def __init__(self, d, k):
                self._d, self._k = d, k

            def remove(self):
                self._d.pop(self._k, None)
        return _Removable(self._hooks, hid)

    # -- value access -------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def set_value(self, value):
        """In-place value swap (keeps grad metadata); optimizer update path."""
        arr = as_jax(value)
        self._data = arr.astype(self._data.dtype) if arr.dtype != self._data.dtype else arr

    def _copy_from(self, other):
        self._data = other._data if isinstance(other, Tensor) else as_jax(other)

    def clone(self):
        from ..tensor.manipulation import _identity_op
        return _identity_op(self)

    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices('cpu')[0]),
                      stop_gradient=self.stop_gradient)

    def pin_memory(self):
        return self

    def cuda(self, *a, **kw):
        return self

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ('cpu', 'tpu', 'gpu'):
                pass
            else:
                try:
                    t = t.astype(a)
                except TypeError:
                    pass
        return t

    @property
    def block(self):  # legacy static-graph attr; harmless stub
        return None

    def value(self):
        return self

    def get_tensor(self):
        return self

    def _is_initialized(self):
        return True

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_flag = ", stop_gradient=%s" % self.stop_gradient
        return "Tensor(shape=%s, dtype=%s%s,\n       %s)" % (
            self.shape, self.dtype, grad_flag, np.asarray(self._data))

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __bool__(self):
        if self.size != 1:
            raise ValueError("truth value of multi-element Tensor is ambiguous")
        return bool(self.numpy())

    def __int__(self):
        return int(self.item())

    def __float__(self):
        return float(self.item())

    def __index__(self):
        return int(self.item())

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, *a, **kw):
        return self._data.__dlpack__(*a, **kw)

    # math dunders / tensor methods are patched in by paddle_tpu.tensor


class Parameter(Tensor):
    """Trainable tensor (paddle.fluid.framework.Parameter parity)."""
    __slots__ = ('trainable', 'optimize_attr', 'regularizer', 'need_clip',
                 'is_distributed', 'placement')

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {'learning_rate': 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.persistable = True
        # distributed placement: PartitionSpec-style tuple aligned to self.shape,
        # consumed by the train-step compiler (distributed/spec.py)
        self.placement = None

    def __repr__(self):
        return "Parameter(shape=%s, dtype=%s, trainable=%s,\n       %s)" % (
            self.shape, self.dtype, self.trainable, np.asarray(self._data))


# ---------------------------------------------------------------------------
# the op runner (Tracer::TraceOp equivalent)
# ---------------------------------------------------------------------------

def wrap_out(arr, requires_grad=False):
    return Tensor(arr, stop_gradient=not requires_grad)


# set by paddle_tpu.amp at import: fn(op_name, [arrays]) -> [arrays]
_amp_cast_hook = [None]

# when set to a dict, run_op records every Parameter flowing through it —
# used by jit.to_static to discover closed-over params of plain functions
_param_recorder = [None]

# when set to a callable(fn, in_tensors, out_tensors), run_op reports every
# op it executes — static.program_guard records the build into a Program so
# Executor.run can replay fetches from fresh feeds (the reference's
# ProgramDesc+Executor contract, without the protobuf IR)
_fwd_recorder = [None]


def run_op(name, fn, *inputs, n_outputs=None):
    """Run op `fn` over Tensor `inputs`; record VJP on the tape when needed.

    fn: pure function over jax arrays (attrs closed over), returning one
    array or a tuple of arrays (ALL outputs must be differentiable-dtype if
    any input requires grad — mixed-output ops must pre-split, see module doc).
    """
    tensors = [t if isinstance(t, Tensor) else Tensor(t) for t in inputs]
    if _param_recorder[0] is not None:
        for t in tensors:
            if isinstance(t, Parameter):
                _param_recorder[0][id(t)] = t
    arrays = [t._data for t in tensors]
    if _amp_cast_hook[0] is not None:
        arrays = _amp_cast_hook[0](name, arrays)
    needs_grad = _tracer.has_grad and any(not t.stop_gradient for t in tensors)

    if not needs_grad:
        out = fn(*arrays)
        multi = isinstance(out, tuple)
        wrapped = [wrap_out(o) for o in (out if multi else (out,))]
        if _fwd_recorder[0] is not None:
            _fwd_recorder[0](fn, tensors, wrapped)
        return tuple(wrapped) if multi else wrapped[0]

    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        # inside an outer trace (functional TrainStep / jit): call fn
        # directly so any custom_vjp inside binds against the OUTER AD
        # trace (an eager jax.vjp here would flatten it — the pallas flash
        # kernel then gets JVP'd by the outer trace and fails). The tape
        # vjp is materialized lazily iff .backward() is actually called.
        out = fn(*arrays)
        vjp_fn, lazy = None, (fn, tuple(arrays))
    else:
        out, vjp_fn = jax.vjp(fn, *arrays)
        lazy = None
    multi = isinstance(out, tuple)
    outs = out if multi else (out,)
    node = GradNode(name, vjp_fn, tensors,
                    [(o.shape, o.dtype) for o in outs], lazy=lazy)
    wrapped = []
    for i, o in enumerate(outs):
        t = wrap_out(o, requires_grad=True)
        t._grad_node = node
        t._node_out_idx = i
        node.out_refs.append(weakref.ref(t))
        wrapped.append(t)
    if _fwd_recorder[0] is not None:
        _fwd_recorder[0](fn, tensors, wrapped)
    return tuple(wrapped) if multi else wrapped[0]


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity."""
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
