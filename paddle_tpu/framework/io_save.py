"""paddle.save / paddle.load (reference: python/paddle/framework/io.py).

Format: pickled nested structure with numpy leaves (reference-compatible
shape); Tensors serialize as numpy arrays and load back as Tensors.
Large-scale sharded checkpointing lives in distributed/checkpoint.py (orbax).
"""
import os
import pickle

import numpy as np

from .core import Tensor, Parameter

__all__ = ['save', 'load']

_PROTOCOL = 4


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), type(obj).__name__,
                              obj.name, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


class _TensorPayload:
    def __init__(self, array, kind, name, stop_gradient):
        self.array = array
        self.kind = kind
        self.name = name
        self.stop_gradient = stop_gradient


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        if obj.kind == 'Parameter':
            p = Parameter(obj.array, name=obj.name)
            return p
        return Tensor(obj.array, stop_gradient=obj.stop_gradient,
                      name=obj.name)
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTOCOL, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, 'wb') as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get('return_numpy', False)
    with open(path, 'rb') as f:
        obj = pickle.load(f)
    return _from_saveable(obj, return_numpy)
