"""paddle.save / paddle.load (reference: python/paddle/framework/io.py).

Format: pickled nested structure with numpy leaves (reference-compatible
shape); Tensors serialize as numpy arrays and load back as Tensors.
Large-scale sharded checkpointing lives in distributed/checkpoint.py (orbax).

Durability: every save is atomic (write-to-temp + fsync + rename, so a
writer preempted mid-save never tears the previous snapshot) and carries
a CRC32 manifest sidecar (`<path>.manifest`) that load verifies before
unpickling — a truncated or bit-flipped file surfaces as
CheckpointCorruptError instead of a confusing UnpicklingError (or, worse,
silently wrong tensors). Files without a manifest load as before (legacy
snapshots, foreign files).
"""
import json
import os
import pickle
import zlib

import numpy as np

from .core import Tensor, Parameter

__all__ = ['save', 'load', 'CheckpointCorruptError', 'manifest_path',
           'verify_checkpoint', 'write_bytes_atomic']

_PROTOCOL = 4
_MANIFEST_FORMAT = 1

# Write-path fault hooks (same shape as distributed/resilience.py's
# transport hooks): testing/chaos.py installs injectors here to crash a
# save at a named point and prove the torn states a preempted writer can
# leave behind. Points, in write order:
#   'pre_rename'   — payload in the temp file, not yet renamed into place
#   'pre_manifest' — payload renamed, manifest sidecar not yet written
_FAULT_HOOKS = []


def _fire(point, path):
    for hook in list(_FAULT_HOOKS):
        hook(point, path)


class CheckpointCorruptError(IOError):
    """The file's bytes do not match its manifest (truncated / torn /
    bit-flipped snapshot)."""


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), type(obj).__name__,
                              obj.name, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


class _TensorPayload:
    def __init__(self, array, kind, name, stop_gradient):
        self.array = array
        self.kind = kind
        self.name = name
        self.stop_gradient = stop_gradient


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        if obj.kind == 'Parameter':
            p = Parameter(obj.array, name=obj.name)
            return p
        return Tensor(obj.array, stop_gradient=obj.stop_gradient,
                      name=obj.name)
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saveable(v, return_numpy) for v in obj)
    return obj


def manifest_path(path):
    return path + '.manifest'


def _write_atomic(path, data):
    """Write bytes to a same-directory temp file, fsync, rename into
    place — a concurrent reader (or a preempted writer) never observes a
    half-written file at `path`."""
    tmp = '%s.tmp.%d' % (path, os.getpid())
    with open(tmp, 'wb') as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_bytes_atomic(path, data):
    """Public door to the atomic byte-writer for small non-pickle
    artifacts that ride next to data files (shard index sidecars,
    JSON manifests): same write-temp + fsync + rename discipline as
    save(), so readers never observe a torn sidecar."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    _write_atomic(path, data)


def save(obj, path, protocol=_PROTOCOL, **configs):
    """configs: encryption_key=... writes an AES-GCM (or HMAC-CTR
    fallback) PTCRYPT1 container (reference framework/io/crypto
    encrypted save)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    key = configs.get('encryption_key')
    payload = pickle.dumps(_to_saveable(obj), protocol=protocol)
    if key is not None:
        from . import crypto
        payload = crypto.encrypt(payload, key)
    manifest = json.dumps({'format': _MANIFEST_FORMAT,
                           'size': len(payload),
                           'crc32': zlib.crc32(payload) & 0xFFFFFFFF})
    # data first, then manifest: a crash between the two renames leaves a
    # stale (or missing) manifest whose mismatch reads as "corrupt" —
    # restore then falls back to an older snapshot, the conservative
    # outcome. The _fire points let chaos tests crash at each boundary.
    tmp = '%s.tmp.%d' % (path, os.getpid())
    with open(tmp, 'wb') as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    _fire('pre_rename', path)
    os.replace(tmp, path)
    _fire('pre_manifest', path)
    _write_atomic(manifest_path(path), manifest.encode())


def _check_manifest(path, payload):
    """Raise CheckpointCorruptError if `path` has a manifest that does
    not vouch for `payload`. Missing/unreadable manifest = legacy file,
    accepted as-is."""
    try:
        with open(manifest_path(path)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return
    if m.get('size') != len(payload) or \
            m.get('crc32') != (zlib.crc32(payload) & 0xFFFFFFFF):
        raise CheckpointCorruptError(
            '%s does not match its manifest (size %d vs %s) — truncated '
            'or torn snapshot' % (path, len(payload), m.get('size')))


def verify_checkpoint(path, require_manifest=False):
    """True iff `path` exists and its bytes match its manifest (or it has
    no manifest to check against). With require_manifest=True a missing
    manifest fails the check: for files that are always written through
    save() (CheckpointManager snapshots, supervisor shard snapshots) a
    bare data file means the writer died between rename and manifest —
    a torn state to fall back from, not a legacy file to trust."""
    try:
        with open(path, 'rb') as f:
            payload = f.read()
        if require_manifest and not os.path.exists(manifest_path(path)):
            return False
        _check_manifest(path, payload)
        return True
    except (OSError, CheckpointCorruptError):
        return False


def load(path, **configs):
    return_numpy = configs.get('return_numpy', False)
    key = configs.get('encryption_key')
    with open(path, 'rb') as f:
        payload = f.read()
    _check_manifest(path, payload)
    from . import crypto
    if payload.startswith(crypto._MAGIC):
        if key is None:
            raise ValueError(
                '%s is encrypted — pass encryption_key= to paddle.load'
                % path)
        payload = crypto.decrypt(payload, key)
    obj = pickle.loads(payload)
    return _from_saveable(obj, return_numpy)
