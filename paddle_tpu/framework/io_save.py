"""paddle.save / paddle.load (reference: python/paddle/framework/io.py).

Format: pickled nested structure with numpy leaves (reference-compatible
shape); Tensors serialize as numpy arrays and load back as Tensors.
Large-scale sharded checkpointing lives in distributed/checkpoint.py (orbax).
"""
import os
import pickle

import numpy as np

from .core import Tensor, Parameter

__all__ = ['save', 'load']

_PROTOCOL = 4


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), type(obj).__name__,
                              obj.name, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


class _TensorPayload:
    def __init__(self, array, kind, name, stop_gradient):
        self.array = array
        self.kind = kind
        self.name = name
        self.stop_gradient = stop_gradient


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        if obj.kind == 'Parameter':
            p = Parameter(obj.array, name=obj.name)
            return p
        return Tensor(obj.array, stop_gradient=obj.stop_gradient,
                      name=obj.name)
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTOCOL, **configs):
    """configs: encryption_key=... writes an AES-GCM (or HMAC-CTR
    fallback) PTCRYPT1 container (reference framework/io/crypto
    encrypted save)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    key = configs.get('encryption_key')
    payload = pickle.dumps(_to_saveable(obj), protocol=protocol)
    if key is not None:
        from . import crypto
        payload = crypto.encrypt(payload, key)
    with open(path, 'wb') as f:
        f.write(payload)


def load(path, **configs):
    return_numpy = configs.get('return_numpy', False)
    key = configs.get('encryption_key')
    with open(path, 'rb') as f:
        payload = f.read()
    from . import crypto
    if payload.startswith(crypto._MAGIC):
        if key is None:
            raise ValueError(
                '%s is encrypted — pass encryption_key= to paddle.load'
                % path)
        payload = crypto.decrypt(payload, key)
    obj = pickle.loads(payload)
    return _from_saveable(obj, return_numpy)
