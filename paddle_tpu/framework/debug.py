"""Numerical debugging utilities.

Reference: FLAGS_check_nan_inf + framework/details/nan_inf_utils_detail.cc
(per-op output scanning naming the offending var). TPU-native: the flag
maps to jax_debug_nans (framework/flags.py); check_numerics gives the
explicit per-tensor check for user code and tests.
"""
import jax.numpy as jnp

from .core import Tensor

__all__ = ['check_numerics', 'enable_check_nan_inf',
           'disable_check_nan_inf']


def check_numerics(x, name='tensor'):
    """Raise FloatingPointError if x contains NaN/Inf; returns x so it can
    be inserted inline in eager code."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    n_nan = int(jnp.isnan(arr).sum())
    n_inf = int(jnp.isinf(arr).sum())
    if n_nan or n_inf:
        raise FloatingPointError(
            '%s contains %d NaN and %d Inf values (shape %s, dtype %s)'
            % (name, n_nan, n_inf, tuple(arr.shape), arr.dtype))
    return x


def enable_check_nan_inf():
    from . import flags
    flags.set_flags({'FLAGS_check_nan_inf': True})


def disable_check_nan_inf():
    from . import flags
    flags.set_flags({'FLAGS_check_nan_inf': False})
