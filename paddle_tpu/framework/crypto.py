"""Encrypted model save/load (reference: framework/io/crypto/cipher.cc
AES cipher via cryptopp + cipher_utils key files, used by inference
loads).

TPU-native build has no cryptopp; AES-256-GCM is driven through OpenSSL's
libcrypto with ctypes (present in this image). When libcrypto is missing
the fallback is an HMAC-SHA256 counter-mode stream cipher with an HMAC
authentication tag — a standard PRF construction, dependency-free. The
container format records which scheme wrote the file.

Format: b'PTCRYPT1' | scheme(1) | nonce(12) | tag(16) | ciphertext.
"""
import ctypes
import ctypes.util
import hashlib
import hmac as hmac_mod
import os
import struct

__all__ = ['Cipher', 'CipherFactory', 'encrypt', 'decrypt',
           'encrypt_file', 'decrypt_file', 'generate_key']

_MAGIC = b'PTCRYPT1'
_SCHEME_GCM = 1
_SCHEME_HMAC_CTR = 2


def generate_key(path=None):
    """32-byte random key, hex-encoded (cipher_utils GenKey parity)."""
    key = os.urandom(32).hex()
    if path:
        with open(path, 'w') as f:
            f.write(key)
    return key


def _norm_key(key):
    if isinstance(key, str):
        try:
            b = bytes.fromhex(key)
            if len(b) in (16, 24, 32):
                key = b
            else:
                key = key.encode()
        except ValueError:
            key = key.encode()
    return hashlib.sha256(key).digest()  # always 32 bytes


# -- OpenSSL AES-256-GCM ------------------------------------------------------

_libcrypto = None


def _crypto():
    global _libcrypto
    if _libcrypto is None:
        name = ctypes.util.find_library('crypto') or 'libcrypto.so.3'
        lib = ctypes.CDLL(name)
        lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
        lib.EVP_aes_256_gcm.restype = ctypes.c_void_p
        for fn in (lib.EVP_EncryptInit_ex, lib.EVP_DecryptInit_ex,
                   lib.EVP_EncryptUpdate, lib.EVP_DecryptUpdate,
                   lib.EVP_EncryptFinal_ex, lib.EVP_DecryptFinal_ex,
                   lib.EVP_CIPHER_CTX_ctrl):
            fn.restype = ctypes.c_int
        _libcrypto = lib
    return _libcrypto


def _gcm(encrypting, key, nonce, data, tag=None):
    lib = _crypto()
    ctx = lib.EVP_CIPHER_CTX_new()
    if not ctx:
        raise RuntimeError('EVP_CIPHER_CTX_new failed')
    try:
        init = lib.EVP_EncryptInit_ex if encrypting else \
            lib.EVP_DecryptInit_ex
        upd = lib.EVP_EncryptUpdate if encrypting else \
            lib.EVP_DecryptUpdate
        fin = lib.EVP_EncryptFinal_ex if encrypting else \
            lib.EVP_DecryptFinal_ex
        if init(ctypes.c_void_p(ctx), ctypes.c_void_p(
                lib.EVP_aes_256_gcm()), None, key, nonce) != 1:
            raise RuntimeError('GCM init failed')
        out = ctypes.create_string_buffer(len(data) + 16)
        outl = ctypes.c_int(0)
        if upd(ctypes.c_void_p(ctx), out, ctypes.byref(outl), data,
               len(data)) != 1:
            raise RuntimeError('GCM update failed')
        n = outl.value
        if not encrypting:
            # set expected tag before final
            if lib.EVP_CIPHER_CTX_ctrl(ctypes.c_void_p(ctx), 0x11, 16,
                                       tag) != 1:  # EVP_CTRL_GCM_SET_TAG
                raise RuntimeError('GCM set-tag failed')
        fl = ctypes.c_int(0)
        if fin(ctypes.c_void_p(ctx), ctypes.byref(
                ctypes.create_string_buffer(16)), ctypes.byref(fl)) != 1:
            raise ValueError('decryption failed: wrong key or corrupted '
                             'data (GCM tag mismatch)')
        if encrypting:
            tag_buf = ctypes.create_string_buffer(16)
            if lib.EVP_CIPHER_CTX_ctrl(ctypes.c_void_p(ctx), 0x10, 16,
                                       tag_buf) != 1:  # EVP_CTRL_GCM_GET_TAG
                raise RuntimeError('GCM get-tag failed')
            return out.raw[:n], tag_buf.raw
        return out.raw[:n]
    finally:
        lib.EVP_CIPHER_CTX_free(ctypes.c_void_p(ctx))


def _gcm_available():
    try:
        _crypto()
        return True
    except Exception:
        return False


# -- HMAC-SHA256 CTR fallback -------------------------------------------------

def _hmac_ctr_keystream(key, nonce, n):
    out = b''
    counter = 0
    while len(out) < n:
        out += hmac_mod.new(key, nonce + struct.pack('<Q', counter),
                            hashlib.sha256).digest()
        counter += 1
    return out[:n]


def _hmac_ctr(key, nonce, data):
    ks = _hmac_ctr_keystream(key, nonce, len(data))
    return bytes(a ^ b for a, b in zip(data, ks))


def _hmac_tag(key, nonce, ct):
    return hmac_mod.new(key, b'tag' + nonce + ct, hashlib.sha256).digest()[:16]


# -- public API ---------------------------------------------------------------

def encrypt(data, key):
    """bytes -> PTCRYPT1 container."""
    k = _norm_key(key)
    nonce = os.urandom(12)
    if _gcm_available():
        ct, tag = _gcm(True, k, nonce, data)
        scheme = _SCHEME_GCM
    else:
        ct = _hmac_ctr(k, nonce, data)
        tag = _hmac_tag(k, nonce, ct)
        scheme = _SCHEME_HMAC_CTR
    return _MAGIC + bytes([scheme]) + nonce + tag + ct


def decrypt(blob, key):
    if not blob.startswith(_MAGIC):
        raise ValueError('not a paddle_tpu encrypted container')
    scheme = blob[len(_MAGIC)]
    nonce = blob[9:21]
    tag = blob[21:37]
    ct = blob[37:]
    k = _norm_key(key)
    if scheme == _SCHEME_GCM:
        return _gcm(False, k, nonce, ct, tag)
    if scheme == _SCHEME_HMAC_CTR:
        if not hmac_mod.compare_digest(tag, _hmac_tag(k, nonce, ct)):
            raise ValueError('decryption failed: wrong key or corrupted '
                             'data (HMAC mismatch)')
        return _hmac_ctr(k, nonce, ct)
    raise ValueError('unknown cipher scheme %d' % scheme)


def is_encrypted(path):
    try:
        with open(path, 'rb') as f:
            return f.read(len(_MAGIC)) == _MAGIC
    except OSError:
        return False


def encrypt_file(src, dst, key):
    with open(src, 'rb') as f:
        data = f.read()
    with open(dst, 'wb') as f:
        f.write(encrypt(data, key))


def decrypt_file(src, dst, key):
    with open(src, 'rb') as f:
        blob = f.read()
    with open(dst, 'wb') as f:
        f.write(decrypt(blob, key))


class Cipher:
    """Reference cipher.h parity surface."""

    def __init__(self, key=None):
        self._key = key

    def encrypt(self, plaintext, key=None):
        return encrypt(plaintext if isinstance(plaintext, bytes)
                       else plaintext.encode(), key or self._key)

    def decrypt(self, ciphertext, key=None):
        return decrypt(ciphertext, key or self._key)

    def encrypt_to_file(self, plaintext, key, filename):
        with open(filename, 'wb') as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key, filename):
        with open(filename, 'rb') as f:
            return self.decrypt(f.read(), key)


class CipherFactory:
    @staticmethod
    def create_cipher(config_file=None):
        return Cipher()
