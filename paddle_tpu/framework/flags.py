"""Typed runtime flag system (reference: paddle/fluid/platform/flags.cc ~60
gflags + pybind/global_value_getter_setter.cc).

One registry: env-var override (FLAGS_*) at import, paddle.set_flags at
runtime. TPU-relevant flags map onto XLA/jax config where meaningful; the
rest are accepted for API compat and readable back.
"""
import os

_REGISTRY = {}


class _Flag:
    __slots__ = ('name', 'value', 'typ', 'help', 'on_set')

    def __init__(self, name, default, typ, help='', on_set=None):
        self.name = name
        self.value = default
        self.typ = typ
        self.help = help
        self.on_set = on_set


def define_flag(name, default, help='', on_set=None):
    typ = type(default)
    f = _Flag(name, default, typ, help, on_set)
    env = os.environ.get('FLAGS_' + name)
    if env is not None:
        f.value = _parse(env, typ)
    _REGISTRY[name] = f
    return f


def _parse(s, typ):
    if typ is bool:
        return s.lower() in ('1', 'true', 'yes')
    return typ(s)


def set_flags(flags):
    for k, v in flags.items():
        name = k[6:] if k.startswith('FLAGS_') else k
        if name not in _REGISTRY:
            define_flag(name, v)
        else:
            f = _REGISTRY[name]
            f.value = _parse(v, f.typ) if isinstance(v, str) and f.typ is not str else v
            if f.on_set:
                f.on_set(f.value)


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        name = k[6:] if k.startswith('FLAGS_') else k
        if name in _REGISTRY:
            out[k] = _REGISTRY[name].value
    return out


def flag_value(name, default=None):
    f = _REGISTRY.get(name)
    return f.value if f is not None else default


def _set_debug_nans(v):
    import jax
    jax.config.update('jax_debug_nans', bool(v))


# reference flag parity (subset that means something on TPU)
define_flag('check_nan_inf', False,
            'scan op outputs for nan/inf (platform/flags.cc:44)',
            on_set=_set_debug_nans)
define_flag('fraction_of_gpu_memory_to_use', 0.92,
            'accepted for compat; XLA BFC handles TPU HBM')
define_flag('allocator_strategy', 'auto_growth', 'compat only')
define_flag('cudnn_deterministic', True, 'XLA on TPU is deterministic')
define_flag('benchmark', False, 'sync-per-op timing mode')
define_flag('paddle_num_threads', 1, 'host threads hint')
define_flag('use_pinned_memory', True, 'compat only')
define_flag('eager_delete_tensor_gb', 0.0, 'compat only (XLA manages)')
define_flag('max_inplace_grad_add', 0, 'compat only')
define_flag('cudnn_exhaustive_search', False, 'XLA autotuning is implicit')
define_flag('sort_sum_gradient', False, 'compat only')
define_flag('tpu_matmul_precision', 'default',
            'jax default_matmul_precision for MXU',
            on_set=lambda v: __import__('jax').config.update(
                'jax_default_matmul_precision', v if v != 'default' else None))
