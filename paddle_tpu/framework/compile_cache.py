"""One configuration path for jax's persistent compilation cache.

Every entry point that used to flip the four ``jax.config`` knobs by
hand (the Predictor's ``set_optim_cache_dir``, the bench ladder's
``_enable_persistent_cache``, bench_extra's serving rungs, the dryrun
driver) now goes through :func:`configure`, which is idempotent across
repeated calls and callers — two Predictors in one process, or a
Predictor plus the bench harness, configure the cache once.

The module also *counts*: one process-global ``jax.monitoring`` event
listener tallies ``/jax/compilation_cache/cache_hits`` and
``cache_misses`` globally and per-thread. The CompileWatchdog
(monitor/perf/watchdog.py) reads the per-thread counts to tell a
persistent-cache *hit* (XLA skipped; not a steady-state violation)
from a real backend compile, and exports them as the
``perf_persistent_cache_hits_total`` / ``misses_total`` families.
Bench rows surface the same tallies as ``compile_cache_hit_rate``.

Directory resolution order: explicit argument >
``PADDLE_TPU_COMPILE_CACHE_DIR`` > ``PADDLE_TPU_CACHE_DIR`` (the bench
ladder's historical knob) > ``<repo>/.jax_cache``.

Stdlib-only at import time (jax loads inside :func:`configure`), so
schema tooling can import the counters without touching a backend.
"""
import os
import threading

__all__ = ['configure', 'disable', 'enabled', 'cache_dir', 'default_dir',
           'stats', 'hit_rate', 'thread_state', 'reset_stats']

_HIT_EVENT = '/jax/compilation_cache/cache_hits'
_MISS_EVENT = '/jax/compilation_cache/cache_misses'

_lock = threading.Lock()
_dir = None                 # currently configured cache dir (None = off)
_listener = None            # installed jax.monitoring record_event hook
_hits = 0
_misses = 0
_tls = threading.local()    # per-thread hit/miss tallies for watchdogs


def default_dir():
    """The cache dir :func:`configure` uses when none is given."""
    return (os.environ.get('PADDLE_TPU_COMPILE_CACHE_DIR')
            or os.environ.get('PADDLE_TPU_CACHE_DIR')
            or os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), '.jax_cache'))


def _on_event(event, **kwargs):
    global _hits, _misses
    if event == _HIT_EVENT:
        with _lock:
            _hits += 1
        _tls.hits = getattr(_tls, 'hits', 0) + 1
        _tls.last = 'hit'
    elif event == _MISS_EVENT:
        with _lock:
            _misses += 1
        _tls.misses = getattr(_tls, 'misses', 0) + 1
        _tls.last = 'miss'


def _install_listener():
    global _listener
    if _listener is not None:
        return
    try:
        from jax._src import monitoring as _mon
        _mon.register_event_listener(_on_event)
        _listener = _on_event
    except Exception:
        _listener = None    # jaxlib without jax.monitoring: counts stay 0


def configure(path=None):
    """Enable the persistent compile cache at `path` (resolution order
    in the module docstring) and install the hit/miss listener.

    Idempotent: repeat calls with the same effective dir are no-ops; a
    different dir re-points the live config (last caller wins, which is
    what the reference's per-Predictor cache dirs did). Returns the
    effective dir, or None when jax rejects every knob (older jaxlib:
    the cache is best-effort, counters stay installed)."""
    global _dir
    path = path or default_dir()
    with _lock:
        already = _dir == path
    _install_listener()
    if already:
        return path
    import jax
    try:
        jax.config.update('jax_enable_compilation_cache', True)
        jax.config.update('jax_compilation_cache_dir', path)
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 0)
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)
    except Exception:
        return None
    _drop_cache_latch()
    with _lock:
        _dir = path
    return path


def _drop_cache_latch():
    """jax memoizes "is the cache used" at the FIRST compile of the
    process (compilation_cache._cache_checked); any compile before
    configure() would latch it off and make the config knobs dead.
    reset_cache() drops the latch (and the in-memory handle — the disk
    cache is untouched) so the next compile re-evaluates the config."""
    try:
        from jax._src import compilation_cache as _jcc
        _jcc.reset_cache()
    except Exception:
        pass


def disable():
    """Turn the persistent cache back off (tests; audits use a scoped
    disable instead — see auto_parallel.audit). Counters keep running."""
    global _dir
    with _lock:
        if _dir is None:
            return
        _dir = None
    try:
        import jax
        jax.config.update('jax_enable_compilation_cache', False)
    except Exception:
        pass
    _drop_cache_latch()


def enabled():
    """True when configure() has pointed jax at a persistent cache."""
    with _lock:
        return _dir is not None


def cache_dir():
    with _lock:
        return _dir


def stats():
    """Process-wide {'hits', 'misses'} since import (or reset_stats)."""
    with _lock:
        return {'hits': _hits, 'misses': _misses}


def hit_rate():
    """hits / (hits + misses), or None before any cache lookup — the
    bench ladder's ``compile_cache_hit_rate`` column."""
    with _lock:
        total = _hits + _misses
        return (_hits / total) if total else None


def thread_state():
    """(hits, misses, last) for the CALLING thread, where `last` is
    'hit' / 'miss' / None. jax fires the lookup event on the compiling
    thread before the backend-compile duration event completes, so a
    watchdog's duration listener sees this thread's lookup for the
    compile it is classifying already counted."""
    return (getattr(_tls, 'hits', 0), getattr(_tls, 'misses', 0),
            getattr(_tls, 'last', None))


def reset_stats():
    """Zero the global tallies (tests). Per-thread tallies are left to
    age out — watchdogs diff against their own marks, so stale thread
    counts never leak across watchdog instances."""
    global _hits, _misses
    with _lock:
        _hits = 0
        _misses = 0
