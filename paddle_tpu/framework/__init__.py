"""Framework core: Tensor, autograd tape, dtype/device/random/flags."""
from .core import (Tensor, Parameter, run_op, to_tensor, no_grad_guard,
                   is_grad_enabled, set_grad_enabled, wrap_out, as_jax)
from .dtype import (convert_dtype, to_jax_dtype, set_default_dtype,
                    get_default_dtype)
from .device import set_device, get_device, device_count
from .random import seed, get_rng_state, set_rng_state, default_generator

# legacy namespace parity: paddle.fluid.core-ish accessors
in_dygraph_mode = lambda: True


def _non_static_mode():
    return True
