"""Dtype registry: paddle-style dtype names over jax/numpy dtypes.

Reference parity: paddle/fluid/framework/framework.proto:106 (VarType) and
python/paddle/fluid/data_feeder.py convert_dtype. TPU-native notes:
 - bfloat16 is the preferred half dtype (MXU-native); float16 is supported
   but second-class.
 - 'int64'/'float64' are ACCEPTED everywhere but stored as int32/float32
   unless jax x64 mode is enabled: TPUs have no fast 64-bit path, and the
   32-bit default is what the reference effectively uses on accelerators
   too (indices cast to int32 in kernels).
"""
import numpy as np
import jax.numpy as jnp

# canonical name -> jnp dtype
_NAME2DTYPE = {
    'bool': jnp.bool_,
    'uint8': jnp.uint8,
    'int8': jnp.int8,
    'int16': jnp.int16,
    'int32': jnp.int32,
    'int64': jnp.int64,
    'float16': jnp.float16,
    'bfloat16': jnp.bfloat16,
    'float32': jnp.float32,
    'float64': jnp.float64,
    'complex64': jnp.complex64,
    'complex128': jnp.complex128,
}

_ALIASES = {
    'float': 'float32', 'double': 'float64', 'half': 'float16',
    'int': 'int32', 'long': 'int64', 'bf16': 'bfloat16', 'fp16': 'float16',
    'fp32': 'float32', 'fp64': 'float64',
}

FLOAT_DTYPES = ('float16', 'bfloat16', 'float32', 'float64')
INT_DTYPES = ('uint8', 'int8', 'int16', 'int32', 'int64')
COMPLEX_DTYPES = ('complex64', 'complex128')


def convert_dtype(dtype):
    """Normalize any dtype spec (str, np.dtype, jnp dtype) to canonical name."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _NAME2DTYPE:
            return name
        raise TypeError("unsupported dtype: %r" % (dtype,))
    # jnp dtypes / numpy dtypes / python types
    try:
        name = np.dtype(dtype).name
    except TypeError:
        name = getattr(dtype, '__name__', str(dtype))
    name = _ALIASES.get(name, name)
    if name in _NAME2DTYPE:
        return name
    raise TypeError("unsupported dtype: %r" % (dtype,))


def to_jax_dtype(dtype):
    if dtype is None:
        return None
    return _NAME2DTYPE[convert_dtype(dtype)]


def is_floating(dtype):
    return convert_dtype(dtype) in FLOAT_DTYPES


def is_integer(dtype):
    return convert_dtype(dtype) in INT_DTYPES


def is_complex(dtype):
    return convert_dtype(dtype) in COMPLEX_DTYPES


_DEFAULT_DTYPE = ['float32']


def set_default_dtype(d):
    """paddle.set_default_dtype parity."""
    name = convert_dtype(d)
    if name not in FLOAT_DTYPES:
        raise TypeError("default dtype must be floating, got %s" % name)
    _DEFAULT_DTYPE[0] = name


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


class DTypeStr(str):
    """paddle.dtype: dtypes are canonical strings that ALSO satisfy
    isinstance(x.dtype, paddle.dtype) for ported reference code."""
    __slots__ = ()
