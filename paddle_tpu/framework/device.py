"""Device management: paddle.set_device / get_device parity over jax devices.

Reference parity: paddle/fluid/platform/place.h (Place variants) and
python/paddle/device/__init__.py. TPU-native: a "place" is a jax.Device; the
default device is jax's default; 'tpu:3' selects jax.devices('tpu')[3].
"""
import jax

_STATE = {'device': None}  # None means jax default


def _backend_of(name):
    name = name.lower()
    if name in ('gpu', 'cuda'):
        return 'gpu'
    if name in ('cpu',):
        return 'cpu'
    if name in ('tpu', 'xpu', 'npu', 'xla'):
        # reference XPU/NPU places map to the accelerator backend here
        return 'tpu'
    raise ValueError("unknown device %r" % name)


def resolve_device(device):
    """Any paddle device spec -> a jax.Device: 'tpu:3'/'cpu'/'cuda',
    a Place object, or a jax.Device passthrough."""
    if isinstance(device, jax.Device):
        return device
    if isinstance(device, _Place):
        backend = 'cpu' if isinstance(device, (CPUPlace, CUDAPinnedPlace)) \
            else 'tpu'
        idx = device.device_id
    else:
        name, _, idx_s = str(device).partition(':')
        backend = _backend_of(name)
        idx = int(idx_s) if idx_s else 0
    try:
        devs = jax.devices(backend)
    except RuntimeError:
        # graceful fallback (e.g. asking for tpu on a cpu-only host)
        devs = jax.devices()
    return devs[idx]  # explicit out-of-range index raises, like set_device


def set_device(device):
    """Select the current device, e.g. 'tpu', 'cpu', 'tpu:0'."""
    dev = resolve_device(device)
    _STATE['device'] = dev
    return dev


def get_device():
    dev = _STATE['device']
    if dev is None:
        dev = jax.devices()[0]
    plat = dev.platform
    if plat == 'TPU':
        plat = 'tpu'
    return "%s:%d" % (plat, dev.id)


def current_jax_device():
    return _STATE['device']


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_tpu():
    return True


def device_count(backend=None):
    try:
        return len(jax.devices(backend) if backend else jax.devices())
    except RuntimeError:
        return 0


class _Place:
    """Place facades (reference platform/place.h variants): on TPU all
    compute places resolve to the accelerator; identities kept for API
    parity and isinstance checks."""

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __repr__(self):
        return '%s(%d)' % (type(self).__name__, self.device_id)

    def __eq__(self, other):
        return type(self) is type(other) and \
            self.device_id == other.device_id


class CPUPlace(_Place):
    pass


class CUDAPlace(_Place):
    pass


class CUDAPinnedPlace(_Place):
    pass


class XPUPlace(_Place):
    pass


class NPUPlace(_Place):
    pass


def get_cudnn_version():
    return None  # no cuDNN on TPU (reference returns None when absent)


def is_compiled_with_rocm():
    return False
