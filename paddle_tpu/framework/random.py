"""Global RNG: paddle.seed / Generator parity over jax threaded PRNG keys.

Reference parity: paddle/fluid/framework/generator.cc, pybind/generator_py.cc.
TPU-native: a Generator holds a jax PRNG key; every draw splits it. Inside a
jit trace the key must be an explicit input — `split_for_trace` hands out a
key that is deterministic per trace-site so eager and traced paths agree; the
train-step compiler threads a live key through state (see framework/functional).
"""
import contextlib

import jax
import numpy as np


class Generator:
    def __init__(self, seed=0):
        self._seed = int(seed)
        self._key_val = None   # lazy: creating a PRNGKey initializes the
        self._trace_counter = 0  # XLA backend, which must not happen at
        # import time (it would break jax.distributed.initialize in
        # multi-process children and wedge under a downed TPU relay)

    @property
    def _key(self):
        if self._key_val is None:
            self._key_val = jax.random.PRNGKey(self._seed)
        return self._key_val

    @_key.setter
    def _key(self, value):
        self._key_val = value

    def manual_seed(self, seed):
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(self._seed)
        self._trace_counter = 0
        return self

    def seed(self):  # paddle Generator.initial_seed-ish
        return self._seed

    def split(self):
        """Return a fresh key, advancing internal state (eager path)."""
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        return np.asarray(self._key)

    def set_state(self, state):
        self._key = jax.numpy.asarray(state, dtype=jax.numpy.uint32)


_DEFAULT = Generator(0)


def default_generator():
    return _DEFAULT


def seed(s):
    """paddle.seed parity: reseed the global generator."""
    _DEFAULT.manual_seed(s)
    return _DEFAULT


def get_rng_state():
    return _DEFAULT.get_state()


def set_rng_state(state):
    _DEFAULT.set_state(state)


def next_key():
    return _DEFAULT.split()


@contextlib.contextmanager
def key_scope(key):
    """Temporarily seat `key` (possibly a tracer) as the generator state.

    The schedule engines (pipeline GPipe/1F1B scan bodies, sp attention)
    use this to hand model code a key derived from (step key, microbatch
    index, stage, layer) — so dropout masks drawn inside a traced-once
    scan body differ per tick/microbatch and reproduce exactly when the
    1F1B backward recomputes a stage (reference capability:
    fleet/meta_parallel/parallel_layers/random.py RNGStatesTracker).
    """
    gen = _DEFAULT
    saved = gen._key
    gen._key = key
    try:
        yield gen
    finally:
        gen._key = saved
