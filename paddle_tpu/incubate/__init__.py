"""paddle.incubate parity: experimental features."""
from ..distributed.fleet.utils import recompute  # noqa: F401
from . import asp  # noqa: F401


def _segment(op_name, data, segment_ids):
    """Shared body of segment_{sum,mean,max,min} (reference segment_pool
    op, paddle/fluid/operators/segment_pool_op.cc). segment_ids must be
    sorted non-negative ints; the segment count is read off the ids, so
    these run eagerly (inside jit, pass concrete ids or pad)."""
    import jax
    import jax.numpy as jnp
    from ..framework.core import run_op
    from ..tensor._helpers import ensure_tensor

    d = ensure_tensor(data)
    ids = ensure_tensor(segment_ids)
    num = int(jax.device_get(ids._data.max())) + 1 if ids.shape[0] else 0

    def fn(a, i):
        if op_name == 'sum':
            return jax.ops.segment_sum(a, i, num_segments=num)
        if op_name == 'mean':
            s = jax.ops.segment_sum(a, i, num_segments=num)
            cnt = jax.ops.segment_sum(jnp.ones((a.shape[0],), a.dtype), i,
                                      num_segments=num)
            cnt = jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (a.ndim - 1))
            return s / cnt
        # empty segments: paddle emits 0, jax emits +/-inf — mask them
        cnt = jax.ops.segment_sum(jnp.ones((a.shape[0],), jnp.float32), i,
                                  num_segments=num)
        empty = (cnt == 0).reshape((-1,) + (1,) * (a.ndim - 1))
        if op_name == 'max':
            out = jax.ops.segment_max(a, i, num_segments=num)
        else:
            out = jax.ops.segment_min(a, i, num_segments=num)
        return jnp.where(empty, jnp.zeros_like(out), out)

    return run_op('segment_' + op_name, fn, d, ids)


def segment_sum(data, segment_ids, name=None):
    return _segment('sum', data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    return _segment('mean', data, segment_ids)


def segment_max(data, segment_ids, name=None):
    return _segment('max', data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment('min', data, segment_ids)


class nn:
    """incubate.nn namespace: fused layers map to the XLA-fused defaults —
    the framework's layers are already the fused implementations on TPU."""
    from ..nn.layer.transformer import (TransformerEncoderLayer as  # noqa: F401
                                        FusedTransformerEncoderLayer,
                                        MultiHeadAttention as
                                        FusedMultiHeadAttention)


class autograd:
    @staticmethod
    def vjp(func, xs, v=None):
        import jax
        from ..framework.core import Tensor
        arrays = [x._data for x in (xs if isinstance(xs, (list, tuple))
                                    else [xs])]

        def fn(*a):
            t = [Tensor(x, stop_gradient=False) for x in a]
            out = func(*t)
            return out._data if isinstance(out, Tensor) else out
        out, vjp_fn = jax.vjp(fn, *arrays)
        if v is None:
            import jax.numpy as jnp
            v_arr = jnp.ones_like(out)
        else:
            v_arr = v._data
        grads = vjp_fn(v_arr)
        return Tensor(out), [Tensor(g) for g in grads]

    @staticmethod
    def jvp(func, xs, v=None):
        import jax
        import jax.numpy as jnp
        from ..framework.core import Tensor
        arrays = [x._data for x in (xs if isinstance(xs, (list, tuple))
                                    else [xs])]

        def fn(*a):
            t = [Tensor(x, stop_gradient=False) for x in a]
            out = func(*t)
            return out._data if isinstance(out, Tensor) else out
        tangents = [v._data if v is not None else jnp.ones_like(a)
                    for a in arrays]
        out, jvp_val = jax.jvp(fn, tuple(arrays), tuple(tangents))
        return Tensor(out), Tensor(jvp_val)
from . import optimizer  # noqa: F401
from . import moe  # noqa: F401
from . import auto_checkpoint  # noqa: F401

from .optimizer import LookAhead, ModelAverage  # noqa: F401


def softmax_mask_fuse_upper_triangle(x):
    """Fused causal-masked softmax (reference incubate op
    softmax_mask_fuse_upper_triangle — a CUDA fusion; XLA fuses the jnp
    form). x: [B, H, N, N] attention scores."""
    import jax
    import jax.numpy as jnp
    from ..framework.core import run_op

    def fn(a):
        n = a.shape[-1]
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask, a, -1e30)
        return jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(a.dtype)
    return run_op('softmax_mask_fuse_upper_triangle', fn, x)
