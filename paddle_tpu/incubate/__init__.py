"""paddle.incubate parity: experimental features."""
from ..distributed.fleet.utils import recompute  # noqa: F401
from . import asp  # noqa: F401


class nn:
    """incubate.nn namespace: fused layers map to the XLA-fused defaults —
    the framework's layers are already the fused implementations on TPU."""
    from ..nn.layer.transformer import (TransformerEncoderLayer as  # noqa: F401
                                        FusedTransformerEncoderLayer,
                                        MultiHeadAttention as
                                        FusedMultiHeadAttention)


class autograd:
    @staticmethod
    def vjp(func, xs, v=None):
        import jax
        from ..framework.core import Tensor
        arrays = [x._data for x in (xs if isinstance(xs, (list, tuple))
                                    else [xs])]

        def fn(*a):
            t = [Tensor(x, stop_gradient=False) for x in a]
            out = func(*t)
            return out._data if isinstance(out, Tensor) else out
        out, vjp_fn = jax.vjp(fn, *arrays)
        if v is None:
            import jax.numpy as jnp
            v_arr = jnp.ones_like(out)
        else:
            v_arr = v._data
        grads = vjp_fn(v_arr)
        return Tensor(out), [Tensor(g) for g in grads]

    @staticmethod
    def jvp(func, xs, v=None):
        import jax
        import jax.numpy as jnp
        from ..framework.core import Tensor
        arrays = [x._data for x in (xs if isinstance(xs, (list, tuple))
                                    else [xs])]

        def fn(*a):
            t = [Tensor(x, stop_gradient=False) for x in a]
            out = func(*t)
            return out._data if isinstance(out, Tensor) else out
        tangents = [v._data if v is not None else jnp.ones_like(a)
                    for a in arrays]
        out, jvp_val = jax.jvp(fn, tuple(arrays), tuple(tangents))
        return Tensor(out), Tensor(jvp_val)
from . import optimizer  # noqa: F401
from . import moe  # noqa: F401
from . import auto_checkpoint  # noqa: F401

from .optimizer import LookAhead, ModelAverage  # noqa: F401


def softmax_mask_fuse_upper_triangle(x):
    """Fused causal-masked softmax (reference incubate op
    softmax_mask_fuse_upper_triangle — a CUDA fusion; XLA fuses the jnp
    form). x: [B, H, N, N] attention scores."""
    import jax
    import jax.numpy as jnp
    from ..framework.core import run_op

    def fn(a):
        n = a.shape[-1]
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask, a, -1e30)
        return jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(a.dtype)
    return run_op('softmax_mask_fuse_upper_triangle', fn, x)
