"""Optimizer wrappers (reference: fluid/optimizer.py — LookaheadOptimizer
:5969, ModelAverage :3573, ExponentialMovingAverage :3882; modern paddle
re-exposes them under paddle.incubate.optimizer).

Each wraps an inner optimizer/parameter list and keeps shadow state in
host-controlled jax arrays — functional updates, no in-place mutation of
live math.
"""
import jax.numpy as jnp

from ..framework.core import no_grad_guard

__all__ = ['LookAhead', 'ModelAverage', 'ExponentialMovingAverage']


class LookAhead:
    """k fast steps, then slow weights pull toward fast by alpha
    (Lookahead Optimizer; reference LookaheadOptimizer)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = {}
        self._steps = 0

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    @no_grad_guard()
    def step(self):
        params = self.inner_optimizer._parameter_list
        for p in params:
            if id(p) not in self._slow:
                self._slow[id(p)] = p._data
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p in params:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (p._data - slow)
                self._slow[id(p)] = slow
                p._data = slow

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def state_dict(self):
        return {'inner': self.inner_optimizer.state_dict(),
                'steps': self._steps}

    def set_state_dict(self, state):
        self.inner_optimizer.set_state_dict(state.get('inner', {}))
        self._steps = state.get('steps', 0)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """Running average of parameters over a window; apply()/restore()
    context swaps the averaged weights in for evaluation (reference
    ModelAverageOptimizer min/max_average_window semantics, simplified to
    a cumulative window)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000000,
                 name=None):
        self._params = list(parameters or [])
        self._sum = {id(p): jnp.zeros_like(p._data) for p in self._params}
        self._count = 0
        self._backup = None

    @no_grad_guard()
    def step(self):
        """Accumulate after the inner optimizer stepped."""
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p._data
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        if self._count == 0:
            return
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            p._data = self._sum[id(p)] / self._count
        return _RestoreCtx(self) if need_restore else None

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None


class _RestoreCtx:
    def __init__(self, ma):
        self._ma = ma

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._ma.restore()
        return False


class ExponentialMovingAverage:
    """EMA of parameters: shadow = decay*shadow + (1-decay)*param, with
    the reference's bias-corrected thres_steps-free form; apply()/
    restore() swap shadows in for eval (reference
    ExponentialMovingAverage :3882)."""

    def __init__(self, decay=0.999, thres_steps=None, parameters=None,
                 name=None):
        self._decay = float(decay)
        self._params = list(parameters or [])
        self._shadow = {id(p): p._data for p in self._params}
        self._step = 0
        self._backup = None

    @no_grad_guard()
    def update(self):
        self._step += 1
        # Adam-style bias-corrected dynamic decay (reference uses
        # min(decay, (1+t)/(10+t)) when thres_steps is set; keep static)
        d = self._decay
        for p in self._params:
            self._shadow[id(p)] = d * self._shadow[id(p)] + \
                (1.0 - d) * p._data

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            p._data = self._shadow[id(p)]
        return _RestoreCtx2(self) if need_restore else None

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None


class _RestoreCtx2(_RestoreCtx):
    def __init__(self, ema):
        self._ma = ema
