"""Expert-parallel Mixture-of-Experts (beyond-reference: SURVEY.md §2.2
notes MoE/expert parallelism is absent from the reference snapshot but in
the capability bar; later paddle grew incubate.distributed.models.moe).

TPU-native shape (Switch Transformer style): expert FFN params are STACKED
[E, ...] and placed on the 'ep' mesh axis; token dispatch/combine are
einsums against a [tokens, E, capacity] one-hot, so XLA's SPMD partitioner
inserts the all_to_alls when the token dim resharding meets the
expert-sharded weights — no hand-written collectives (SURVEY §7.1: let the
compiler place comm). Capacity overflow drops tokens (residual passthrough
keeps them alive), and the Switch load-balancing aux loss is recorded on
the layer for the model loss to pick up.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..framework.core import Tensor, run_op
from ..nn import functional as F

__all__ = ['SwitchMoE', 'GShardMoE']


class SwitchMoE(nn.Layer):
    """Top-k routed MoE FFN block: y = combine(expert_ffn(dispatch(x))).
    top_k=1 is Switch; top_k=2 is the GShard configuration (see GShardMoE).

    hidden_size -> ffn_size -> hidden_size per expert; num_experts experts
    sharded over the 'ep' mesh axis when present (placement hints consumed
    by distributed/strategy.py).
    """

    def __init__(self, hidden_size, ffn_size=None, num_experts=4,
                 capacity_factor=1.5, aux_loss_weight=0.01, top_k=1,
                 name=None):
        super().__init__()
        if int(top_k) != top_k or not 1 <= int(top_k) <= num_experts:
            raise ValueError('top_k must be an integer in '
                             '[1, num_experts], got %r' % (top_k,))
        self.top_k = int(top_k)
        self.hidden_size = hidden_size
        self.ffn_size = ffn_size or 4 * hidden_size
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        self.aux_loss_weight = float(aux_loss_weight)
        self.gate = nn.Linear(hidden_size, num_experts)
        e, h, f = num_experts, hidden_size, self.ffn_size
        from ..nn import initializer as init_mod
        self.w1 = self.create_parameter(
            [e, h, f],
            default_initializer=init_mod.Normal(std=1.0 / math.sqrt(h)))
        self.b1 = self.create_parameter([e, f], is_bias=True)
        self.w2 = self.create_parameter(
            [e, f, h],
            default_initializer=init_mod.Normal(std=1.0 / math.sqrt(f)))
        self.b2 = self.create_parameter([e, h], is_bias=True)
        # expert dim rides the 'ep' mesh axis
        self.w1.placement = ('ep', None, None)
        self.b1.placement = ('ep', None)
        self.w2.placement = ('ep', None, None)
        self.b2.placement = ('ep', None)
        self.aux_loss = None

    def forward(self, x):
        """x [B, S, H] (or [T, H]) -> same shape."""
        e = self.num_experts
        gate_logits = self.gate(x)  # [..., E]

        def fn(xa, ga, w1, b1, w2, b2):
            shape = xa.shape
            xt = xa.reshape(-1, shape[-1])            # [T, H]
            gl = ga.reshape(-1, e)                    # [T, E]
            t = xt.shape[0]
            cap = max(1, int(self.capacity_factor * t / e))

            probs = jax.nn.softmax(gl.astype(jnp.float32), axis=-1)
            K = self.top_k
            topv, topi = jax.lax.top_k(probs, K)      # [T, K]
            if K > 1:
                # GShard-style renormalized gates over the chosen experts
                gates = topv / jnp.maximum(topv.sum(-1, keepdims=True),
                                           1e-9)
            else:
                gates = topv  # Switch keeps the raw top-1 probability

            onehot = None  # top-1 assignment, captured in the k=0 round
            dispatch = jnp.zeros((t, e, cap), jnp.float32)
            combine = jnp.zeros((t, e, cap), jnp.float32)
            counts = jnp.zeros((e,), jnp.float32)
            for k in range(K):
                oh_k = jax.nn.one_hot(topi[:, k], e, dtype=jnp.float32)
                if k == 0:
                    onehot = oh_k
                # capacity slots fill top-1 assignments first, then
                # top-2, ... (GShard priority order)
                pos = ((jnp.cumsum(oh_k, axis=0) - 1.0 + counts[None])
                       * oh_k - (1.0 - oh_k))
                in_cap = (pos < cap) & (pos >= 0)
                pos_cl = jnp.clip(pos, 0, cap - 1).astype(jnp.int32)
                cap_oh = jax.nn.one_hot(pos_cl, cap, dtype=jnp.float32)
                d_k = cap_oh * in_cap[..., None]      # [T, E, C]
                dispatch = dispatch + d_k
                combine = combine + d_k * gates[:, k][:, None, None]
                counts = counts + oh_k.sum(0)

            # expert matmuls contract in the compute dtype with f32 MXU
            # accumulation — upcasting the operands would run the MXU at
            # its f32 rate (~8x slower on v5e). dispatch/combine are
            # exact in bf16 (0/1 capacity masks; combine's gate weights
            # round at bf16, the same precision the probs would reach as
            # activations anyway); the f32 routing math above is
            # unaffected.
            cdt = xt.dtype
            # dispatch is a 0/1 capacity mask: each (e, c) slot sums at
            # most ONE token, so f32 accumulation buys nothing — contract
            # straight in the compute dtype
            xin = jnp.einsum('tec,th->ech', dispatch.astype(cdt), xt)
            h1 = jax.nn.gelu(
                jnp.einsum('ech,ehf->ecf', xin, w1,
                           preferred_element_type=jnp.float32)
                + b1.astype(jnp.float32)[:, None])
            out_e = jnp.einsum('ecf,efh->ech', h1.astype(cdt), w2,
                               preferred_element_type=jnp.float32) \
                + b2.astype(jnp.float32)[:, None]
            y = jnp.einsum('tec,ech->th', combine.astype(cdt),
                           out_e.astype(cdt),
                           preferred_element_type=jnp.float32)

            # Switch aux loss: E * sum_e frac_tokens_e * mean_prob_e
            frac = jnp.mean(onehot, axis=0)
            mean_p = jnp.mean(probs, axis=0)
            aux = e * jnp.sum(frac * mean_p)
            return y.reshape(shape).astype(xa.dtype), aux

        y, aux = run_op('switch_moe', fn, x, gate_logits,
                        self.w1, self.b1, self.w2, self.b2)
        self.aux_loss = aux
        return y


class GShardMoE(SwitchMoE):
    """Top-2 routed MoE (GShard configuration): renormalized two-expert
    gates, capacity filled in top-1-first priority order."""

    def __init__(self, hidden_size, ffn_size=None, num_experts=4,
                 capacity_factor=2.0, aux_loss_weight=0.01, name=None):
        super().__init__(hidden_size, ffn_size, num_experts,
                         capacity_factor, aux_loss_weight, top_k=2,
                         name=name)
