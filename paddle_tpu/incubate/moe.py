"""Expert-parallel Mixture-of-Experts (beyond-reference: SURVEY.md §2.2
notes MoE/expert parallelism is absent from the reference snapshot but in
the capability bar; later paddle grew incubate.distributed.models.moe).

TPU-native shape (Switch Transformer style): expert FFN params are STACKED
[E, ...] and placed on the 'ep' mesh axis; token dispatch/combine are
einsums against a [tokens, E, capacity] one-hot, so XLA's SPMD partitioner
inserts the all_to_alls when the token dim resharding meets the
expert-sharded weights — no hand-written collectives (SURVEY §7.1: let the
compiler place comm). Capacity overflow drops tokens (residual passthrough
keeps them alive), and the Switch load-balancing aux loss is recorded on
the layer for the model loss to pick up.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..framework.core import Tensor, run_op
from ..nn import functional as F

__all__ = ['SwitchMoE']


class SwitchMoE(nn.Layer):
    """Top-1 routed MoE FFN block: y = combine(expert_ffn(dispatch(x))).

    hidden_size -> ffn_size -> hidden_size per expert; num_experts experts
    sharded over the 'ep' mesh axis when present (placement hints consumed
    by distributed/strategy.py).
    """

    def __init__(self, hidden_size, ffn_size=None, num_experts=4,
                 capacity_factor=1.5, aux_loss_weight=0.01, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.ffn_size = ffn_size or 4 * hidden_size
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        self.aux_loss_weight = float(aux_loss_weight)
        self.gate = nn.Linear(hidden_size, num_experts)
        e, h, f = num_experts, hidden_size, self.ffn_size
        from ..nn import initializer as init_mod
        self.w1 = self.create_parameter(
            [e, h, f],
            default_initializer=init_mod.Normal(std=1.0 / math.sqrt(h)))
        self.b1 = self.create_parameter([e, f], is_bias=True)
        self.w2 = self.create_parameter(
            [e, f, h],
            default_initializer=init_mod.Normal(std=1.0 / math.sqrt(f)))
        self.b2 = self.create_parameter([e, h], is_bias=True)
        # expert dim rides the 'ep' mesh axis
        self.w1.placement = ('ep', None, None)
        self.b1.placement = ('ep', None)
        self.w2.placement = ('ep', None, None)
        self.b2.placement = ('ep', None)
        self.aux_loss = None

    def forward(self, x):
        """x [B, S, H] (or [T, H]) -> same shape."""
        e = self.num_experts
        gate_logits = self.gate(x)  # [..., E]

        def fn(xa, ga, w1, b1, w2, b2):
            shape = xa.shape
            xt = xa.reshape(-1, shape[-1])            # [T, H]
            gl = ga.reshape(-1, e)                    # [T, E]
            t = xt.shape[0]
            cap = max(1, int(self.capacity_factor * t / e))

            probs = jax.nn.softmax(gl.astype(jnp.float32), axis=-1)
            top_p = jnp.max(probs, axis=-1)           # [T]
            top_e = jnp.argmax(probs, axis=-1)        # [T]

            onehot = jax.nn.one_hot(top_e, e, dtype=jnp.float32)  # [T,E]
            pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0       # [T,E]
            in_cap = (pos < cap) & (pos >= 0)
            pos_cl = jnp.clip(pos, 0, cap - 1).astype(jnp.int32)
            cap_oh = jax.nn.one_hot(pos_cl, cap, dtype=jnp.float32)
            dispatch = cap_oh * in_cap[..., None]     # [T, E, C]
            combine = dispatch * top_p[:, None, None]

            xin = jnp.einsum('tec,th->ech', dispatch,
                             xt.astype(jnp.float32))
            h1 = jax.nn.gelu(
                jnp.einsum('ech,ehf->ecf', xin, w1.astype(jnp.float32))
                + b1.astype(jnp.float32)[:, None])
            out_e = jnp.einsum('ecf,efh->ech', h1,
                               w2.astype(jnp.float32)) \
                + b2.astype(jnp.float32)[:, None]
            y = jnp.einsum('tec,ech->th', combine, out_e)

            # Switch aux loss: E * sum_e frac_tokens_e * mean_prob_e
            frac = jnp.mean(onehot, axis=0)
            mean_p = jnp.mean(probs, axis=0)
            aux = e * jnp.sum(frac * mean_p)
            return y.reshape(shape).astype(xa.dtype), aux

        y, aux = run_op('switch_moe', fn, x, gate_logits,
                        self.w1, self.b1, self.w2, self.b2)
        self.aux_loss = aux
        return y
