"""Auto-checkpoint / epoch-level resume (reference:
fluid/incubate/checkpoint/auto_checkpoint.py — TrainEpochRange (:265)
wraps the epoch loop, periodically snapshots training state keyed by job
id, and on restart resumes at the last saved epoch; configured via
PADDLE_* env).

TPU-native: one snapshot layer (framework io_save / orbax-backed
distributed checkpoint) holds {epoch, model state_dict, optimizer state};
gang-scheduled TPU jobs restart whole, so epoch-granular resume is the
first-class recovery path (SURVEY.md §5.3).
"""
import os
import re

from ..framework import io_save

__all__ = ['TrainEpochRange', 'train_epoch_range']

_CKPT_RE = re.compile(r'^epoch_(\d+)\.ckpt$')


class TrainEpochRange:
    """for epoch in TrainEpochRange(20, 'job1', model=m, optimizer=opt):
    — resumes from the newest snapshot in checkpoint_dir and saves one
    every `save_checkpoint_inter` epochs (after the epoch body ran)."""

    def __init__(self, max_epoch_num, name=None, checkpoint_dir=None,
                 save_checkpoint_inter=1, model=None, optimizer=None,
                 extra_state=None, keep_last=3):
        self.max_epoch_num = int(max_epoch_num)
        self.name = name or os.environ.get('PADDLE_JOB_ID', 'acp_job')
        self.dir = checkpoint_dir or os.environ.get(
            'PADDLE_CHECKPOINT_DIR', './acp_checkpoints')
        self.dir = os.path.join(self.dir, self.name)
        self.inter = max(int(save_checkpoint_inter), 1)
        self.model = model
        self.optimizer = optimizer
        self.extra_state = extra_state if extra_state is not None else {}
        self.keep_last = int(keep_last)
        self.restored_epoch = -1
        self.skipped_corrupt = []   # epochs whose snapshot failed verify
        self._restore()

    # -- snapshot plumbing ---------------------------------------------------
    def _epochs_on_disk(self):
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for n in names:
            m = _CKPT_RE.match(n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _path(self, epoch):
        return os.path.join(self.dir, 'epoch_%d.ckpt' % epoch)

    def _restore(self):
        """Resume from the NEWEST VALID snapshot: a truncated/torn latest
        checkpoint (writer preempted mid-save) is detected via its CRC32
        manifest and skipped, falling back to the previous epoch — losing
        one save interval instead of the whole job."""
        for epoch in reversed(self._epochs_on_disk()):
            path = self._path(epoch)
            if not io_save.verify_checkpoint(path):
                self.skipped_corrupt.append(epoch)
                continue
            try:
                payload = io_save.load(path)
            except Exception:
                self.skipped_corrupt.append(epoch)
                continue
            if self.model is not None and 'model' in payload:
                self.model.set_state_dict(payload['model'])
            if self.optimizer is not None and 'optimizer' in payload:
                self.optimizer.set_state_dict(payload['optimizer'])
            self.extra_state.update(payload.get('extra', {}))
            self.restored_epoch = epoch
            return

    def save(self, epoch):
        payload = {'epoch': epoch, 'extra': dict(self.extra_state)}
        if self.model is not None:
            payload['model'] = self.model.state_dict()
        if self.optimizer is not None:
            payload['optimizer'] = self.optimizer.state_dict()
        # io_save writes atomically (temp + rename) with a manifest, so a
        # preemption mid-save can never tear an existing snapshot
        io_save.save(payload, self._path(epoch))
        for old in self._epochs_on_disk()[:-self.keep_last]:
            for p in (self._path(old),
                      io_save.manifest_path(self._path(old))):
                try:
                    os.remove(p)
                except OSError:
                    pass

    # -- the epoch loop ------------------------------------------------------
    def __iter__(self):
        start = self.restored_epoch + 1
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            if (epoch + 1) % self.inter == 0 or \
                    epoch == self.max_epoch_num - 1:
                self.save(epoch)


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1, **kwargs):
    """Generator form (reference acp.train_epoch_range)."""
    return TrainEpochRange(max_epoch_num,
                           save_checkpoint_inter=save_checkpoint_inter,
                           **kwargs)
