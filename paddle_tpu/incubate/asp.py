"""ASP: automatic structured (n:m) sparsity.

Parity: python/paddle/fluid/contrib/sparsity/ (utils.py create_mask /
check_sparsity / calculate_density, asp.py prune_model + ASPHelper,
fleet meta-optimizer asp_optimizer.py). TPU-native: masks are plain
arrays applied to Layer weights; `decorate(optimizer)` re-applies masks
after every step so training preserves the 2:4 pattern (the reference
hooks the same way via OptimizerWithSparsityGuarantee).
"""
import numpy as np

__all__ = ['calculate_density', 'check_mask_1d', 'check_mask_2d',
           'create_mask', 'check_sparsity', 'prune_model', 'decorate',
           'reset_excluded_layers', 'set_excluded_layers', 'ASPHelper']

_EXCLUDED = set()


def calculate_density(mat):
    return float(np.count_nonzero(mat)) / mat.size


def _group_view(mat, m):
    """Reshape the last dim into groups of m (pad refused — caller checks)."""
    arr = np.asarray(mat)
    if arr.shape[-1] % m:
        raise ValueError('last dim %d not divisible by m=%d'
                         % (arr.shape[-1], m))
    return arr.reshape(-1, m)


def check_mask_1d(mat, n, m):
    """True iff every group of m consecutive (row-major) elements has at
    most n nonzeros."""
    groups = _group_view(mat, m)
    return bool(np.all((groups != 0).sum(1) <= n))


def create_mask_1d(mat, n, m):
    groups = _group_view(np.abs(mat), m)
    # keep the n largest magnitudes per group
    idx = np.argsort(-groups, axis=1, kind='stable')[:, :n]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, idx, 1.0, axis=1)
    return mask.reshape(np.asarray(mat).shape)


def check_mask_2d(mat, n, m):
    """True iff every m×m block has ≤ n nonzeros per row AND per column."""
    arr = np.asarray(mat)
    h, w = arr.shape[-2], arr.shape[-1]
    if h % m or w % m:
        raise ValueError('shape %s not divisible into %dx%d blocks'
                         % (arr.shape, m, m))
    a = arr.reshape(-1, h // m, m, w // m, m)
    nz = a != 0
    return bool(np.all(nz.sum(2) <= n) and np.all(nz.sum(4) <= n))


def create_mask_2d_greedy(mat, n, m):
    """Greedy 2D mask: per m×m block pick entries in decreasing magnitude
    subject to ≤ n per row and per column."""
    arr = np.asarray(mat)
    h, w = arr.shape[-2], arr.shape[-1]
    if h % m or w % m:
        raise ValueError('shape %s not divisible into %dx%d blocks'
                         % (arr.shape, m, m))
    flat = arr.reshape(-1, h, w)
    mask = np.zeros_like(flat)
    for b in range(flat.shape[0]):
        for bi in range(0, h, m):
            for bj in range(0, w, m):
                block = np.abs(flat[b, bi:bi + m, bj:bj + m])
                order = np.dstack(np.unravel_index(
                    np.argsort(-block, axis=None), (m, m)))[0]
                rows = np.zeros(m, np.int64)
                cols = np.zeros(m, np.int64)
                for r, c in order:
                    if rows[r] < n and cols[c] < n:
                        mask[b, bi + r, bj + c] = 1.0
                        rows[r] += 1
                        cols[c] += 1
    return mask.reshape(arr.shape)


_MASK_FUNCS = {
    'mask_1d': create_mask_1d,
    'mask_2d_greedy': create_mask_2d_greedy,
    'mask_2d_best': create_mask_2d_greedy,  # greedy ≈ best for 2:4
}
_CHECK_FUNCS = {
    'check_1d': check_mask_1d,
    'check_2d': check_mask_2d,
}


def create_mask(mat, func_name='mask_1d', n=2, m=4):
    if func_name not in _MASK_FUNCS:
        raise ValueError('unknown mask func %r (have %s)'
                         % (func_name, sorted(_MASK_FUNCS)))
    return _MASK_FUNCS[func_name](np.asarray(mat), n, m)


def check_sparsity(mat, func_name='check_1d', n=2, m=4):
    if func_name not in _CHECK_FUNCS:
        raise ValueError('unknown check func %r (have %s)'
                         % (func_name, sorted(_CHECK_FUNCS)))
    return _CHECK_FUNCS[func_name](np.asarray(mat), n, m)


def set_excluded_layers(param_names):
    """Exclude parameters by name from pruning (reference
    sparsity.set_excluded_layers)."""
    _EXCLUDED.update(param_names)


def reset_excluded_layers():
    _EXCLUDED.clear()


def _prunable_params(model):
    from ..nn import Conv2D, Linear
    for lname, layer in model.named_sublayers():
        if type(layer) in (Linear, Conv2D):
            w = layer.weight
            name = getattr(w, 'name', None) or (lname + '.weight')
            if name in _EXCLUDED or lname in _EXCLUDED:
                continue
            yield name, w


class ASPHelper:
    """Holds masks for a pruned model and re-applies them after optimizer
    steps (reference asp.py ASPHelper / OptimizerWithSparsityGuarantee)."""

    def __init__(self):
        self.masks = {}

    def prune_model(self, model, n=2, m=4, mask_algo='mask_1d',
                    with_mask=True):
        import jax.numpy as jnp
        for name, w in _prunable_params(model):
            arr = np.asarray(w._data)
            if arr.ndim < 2 or arr.shape[-1] % m:
                continue
            if arr.ndim > 2:
                # conv [out,in,kh,kw]: prune over the flattened (in*kh*kw)
                # per-out-channel view like the reference
                flat = arr.reshape(arr.shape[0], -1)
                if flat.shape[-1] % m:
                    continue
                mask = create_mask(flat, mask_algo, n, m).reshape(arr.shape)
            else:
                mask = create_mask(arr, mask_algo, n, m)
            w._data = jnp.asarray(arr * mask, dtype=w._data.dtype)
            if with_mask:
                self.masks[id(w)] = (w, jnp.asarray(mask,
                                                    dtype=w._data.dtype))
        return self.masks

    def apply_masks(self):
        for w, mask in self.masks.values():
            w._data = w._data * mask

    def decorate(self, optimizer):
        helper = self
        orig_step = optimizer.step

        def step(*args, **kwargs):
            out = orig_step(*args, **kwargs)
            helper.apply_masks()
            return out
        optimizer.step = step
        optimizer._asp_helper = helper
        return optimizer


_default_helper = ASPHelper()


def prune_model(model, n=2, m=4, mask_algo='mask_1d', with_mask=True):
    """Prune all Linear/Conv2D weights of `model` to n:m sparsity."""
    return _default_helper.prune_model(model, n=n, m=m,
                                       mask_algo=mask_algo,
                                       with_mask=with_mask)


def decorate(optimizer):
    """Wrap an optimizer so each step() re-applies the sparsity masks."""
    return _default_helper.decorate(optimizer)
