"""paddle.hub shim (reference: python/paddle/hapi/hub.py). Zero-egress
environment: local-dir sources only."""
import importlib.util
import os
import sys

__all__ = ['list', 'help', 'load']


def _load_entry(repo_dir):
    path = os.path.join(repo_dir, 'hubconf.py')
    spec = importlib.util.spec_from_file_location('hubconf', path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules['hubconf'] = mod
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source='local', force_reload=False):
    if source != 'local':
        raise RuntimeError("only source='local' is supported (no egress)")
    mod = _load_entry(repo_dir)
    return [k for k in dir(mod) if callable(getattr(mod, k))
            and not k.startswith('_')]


def help(repo_dir, model, source='local', force_reload=False):
    mod = _load_entry(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source='local', force_reload=False, **kwargs):
    if source != 'local':
        raise RuntimeError("only source='local' is supported (no egress)")
    mod = _load_entry(repo_dir)
    return getattr(mod, model)(**kwargs)
