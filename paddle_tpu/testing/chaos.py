"""Fault-injection harness for failure-path tests.

The resilience layer (distributed/resilience.py) exposes hook points —
'connect', 'send', 'recv', each fired with the endpoint string — and this
module installs injectors into them. Everything is context-managed so a
failing test can never leak a fault into the next one.

    with chaos.drop_connections(times=2):
        client.get_degree(...)        # first two transport ops fail

    chaos.kill_server(graph_server)   # hard kill: listener AND live conns

    chaos.truncate_file(ckpt_path)    # corrupt a checkpoint in place

Faults compose (nested context managers fire in install order) and can be
scoped to an endpoint substring, a hook point, and a max fire count.
"""
import contextlib
import os
import socket
import threading

from ..distributed import resilience
from ..framework import io_save as _io_save
from ..monitor import tracing as _tracing

__all__ = ['inject', 'drop_connections', 'delay_connections', 'partition',
           'fail_after', 'kill_server', 'truncate_file', 'crash_io_save',
           'active_faults']


def active_faults():
    """Number of currently installed injectors (leak canary for tests) —
    transport hooks plus checkpoint-writer hooks."""
    return len(resilience._FAULT_HOOKS) + len(_io_save._FAULT_HOOKS)


@contextlib.contextmanager
def inject(hook):
    """Install a raw `fn(point, endpoint)` injector for the duration."""
    resilience._FAULT_HOOKS.append(hook)
    try:
        yield hook
    finally:
        try:
            resilience._FAULT_HOOKS.remove(hook)
        except ValueError:
            pass


class _Fault:
    """Counted, endpoint/point-scoped injector."""

    def __init__(self, action, points, endpoint_substr, times):
        self._action = action
        self._points = points
        self._match = endpoint_substr
        self._times = times
        self._lock = threading.Lock()
        self.fired = 0

    def __call__(self, point, endpoint):
        if self._points is not None and point not in self._points:
            return
        if self._match is not None and self._match not in endpoint:
            return
        with self._lock:
            if self._times is not None and self.fired >= self._times:
                return
            self.fired += 1
        # annotate the current span (the rpc.attempt in flight) and give
        # the flight recorder a chance to dump, BEFORE the action fires —
        # the action usually raises
        _tracing.note_fault(point, endpoint)
        self._action(point, endpoint)


def _as_points(point):
    if point is None:
        return None
    if isinstance(point, str):
        return (point,)
    return tuple(point)


def drop_connections(endpoint=None, point=None, times=None):
    """Make matching transport ops raise ConnectionError.

    point: 'connect' | 'send' | 'recv' | tuple | None (= all three);
    times: stop firing after N drops (None = for the whole scope).
    Returns a context manager yielding the fault (inspect `.fired`).
    """
    def action(p, ep):
        raise ConnectionError('chaos: dropped %s to %s' % (p, ep))
    return inject(_Fault(action, _as_points(point), endpoint, times))


def partition(endpoint, times=None):
    """Network-partition a single endpoint: both send AND recv raise until
    the context exits (or `times` ops have been dropped). Unlike
    drop_connections(point=None) this never touches 'connect', so a
    partitioned peer looks *reachable* but black-holes traffic — the
    failure mode that forces a gateway to fail requests over rather than
    simply re-dial. Returns the fault (inspect `.fired`)."""
    def action(p, ep):
        raise ConnectionError('chaos: partitioned %s at %s' % (ep, p))
    return inject(_Fault(action, ('send', 'recv'), endpoint, times))


def delay_connections(seconds, endpoint=None, point='connect', times=None):
    """Sleep `seconds` at matching hook points (latency injection)."""
    import time

    def action(p, ep):
        time.sleep(seconds)
    return inject(_Fault(action, _as_points(point), endpoint, times))


def fail_after(n, endpoint=None, point='send', exc=ConnectionResetError):
    """Let the first n matching ops through, then fail every later one —
    a server that dies mid-batch from the client's point of view."""
    state = {'seen': 0}
    lock = threading.Lock()

    def hook(p, ep):
        if p != point:
            return
        if endpoint is not None and endpoint not in ep:
            return
        with lock:
            state['seen'] += 1
            if state['seen'] > n:
                raise exc('chaos: %s to %s failed after %d ops'
                          % (p, ep, n))
    return inject(hook)


def kill_server(server):
    """Hard-kill a GraphPyServer or EmbeddingServer: stop the listener AND
    sever every established connection, like a SIGKILLed pod. In-flight
    client calls see a reset; later calls see refused connections (until
    something rebinds the port)."""
    srv = getattr(server, '_srv', server)
    try:
        srv.shutdown()
    except Exception:
        pass
    for conn in list(getattr(srv, 'live_connections', ())):
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass
    try:
        srv.server_close()
    except Exception:
        pass


class WriterKilled(BaseException):
    """Raised by crash_io_save to simulate a writer dying mid-save.

    Deliberately NOT an Exception: a preempted pod doesn't run except
    handlers, and deriving from BaseException keeps broad `except
    Exception` recovery paths in the code under test from swallowing the
    simulated death."""


def crash_io_save(point, path_substr=None, times=1):
    """Kill the io_save atomic writer at a named point for the scope.

    point: 'pre_rename' (payload still in the temp file — the target
    path is untouched) or 'pre_manifest' (payload renamed into place,
    manifest sidecar missing/stale). path_substr scopes the crash to
    matching destination paths; times bounds how many saves die.
    Returns a context manager yielding the fault (inspect `.fired`).
    """
    def action(p, target):
        raise WriterKilled('chaos: writer killed at %s of %s'
                           % (p, target))
    fault = _Fault(action, _as_points(point), path_substr, times)

    @contextlib.contextmanager
    def _scope():
        _io_save._FAULT_HOOKS.append(fault)
        try:
            yield fault
        finally:
            try:
                _io_save._FAULT_HOOKS.remove(fault)
            except ValueError:
                pass
    return _scope()


def truncate_file(path, keep_bytes=None, drop_bytes=16):
    """Truncate a file in place (a preempted writer / torn disk write).
    keep_bytes wins if given; otherwise the final drop_bytes are cut."""
    size = os.path.getsize(path)
    if keep_bytes is None:
        keep_bytes = max(size - drop_bytes, 0)
    with open(path, 'r+b') as f:
        f.truncate(keep_bytes)
    return keep_bytes
