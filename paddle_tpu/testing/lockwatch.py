"""Runtime lock-order witness — the dynamic half of graftlint's lock
checker.

The static checker (tools/graftlint/checkers/locks.py) derives a
lock-acquisition-order graph lexically: an edge A -> B means some code
path acquires B while holding A.  That analysis is conservative and
blind to locks passed through indirection, so threaded tests wrap their
locks in a :class:`LockWatch` and assert the ORDER OBSERVED AT RUNTIME
stays consistent — both internally (no thread ever acquires in an order
that inverts another thread's) and against the static graph (the union
of runtime and static edges must stay acyclic).

Usage::

    watch = LockWatch()
    replica._cv = watch.wrap('replica._cv', replica._cv)
    ... drive threads ...
    watch.assert_acyclic()                    # runtime-only check
    watch.assert_acyclic(static_edges)        # cross-check vs graftlint

Wrapped locks proxy every other attribute (``wait``, ``notify_all``,
``locked`` ...) to the underlying object, so a wrapped ``Condition``
still behaves like one.
"""
import threading

__all__ = ['LockWatch', 'LockOrderError']


class LockOrderError(AssertionError):
    """Two code paths acquire the same locks in conflicting order."""


class _WatchedLock:
    """Proxy that reports acquire/release to its LockWatch."""

    def __init__(self, watch, name, lock):
        self._watch = watch
        self._name = name
        self._lock = lock

    def acquire(self, *a, **kw):
        got = self._lock.acquire(*a, **kw)
        if got:
            self._watch._on_acquire(self._name)
        return got

    def release(self):
        self._watch._on_release(self._name)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition.wait releases and re-acquires the underlying lock; the
    # held-stack position does not change, so plain passthrough is right.
    def __getattr__(self, attr):
        return getattr(self._lock, attr)


class LockWatch:
    """Records the lock-acquisition-order graph actually exercised.

    ``strict=True`` raises at the acquisition that first inverts an
    already-observed edge (best for pinpointing the offending stack);
    the default defers to :meth:`assert_acyclic` so a test can drive
    all its threads first.
    """

    def __init__(self, strict=False):
        self._tls = threading.local()
        self._mu = threading.Lock()
        self._edges = {}        # (held, acquired) -> observation count
        self._strict = strict

    def wrap(self, name, lock):
        return _WatchedLock(self, name, lock)

    def _held(self):
        st = getattr(self._tls, 'stack', None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, name):
        held = self._held()
        with self._mu:
            for h in held:
                if h != name:   # re-entrant re-acquire adds no edge
                    self._edges[(h, name)] = \
                        self._edges.get((h, name), 0) + 1
                    if self._strict and (name, h) in self._edges:
                        raise LockOrderError(
                            'lock order inversion: acquiring %r while '
                            'holding %r, but the opposite order was '
                            'already observed' % (name, h))
        held.append(name)

    def _on_release(self, name):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edges(self):
        with self._mu:
            return dict(self._edges)

    def assert_acyclic(self, extra_edges=()):
        """Raise LockOrderError if observed edges (unioned with
        ``extra_edges``, e.g. graftlint's static acquisition_order)
        contain a cycle."""
        graph = {}
        for a, b in list(self.edges()) + [tuple(e) for e in extra_edges]:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        stack = []

        def visit(n):
            color[n] = GREY
            stack.append(n)
            for m in sorted(graph[n]):
                if color[m] == GREY:
                    cyc = stack[stack.index(m):] + [m]
                    raise LockOrderError(
                        'lock acquisition-order cycle: %s'
                        % ' -> '.join(cyc))
                if color[m] == WHITE:
                    visit(m)
            stack.pop()
            color[n] = BLACK

        for n in sorted(graph):
            if color[n] == WHITE:
                visit(n)
