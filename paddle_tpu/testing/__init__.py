"""Test-support utilities (fault injection lives in testing.chaos,
runtime lock-order witnessing in testing.lockwatch)."""
from . import chaos  # noqa: F401
from . import lockwatch  # noqa: F401
