"""Test-support utilities (fault injection lives in testing.chaos)."""
from . import chaos  # noqa: F401
