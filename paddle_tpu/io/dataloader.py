"""DataLoader (reference: fluid/reader.py:146 DataLoader,
dataloader/dataloader_iter.py single/multi-process iterators,
dataloader/worker.py).

Single-process path: inline collate. Multi-worker path: multiprocessing pool
with an index queue and a thread that reorders results — same scheme as the
reference's _DataLoaderIterMultiProcess, minus CUDA-pinned shared memory
(not needed for TPU hosts).
"""
import itertools
import queue
import threading
import multiprocessing as mp

import numpy as np

from ..framework.core import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler

__all__ = ['DataLoader', 'default_collate_fn']


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._data for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def _np_collate(batch):
    """Worker-side collate to numpy (picklable across processes)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [_np_collate(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    return batch


class WorkerInfo:
    """Info for the current DataLoader worker (reference
    dataloader/worker.py get_worker_info): None in the main process."""

    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = [None]


def get_worker_info():
    return _worker_info[0]


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 worker_init_fn, num_workers=0):
    _worker_info[0] = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            data = collate_fn(samples)
            data_queue.put((batch_id, data, None))
        except Exception as e:  # propagate worker errors to the main proc
            data_queue.put((batch_id, None, repr(e)))


def _to_tensor_tree(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, list):
        return [_to_tensor_tree(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    return obj


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, use_shared_memory=True,
                 prefetch_factor=2, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.num_workers = num_workers
        self.collate_fn = collate_fn
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset loader is unknown")
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_multi()

    def _iter_iterable(self):
        collate = self.collate_fn or default_collate_fn
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield collate(batch)
                batch = []
        if batch and not self.drop_last:
            yield collate(batch)

    def _iter_single(self):
        collate = self.collate_fn or default_collate_fn
        for indices in self.batch_sampler:
            yield collate([self.dataset[i] for i in indices])

    def _iter_multi(self):
        collate = self.collate_fn or _np_collate
        user_collate = self.collate_fn is not None
        ctx = mp.get_context('fork')
        index_queues, workers = [], []
        data_queue = ctx.Queue()
        for wid in range(self.num_workers):
            iq = ctx.Queue()
            w = ctx.Process(target=_worker_loop,
                            args=(self.dataset, iq, data_queue, collate, wid,
                                  self.worker_init_fn, self.num_workers),
                            daemon=True)
            w.start()
            index_queues.append(iq)
            workers.append(w)

        try:
            all_batches = list(enumerate(self.batch_sampler))
            for bid, indices in all_batches:
                index_queues[bid % self.num_workers].put((bid, indices))
            buffered = {}
            for next_yield in range(len(all_batches)):
                while next_yield not in buffered:
                    bid, data, err = data_queue.get()
                    buffered[bid] = (data, err)
                data, err = buffered.pop(next_yield)
                if err is not None:
                    raise RuntimeError("DataLoader worker failed: %s" % err)
                yield data if user_collate else _to_tensor_tree(data)
        finally:
            for iq in index_queues:
                iq.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
