"""Data pipeline (reference: python/paddle/io/ + fluid/reader.py:146 +
dataloader/dataloader_iter.py).

TPU-native notes: batches are assembled as host numpy arrays (device transfer
happens at jit boundary, overlapped by XLA's async dispatch); multi-process
workers use the stdlib multiprocessing queue path (the reference's
mmap/shared-mem IPC is a CUDA-pinned-memory optimization that does not apply
to TPU hosts); DistributedBatchSampler shards by process for multi-host.
"""
from .dataset import (Dataset, IterableDataset, TensorDataset,  # noqa: F401
                      ComposeDataset, ChainDataset, Subset, random_split,
                      ConcatDataset)
from .sampler import (Sampler, SequenceSampler, RandomSampler,  # noqa: F401
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler)
from .dataloader import (DataLoader, default_collate_fn,  # noqa: F401
                         get_worker_info, WorkerInfo)

__all__ = ['get_worker_info', 'WorkerInfo',
           'Dataset', 'IterableDataset', 'TensorDataset', 'ComposeDataset',
           'ChainDataset', 'Subset', 'random_split', 'Sampler',
           'SequenceSampler', 'RandomSampler', 'WeightedRandomSampler',
           'BatchSampler', 'DistributedBatchSampler', 'DataLoader']
