"""Probability distributions (reference: python/paddle/distribution.py)."""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, wrap_out, run_op
from ..framework import random as rng
from ..tensor._helpers import ensure_tensor

__all__ = ['Distribution', 'Normal', 'Uniform', 'Categorical', 'Beta',
           'Dirichlet', 'Exponential', 'Bernoulli', 'Multinomial', 'kl_divergence']


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        from ..tensor.math import exp
        return exp(self.log_prob(value))


def _arr(x):
    return ensure_tensor(x)._data if not isinstance(x, (int, float)) \
        else jnp.asarray(float(x))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                  self.scale.shape)
        eps = jax.random.normal(rng.next_key(), shp)
        return wrap_out(self.loc + self.scale * eps)

    def rsample(self, shape=()):
        return self.sample(shape)

    def entropy(self):
        return wrap_out(0.5 + 0.5 * math.log(2 * math.pi) +
                        jnp.log(self.scale) * jnp.ones_like(self.loc))

    def log_prob(self, value):
        v = ensure_tensor(value)

        def fn(x):
            var = self.scale ** 2
            return -((x - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) \
                - 0.5 * math.log(2 * math.pi)
        return run_op('normal_log_prob', fn, v)

    def kl_divergence(self, other):
        var_a = self.scale ** 2
        var_b = other.scale ** 2
        return wrap_out(jnp.log(other.scale / self.scale) +
                        (var_a + (self.loc - other.loc) ** 2) / (2 * var_b) - 0.5)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                  self.high.shape)
        u = jax.random.uniform(rng.next_key(), shp)
        return wrap_out(self.low + (self.high - self.low) * u)

    def entropy(self):
        return wrap_out(jnp.log(self.high - self.low))

    def log_prob(self, value):
        v = ensure_tensor(value)

        def fn(x):
            inside = (x >= self.low) & (x < self.high)
            return jnp.where(inside, -jnp.log(self.high - self.low), -np.inf)
        return run_op('uniform_log_prob', fn, v)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits)

    def sample(self, shape=()):
        out = jax.random.categorical(rng.next_key(), self.logits,
                                     shape=tuple(shape) + self.logits.shape[:-1])
        return wrap_out(out.astype(jnp.int64))

    def entropy(self):
        p = jax.nn.softmax(self.logits, -1)
        logp = jax.nn.log_softmax(self.logits, -1)
        return wrap_out(-jnp.sum(p * logp, -1))

    def log_prob(self, value):
        v = ensure_tensor(value)._data.astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, -1)
        return wrap_out(jnp.take_along_axis(logp, v[..., None], -1)[..., 0])

    def probs(self, value):
        v = ensure_tensor(value)._data.astype(jnp.int32)
        p = jax.nn.softmax(self.logits, -1)
        return wrap_out(jnp.take_along_axis(p, v[..., None], -1)[..., 0])

    def kl_divergence(self, other):
        p = jax.nn.softmax(self.logits, -1)
        return wrap_out(jnp.sum(p * (jax.nn.log_softmax(self.logits, -1) -
                                     jax.nn.log_softmax(other.logits, -1)), -1))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)

    def sample(self, shape=()):
        shp = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape,
                                                  self.beta.shape)
        return wrap_out(jax.random.beta(rng.next_key(), self.alpha, self.beta,
                                        shp))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = ensure_tensor(value)._data
        return wrap_out((self.alpha - 1) * jnp.log(v) +
                        (self.beta - 1) * jnp.log1p(-v) -
                        betaln(self.alpha, self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        a, b = self.alpha, self.beta
        return wrap_out(betaln(a, b) - (a - 1) * digamma(a) -
                        (b - 1) * digamma(b) +
                        (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)

    def sample(self, shape=()):
        return wrap_out(jax.random.dirichlet(rng.next_key(),
                                             self.concentration,
                                             tuple(shape)))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = ensure_tensor(value)._data
        a = self.concentration
        return wrap_out(jnp.sum((a - 1) * jnp.log(v), -1) +
                        gammaln(jnp.sum(a, -1)) - jnp.sum(gammaln(a), -1))


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _arr(rate)

    def sample(self, shape=()):
        shp = tuple(shape) + self.rate.shape
        return wrap_out(jax.random.exponential(rng.next_key(), shp) / self.rate)

    def log_prob(self, value):
        v = ensure_tensor(value)._data
        return wrap_out(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return wrap_out(1.0 - jnp.log(self.rate))


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.p = _arr(probs)

    def sample(self, shape=()):
        shp = tuple(shape) + self.p.shape
        return wrap_out(jax.random.bernoulli(
            rng.next_key(), self.p, shp).astype(jnp.float32))

    def log_prob(self, value):
        v = ensure_tensor(value)._data
        return wrap_out(v * jnp.log(self.p) + (1 - v) * jnp.log1p(-self.p))

    def entropy(self):
        p = self.p
        return wrap_out(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.n = int(total_count)
        self.p = _arr(probs)

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.p, 1e-30))
        draws = jax.random.categorical(
            rng.next_key(), logits,
            shape=tuple(shape) + (self.n,) + self.p.shape[:-1])
        k = self.p.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        return wrap_out(jnp.sum(onehot, axis=len(shape)))


def kl_divergence(p, q):
    return p.kl_divergence(q)
