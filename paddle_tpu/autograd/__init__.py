"""Autograd public API (reference: python/paddle/autograd/).

paddle.grad maps to the tape (PartialGradEngine parity,
imperative/partial_grad_engine.cc); PyLayer maps to a recorded custom-VJP op.
"""
import jax
import jax.numpy as jnp

from ..framework.core import (Tensor, GradNode, backward_engine, no_grad_guard,
                              enable_grad_guard, run_op, wrap_out, is_grad_enabled,
                              set_grad_enabled)

no_grad = no_grad_guard
enable_grad = enable_grad_guard

__all__ = ['backward', 'grad', 'no_grad', 'enable_grad', 'PyLayer',
           'PyLayerContext', 'is_grad_enabled', 'set_grad_enabled']


def backward(tensors, grad_tensors=None, retain_graph=False):
    backward_engine(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad: grads of outputs w.r.t. inputs without touching .grad."""
    if create_graph:
        # double-backward needs backward ops recorded on the tape, which the
        # per-op jax.vjp design does not retain; use incubate.autograd.vjp /
        # jax.grad composition for higher-order derivatives.
        raise NotImplementedError(
            "create_graph=True is not supported by the eager tape; "
            "compose jax-level transforms via "
            "paddle_tpu.incubate.autograd.vjp/jvp for higher-order grads")
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    # stash and clear .grad, run backward, harvest, restore
    saved = [(t, t._grad) for t in ins]
    for t in ins:
        t._grad = None
    retain = True if retain_graph is None else retain_graph
    backward_engine(list(outs), grad_tensors=grad_outputs, retain_graph=retain)
    results = []
    for t in ins:
        g = t._grad
        if g is None and not allow_unused:
            g = Tensor(jnp.zeros(t.shape, t._data.dtype))
        results.append(g)
    for t, old in saved:
        t._grad = old
    return results


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.container = None

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """Custom op with user-defined forward/backward (reference:
    python/paddle/autograd/py_layer.py). The backward runs as the node's vjp."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        with no_grad_guard():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]

        needs = is_grad_enabled() and any(not t.stop_gradient for t in tensor_args)
        if not needs:
            return out

        def vjp_fn(cots):
            cot_list = list(cots) if isinstance(cots, tuple) else [cots]
            cot_tensors = [Tensor(c) for c in cot_list]
            with no_grad_guard():
                gin = cls.backward(ctx, *cot_tensors)
            gins = list(gin) if isinstance(gin, (tuple, list)) else [gin]
            return [g._data if isinstance(g, Tensor) else g for g in gins]

        node = GradNode('py_layer:%s' % cls.__name__, vjp_fn, tensor_args,
                        [(tuple(t.shape), t._data.dtype) for t in outs])
        import weakref
        for i, t in enumerate(outs):
            t.stop_gradient = False
            t._grad_node = node
            t._node_out_idx = i
            node.out_refs.append(weakref.ref(t))
        return out if multi else outs[0]


class LegacyPyLayer(PyLayer):
    pass


class backward_mode:
    """reference autograd/backward_mode.py: backward(tensors, grads) over
    the tape."""

    @staticmethod
    def backward(tensors, grad_tensors=None, retain_graph=False):
        tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
        grads = grad_tensors or [None] * len(tensors)
        for t, g in zip(tensors, grads):
            t.backward(g, retain_graph=retain_graph)
