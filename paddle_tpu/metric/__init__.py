"""Streaming metrics (reference: python/paddle/metric/metrics.py)."""
import numpy as np
import jax

from ..framework.core import Tensor

__all__ = ['Metric', 'Accuracy', 'Precision', 'Recall', 'Auc', 'accuracy',
           'auc']


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or 'acc'
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = (idx == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
        self.count += num
        res = [self.total[i] / max(self.count, 1) for i in range(len(self.topk))]
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return ['%s_top%d' % (self._name, k) for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or 'precision'
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or 'recall'
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve='ROC', num_thresholds=4095, name=None):
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._name = name or 'auc'
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p.reshape(-1) * self._num_thresholds).astype(np.int64),
                       0, self._num_thresholds)
        for b, lab in zip(bins, l):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1)
        self._stat_neg = np.zeros(self._num_thresholds + 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # integrate from highest threshold down
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp
    from ..framework.core import wrap_out
    p = input._data
    l = label._data
    if l.ndim == p.ndim:
        l = l.squeeze(-1)
    idx = jnp.argsort(-p, axis=-1)[..., :k]
    corr = jnp.any(idx == l[..., None], axis=-1)
    return wrap_out(jnp.mean(corr.astype(jnp.float32)))


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1, name=None):
    """Batch AUC via threshold buckets (reference operators/metrics/
    auc_op.cc; paddle.static.auc). input: [N, 2] class probs or [N, 1]
    positive-class scores; label: [N, 1] or [N] in {0, 1}. Returns the
    AUC value tensor (the reference additionally returns its stat
    states; the streaming variant lives in metric.Auc)."""
    import jax.numpy as jnp
    from ..framework.core import wrap_out
    p = input._data if hasattr(input, '_data') else jnp.asarray(input)
    l = label._data if hasattr(label, '_data') else jnp.asarray(label)
    if p.ndim == 2 and p.shape[1] == 2:
        pos = p[:, 1]
    else:
        pos = p.reshape(-1)
    l = l.reshape(-1).astype(jnp.float32)
    # bucketed TPR/FPR sweep (trapezoid rule), XLA-friendly fixed shapes
    buckets = jnp.clip((pos * num_thresholds).astype(jnp.int32), 0,
                       num_thresholds)
    oneh = jax.nn.one_hot(buckets, num_thresholds + 1, dtype=jnp.float32)
    pos_hist = jnp.sum(oneh * l[:, None], axis=0)
    neg_hist = jnp.sum(oneh * (1.0 - l)[:, None], axis=0)
    # cumulative from the HIGH-threshold end: tp(t) = positives above t
    tp = jnp.cumsum(pos_hist[::-1])
    fp = jnp.cumsum(neg_hist[::-1])
    tot_p = jnp.maximum(tp[-1], 1e-12)
    tot_n = jnp.maximum(fp[-1], 1e-12)
    tpr = jnp.concatenate([jnp.zeros(1), tp / tot_p])
    fpr = jnp.concatenate([jnp.zeros(1), fp / tot_n])
    area = jnp.sum((fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2.0)
    return wrap_out(area)
