from .optimizers import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,  # noqa: F401
                         Adagrad, Adadelta, RMSProp, Lamb, LarsMomentum,
                         Ftrl, Dpsgd, ProximalGD, ProximalAdagrad,
                         SparseAdam)
from . import lr  # noqa: F401
