from .optimizers import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,  # noqa: F401
                         Adagrad, Adadelta, RMSProp, Lamb)
from . import lr  # noqa: F401
