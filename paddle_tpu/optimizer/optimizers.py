"""Optimizers (reference: python/paddle/optimizer/*.py, operators/optimizers/).

Each optimizer defines a PURE update rule `_apply(p, g, slots, lr, t)` over
jax arrays. The eager `step()` runs it per-parameter; the functional
train-step compiler (framework/functional.py) lifts the same rule into the
jitted step so the whole update fuses into the compiled program — the
TPU-native replacement for per-op optimizer kernels (sgd_op.cc, adam_op.cc).
"""
import jax.numpy as jnp

from ..framework.core import Tensor, no_grad_guard
from .lr import LRScheduler

__all__ = ['Optimizer', 'SGD', 'Momentum', 'Adam', 'AdamW', 'Adamax',
           'Adagrad', 'Adadelta', 'RMSProp', 'Lamb', 'LarsMomentum',
           'Ftrl', 'Dpsgd', 'ProximalGD', 'ProximalAdagrad', 'SparseAdam']


def _is_low_precision(arr):
    return arr.dtype in (jnp.bfloat16, jnp.float16)


def _slot_zeros(p):
    """Optimizer state for bf16/fp16 params is stored in f32: the per-step
    EMA increments ((1-beta2)*g**2 at beta2=0.999 is ~0.1% of the running
    moment) fall below bf16's ~0.4% mantissa resolution, so low-precision
    moments freeze. The reference reaches the same place through its
    MasterParam/multi_precision path (operators/optimizers/adam_op.cu
    MultiPrecisionAdam); on TPU f32 state is simply the default."""
    d = p._data
    return jnp.zeros(d.shape, jnp.float32 if _is_low_precision(d) else d.dtype)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        if parameters is not None and not isinstance(parameters, (list, tuple)):
            parameters = list(parameters)
        self._parameter_list = list(parameters) if parameters is not None else None
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._slots = {}   # id(param) -> dict of slot arrays
        self._step_count = 0
        # reference multi_precision (MasterParam): keep an f32 master copy
        # of each bf16/fp16 param in the slots; the update rule runs on the
        # master and the stored param is its rounded shadow. Subclasses
        # whose signatures take multi_precision set this.
        self._multi_precision = False

    # -- lr -----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return self._lr

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using LRScheduler")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # -- slots --------------------------------------------------------------
    def _init_slots(self, p):
        """Return dict name->array of per-param optimizer state."""
        return {}

    def _get_slots(self, p):
        key = id(p)
        if key not in self._slots:
            slots = self._init_slots(p)
            if self._multi_precision and _is_low_precision(p._data):
                slots = dict(slots)
                slots['master'] = p._data.astype(jnp.float32)
            self._slots[key] = slots
        return self._slots[key]

    # -- core update rule (pure) -------------------------------------------
    def _apply(self, p, g, slots, lr, t):
        raise NotImplementedError

    def _update_operand(self, p, slots):
        """(master_or_None, value the update rule runs on)."""
        master = slots.get('master')
        return master, (master if master is not None else p._data)

    def _store_update(self, p, new_p, new_slots, master):
        """Write an update back: master (if any) keeps full precision, the
        stored param is its rounded shadow; dtypes never drift."""
        if master is not None:
            new_slots = dict(new_slots)
            new_slots['master'] = new_p
        p._data = new_p.astype(p._data.dtype)
        self._slots[id(p)] = new_slots

    def _decay_coeff(self):
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if hasattr(wd, '_coeff'):
            return wd._coeff
        return float(wd)

    def _apply_decoupled_decay(self):
        return False

    # -- public api ---------------------------------------------------------
    @no_grad_guard()
    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer created without parameters")
        params_grads = [(p, p.grad) for p in params
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        lr = self.get_lr()
        coeff = self._decay_coeff()
        for p, g in params_grads:
            slots = self._get_slots(p)
            master, pval = self._update_operand(p, slots)
            garr = g._data.astype(pval.dtype) if g._data.dtype != pval.dtype \
                else g._data
            if coeff and not self._apply_decoupled_decay():
                garr = garr + coeff * pval
            # per-param regularizer overrides global (reference semantics)
            if p.regularizer is not None:
                garr = p.regularizer._append(garr, pval)
            plr = lr * p.optimize_attr.get('learning_rate', 1.0)
            # name hint for rules with per-param behavior (e.g. LARS
            # weight-decay exclusion); static at jit trace time
            self._apply_param_name = getattr(p, 'name', None)
            new_p, new_slots = self._apply(pval, garr, slots, plr,
                                           self._step_count)
            if coeff and self._apply_decoupled_decay() and \
                    getattr(p, 'no_weight_decay', False) is False:
                new_p = new_p - plr * coeff * pval
            self._store_update(p, new_p, new_slots, master)

    def clear_grad(self, set_to_zero=True):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_grad()
                if set_to_zero and not p.stop_gradient:
                    # paddle parity: grads become zero tensors, so step()
                    # applies decay/momentum to every listed param — the
                    # reference behaves the same for zero-grad params
                    p._grad = Tensor(jnp.zeros_like(p._data))

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # -- state dict ---------------------------------------------------------
    def state_dict(self):
        state = {'step': self._step_count}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                for name, arr in self._get_slots(p).items():
                    state['%s_%s' % (p.name or 'param%d' % i, name)] = \
                        Tensor(arr)
        if isinstance(self._lr, LRScheduler):
            state['LR_Scheduler'] = self._lr.state_dict()
        return state

    def set_state_dict(self, state_dict):
        self._step_count = state_dict.get('step', 0)
        if isinstance(self._lr, LRScheduler) and 'LR_Scheduler' in state_dict:
            self._lr.set_state_dict(state_dict['LR_Scheduler'])
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                slots = self._get_slots(p)
                for name in list(slots.keys()):
                    key = '%s_%s' % (p.name or 'param%d' % i, name)
                    if key in state_dict:
                        v = state_dict[key]
                        slots[name] = v._data if isinstance(v, Tensor) \
                            else jnp.asarray(v)

    set_dict = set_state_dict


class SGD(Optimizer):
    def _apply(self, p, g, slots, lr, t):
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov
        self._multi_precision = multi_precision

    def _init_slots(self, p):
        return {'velocity': _slot_zeros(p)}

    def _apply(self, p, g, slots, lr, t):
        v = self._momentum * slots['velocity'] + g
        if self._nesterov:
            update = g + self._momentum * v
        else:
            update = v
        return p - lr * update, {'velocity': v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._multi_precision = multi_precision

    def _init_slots(self, p):
        return {'moment1': _slot_zeros(p),
                'moment2': _slot_zeros(p)}

    def _apply(self, p, g, slots, lr, t):
        b1 = self._beta1() if callable(self._beta1) else self._beta1
        b2 = self._beta2() if callable(self._beta2) else self._beta2
        m = b1 * slots['moment1'] + (1 - b1) * g
        v = b2 * slots['moment2'] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return new_p, {'moment1': m, 'moment2': v}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip,
                         multi_precision=multi_precision)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_decoupled_decay(self):
        return True

    @no_grad_guard()
    def step(self):
        # decoupled decay with optional per-param predicate
        params = self._parameter_list
        params_grads = [(p, p.grad) for p in params
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        lr = self.get_lr()
        coeff = self._decay_coeff()
        for p, g in params_grads:
            slots = self._get_slots(p)
            master, pval = self._update_operand(p, slots)
            garr = g._data.astype(pval.dtype) if g._data.dtype != pval.dtype \
                else g._data
            plr = lr * p.optimize_attr.get('learning_rate', 1.0)
            decay = coeff
            if self._apply_decay_param_fun is not None and \
                    not self._apply_decay_param_fun(p.name):
                decay = 0.0
            if decay:
                pval = pval * (1.0 - plr * decay)
            new_p, new_slots = self._apply(pval, garr, slots, plr,
                                           self._step_count)
            self._store_update(p, new_p, new_slots, master)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {'moment': _slot_zeros(p),
                'inf_norm': _slot_zeros(p)}

    def _apply(self, p, g, slots, lr, t):
        m = self._beta1 * slots['moment'] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots['inf_norm'], jnp.abs(g))
        new_p = p - (lr / (1 - self._beta1 ** t)) * m / (u + self._epsilon)
        return new_p, {'moment': m, 'inf_norm': u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _init_slots(self, p):
        return {'moment': _slot_zeros(p) + self._init_val}

    def _apply(self, p, g, slots, lr, t):
        mom = slots['moment'] + g * g
        return p - lr * g / (jnp.sqrt(mom) + self._epsilon), {'moment': mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _init_slots(self, p):
        return {'avg_squared_grad': _slot_zeros(p),
                'avg_squared_update': _slot_zeros(p)}

    def _apply(self, p, g, slots, lr, t):
        asg = self._rho * slots['avg_squared_grad'] + (1 - self._rho) * g * g
        update = -jnp.sqrt((slots['avg_squared_update'] + self._epsilon) /
                           (asg + self._epsilon)) * g
        asu = self._rho * slots['avg_squared_update'] + \
            (1 - self._rho) * update * update
        return p + lr * update, {'avg_squared_grad': asg,
                                 'avg_squared_update': asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, p):
        return {'mean_square': _slot_zeros(p),
                'mean_grad': _slot_zeros(p),
                'momentum': _slot_zeros(p)}

    def _apply(self, p, g, slots, lr, t):
        ms = self._rho * slots['mean_square'] + (1 - self._rho) * g * g
        mg = slots['mean_grad']
        if self._centered:
            mg = self._rho * mg + (1 - self._rho) * g
            denom = ms - mg * mg + self._epsilon
        else:
            denom = ms + self._epsilon
        mom = self._momentum * slots['momentum'] + lr * g / jnp.sqrt(denom)
        return p - mom, {'mean_square': ms, 'mean_grad': mg, 'momentum': mom}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_slots(self, p):
        return {'moment1': _slot_zeros(p),
                'moment2': _slot_zeros(p)}

    def _apply(self, p, g, slots, lr, t):
        m = self._beta1 * slots['moment1'] + (1 - self._beta1) * g
        v = self._beta2 * slots['moment2'] + (1 - self._beta2) * g * g
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = self._lamb_wd
        update = r + wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return p - lr * trust * update, {'moment1': m, 'moment2': v}


class LarsMomentum(Optimizer):
    """LARS (layer-wise adaptive rate scaling) momentum.

    Parity: paddle/fluid/operators/optimizers/lars_momentum_op.cc +
    fleet meta_optimizers/lars_optimizer.py. local_lr scales the update by
    ||w|| / (||g|| + wd*||w||) per layer for large-batch stability.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = tuple(exclude_from_weight_decay or ())

    def _init_slots(self, p):
        return {'velocity': _slot_zeros(p)}

    def _excluded(self):
        name = getattr(self, '_apply_param_name', None) or ''
        return any(tok in name for tok in self._exclude)

    def _apply(self, p, g, slots, lr, t):
        if self._excluded():
            # excluded params (bn scales, biases): plain momentum, no
            # LARS scaling or weight decay (reference lars_momentum_op)
            v = self._momentum * slots['velocity'] + lr * g
            return p - v, {'velocity': v}
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        wd = self._lars_wd
        denom = g_norm + wd * w_norm + self._epsilon
        local_lr = jnp.where(
            (w_norm > 0) & (denom > 0),
            lr * self._lars_coeff * w_norm / jnp.maximum(denom, 1e-30), lr)
        v = self._momentum * slots['velocity'] + local_lr * (g + wd * p)
        return p - v, {'velocity': v}


class Ftrl(Optimizer):
    """FTRL-Proximal (reference: operators/optimizers/ftrl_op.cc).
    Accumulates squared grads (n) and a linear term (z); the closed-form
    per-coordinate update applies L1/L2 shrinkage."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._l1 = float(l1)
        self._l2 = float(l2)
        self._lr_power = float(lr_power)

    def _init_slots(self, p):
        return {'squared': _slot_zeros(p),
                'linear': _slot_zeros(p)}

    def _apply(self, p, g, slots, lr, t):
        n, z = slots['squared'], slots['linear']
        n_new = n + g * g
        pw = -self._lr_power
        sigma = (n_new ** pw - n ** pw) / lr
        z_new = z + g - sigma * p
        new_p = jnp.where(
            jnp.abs(z_new) <= self._l1,
            jnp.zeros_like(p),
            (jnp.sign(z_new) * self._l1 - z_new) /
            (n_new ** pw / lr + 2.0 * self._l2))
        return new_p, {'squared': n_new, 'linear': z_new}


class Dpsgd(Optimizer):
    """Differentially-private SGD (reference: operators/optimizers/
    dpsgd_op.cc): per-update L2 clipping + calibrated gaussian noise."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, parameters=None, weight_decay=None,
                 grad_clip=None, seed=0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._clip = float(clip)
        self._batch = float(batch_size)
        self._sigma = float(sigma)
        self._seed = int(seed)

    def _apply(self, p, g, slots, lr, t):
        import jax
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        scale = jnp.minimum(1.0, self._clip / jnp.maximum(g_norm, 1e-30))
        key = jax.random.fold_in(
            jax.random.PRNGKey(self._seed),
            jnp.asarray(t, jnp.int32).astype(jnp.uint32))
        noise = jax.random.normal(key, g.shape, g.dtype) * \
            (self._sigma * self._clip)
        g_priv = (g * scale + noise / self._batch)
        return p - lr * g_priv, {}


class ProximalGD(Optimizer):
    """Proximal gradient descent with L1/L2 (reference:
    operators/optimizers/proximal_gd_op.cc)."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._l1 = float(l1)
        self._l2 = float(l2)

    def _prox(self, w, lr):
        shrunk = jnp.sign(w) * jnp.maximum(
            jnp.abs(w) - lr * self._l1, 0.0)
        return shrunk / (1.0 + lr * self._l2)

    def _apply(self, p, g, slots, lr, t):
        return self._prox(p - lr * g, lr), {}


class ProximalAdagrad(ProximalGD):
    """Adagrad step + proximal L1/L2 shrinkage (reference:
    operators/optimizers/proximal_adagrad_op.cc)."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, l1, l2, parameters, weight_decay,
                         grad_clip)
        self._epsilon = float(epsilon)

    def _init_slots(self, p):
        return {'moment': _slot_zeros(p)}

    def _apply(self, p, g, slots, lr, t):
        mom = slots['moment'] + g * g
        eff = lr / (jnp.sqrt(mom) + self._epsilon)
        return self._prox(p - eff * g, lr), {'moment': mom}


class SparseAdam(Adam):
    """Row-sparse-aware Adam (reference: adam_op.cc lazy_mode): moments
    update only where the grad is nonzero, so untouched embedding rows
    keep their state frozen instead of decaying every step."""

    def _apply(self, p, g, slots, lr, t):
        touched = jnp.any(g != 0, axis=tuple(range(1, g.ndim)),
                          keepdims=True) if g.ndim > 1 else (g != 0)
        b1 = self._beta1() if callable(self._beta1) else self._beta1
        b2 = self._beta2() if callable(self._beta2) else self._beta2
        m = jnp.where(touched, b1 * slots['moment1'] + (1 - b1) * g,
                      slots['moment1'])
        v = jnp.where(touched, b2 * slots['moment2'] + (1 - b2) * g * g,
                      slots['moment2'])
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return p - jnp.where(touched, upd, 0.0), \
            {'moment1': m, 'moment2': v}
