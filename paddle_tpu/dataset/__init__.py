"""Legacy reader-generator dataset API (reference: python/paddle/dataset/
— uci_housing.train(), imdb.word_dict(), mnist.train(), ... each returning
a no-arg callable yielding samples). Thin adapters over the class-based
datasets in paddle_tpu.vision.datasets / paddle_tpu.text.datasets.
"""
import types as _types

__all__ = ['uci_housing', 'imdb', 'movielens', 'mnist', 'cifar', 'common']


def _reader_from(dataset_factory):
    def reader():
        ds = dataset_factory()
        for i in range(len(ds)):
            yield tuple(ds[i]) if isinstance(ds[i], (tuple, list)) \
                else (ds[i],)
    return reader


def _module(name, **fns):
    m = _types.ModuleType(__name__ + '.' + name)
    for k, v in fns.items():
        setattr(m, k, v)
    return m


def _uci_train(data_file=None):
    from ..text.datasets import UCIHousing
    return _reader_from(lambda: UCIHousing(data_file=data_file,
                                           mode='train'))


def _uci_test(data_file=None):
    from ..text.datasets import UCIHousing
    return _reader_from(lambda: UCIHousing(data_file=data_file,
                                           mode='test'))


uci_housing = _module('uci_housing', train=_uci_train, test=_uci_test)


def _imdb_word_dict(data_file=None, cutoff=150):
    from ..text.datasets import Imdb
    return Imdb(data_file=data_file, mode='train', cutoff=cutoff).word_idx


def _imdb_train(word_idx=None, data_file=None):
    from ..text.datasets import Imdb
    return _reader_from(lambda: Imdb(data_file=data_file, mode='train',
                                     word_idx=word_idx))


def _imdb_test(word_idx=None, data_file=None):
    from ..text.datasets import Imdb
    return _reader_from(lambda: Imdb(data_file=data_file, mode='test',
                                     word_idx=word_idx))


imdb = _module('imdb', word_dict=_imdb_word_dict, train=_imdb_train,
               test=_imdb_test)


def _ml_train(data_file=None):
    from ..text.datasets import Movielens
    return _reader_from(lambda: Movielens(data_file=data_file,
                                          mode='train'))


def _ml_test(data_file=None):
    from ..text.datasets import Movielens
    return _reader_from(lambda: Movielens(data_file=data_file, mode='test'))


movielens = _module('movielens', train=_ml_train, test=_ml_test)


def _mnist_reader(mode):
    def factory(image_path=None, label_path=None):
        from ..vision.datasets import MNIST
        return _reader_from(lambda: MNIST(image_path=image_path,
                                          label_path=label_path, mode=mode))
    return factory


mnist = _module('mnist', train=_mnist_reader('train'),
                test=_mnist_reader('test'))


def _cifar_reader(cls_name, mode):
    def factory(data_file=None):
        from ..vision import datasets as vd
        cls = getattr(vd, cls_name)
        return _reader_from(lambda: cls(data_file=data_file, mode=mode))
    return factory


cifar = _module('cifar',
                train10=_cifar_reader('Cifar10', 'train'),
                test10=_cifar_reader('Cifar10', 'test'),
                train100=_cifar_reader('Cifar100', 'train'),
                test100=_cifar_reader('Cifar100', 'test'))


def _cluster_files_reader(files_pattern, trainer_count, trainer_id):
    """reference dataset/common.py cluster_files_reader parity."""
    import glob

    def reader():
        files = sorted(glob.glob(files_pattern))
        my = files[trainer_id::trainer_count]
        for fn in my:
            with open(fn) as f:
                for line in f:
                    yield line.rstrip('\n')
    return reader


common = _module('common', cluster_files_reader=_cluster_files_reader)
