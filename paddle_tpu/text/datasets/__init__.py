"""NLP datasets (reference: python/paddle/text/datasets/ — imdb.py,
conll05.py, movielens.py, uci_housing.py, wmt14.py, wmt16.py).

Zero-egress environment: every loader parses the reference's standard
archive layout from a LOCAL file (`data_file=`, or
$PADDLE_TPU_DATA_HOME/<name>/); there is no downloader. `FakeTextDataset` /
`FakeLMDataset` provide deterministic synthetic data for tests/benches.
"""
import gzip
import io
import os
import re
import tarfile
import zipfile

import numpy as np

from ...io.dataset import Dataset

__all__ = [
    'Imikolov','Imdb', 'Conll05st', 'Movielens', 'UCIHousing', 'WMT14', 'WMT16',
           'FakeTextDataset', 'FakeLMDataset', 'MovieInfo', 'UserInfo']


def _data_home():
    return os.environ.get('PADDLE_TPU_DATA_HOME',
                          os.path.expanduser('~/.cache/paddle_tpu'))


def _resolve(data_file, *default_parts):
    path = data_file or os.path.join(_data_home(), *default_parts)
    if not os.path.exists(path):
        raise FileNotFoundError(
            '%s not found (zero-egress env: place the standard archive '
            'there or pass data_file=)' % path)
    return path


class FakeTextDataset(Dataset):
    """Deterministic synthetic token-classification data."""

    def __init__(self, num_samples=1024, seq_len=128, vocab_size=30522,
                 num_classes=2, seed=0):
        rng = np.random.RandomState(seed)
        self.tokens = rng.randint(0, vocab_size, size=(num_samples, seq_len))
        self.labels = rng.randint(0, num_classes, size=num_samples)

    def __getitem__(self, idx):
        return (self.tokens[idx].astype(np.int64),
                np.asarray(self.labels[idx], np.int64))

    def __len__(self):
        return len(self.labels)


class FakeLMDataset(Dataset):
    """Synthetic causal-LM data: input ids + shifted labels."""

    def __init__(self, num_samples=1024, seq_len=512, vocab_size=50304,
                 seed=0):
        self.num_samples = num_samples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self._seeds = np.random.RandomState(seed).randint(
            0, 2 ** 31 - 1, size=num_samples)

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seeds[idx])
        ids = rng.randint(0, self.vocab_size, size=self.seq_len + 1)
        return ids[:-1].astype(np.int64), ids[1:].astype(np.int64)

    def __len__(self):
        return self.num_samples


class UCIHousing(Dataset):
    """Boston housing regression (reference text/datasets/uci_housing.py:
    14 columns, feature normalization, 80/20 train split)."""

    def __init__(self, data_file=None, mode='train', download=False):
        path = _resolve(data_file, 'uci_housing', 'housing.data')
        raw = np.loadtxt(path).astype(np.float32)
        feats = raw[:, :-1]
        feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
        n_train = int(0.8 * len(raw))
        if mode == 'train':
            self.x, self.y = feats[:n_train], raw[:n_train, -1:]
        else:
            self.x, self.y = feats[n_train:], raw[n_train:, -1:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


_IMDB_TOKEN = re.compile(r"[a-z0-9']+")


class Imdb(Dataset):
    """IMDB sentiment (reference text/datasets/imdb.py): parses the
    aclImdb_v1.tar.gz layout (aclImdb/<mode>/{pos,neg}/*.txt). The
    frequency-cutoff word dict is built over train AND test docs
    (reference imdb.py word-dict pattern covers both splits); pass
    word_idx= to reuse an external dict. Yields (ids, label), label
    0=pos 1=neg (reference convention)."""

    def __init__(self, data_file=None, mode='train', cutoff=150,
                 word_idx=None, download=False):
        path = _resolve(data_file, 'imdb', 'aclImdb_v1.tar.gz')
        # ONE decompression pass: gzip has no random access, so cache the
        # token lists of all needed members up front
        pat = re.compile(r'aclImdb/(train|test)/(pos|neg)/.*\.txt$')
        by_member = {}
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if m.isfile() and pat.match(m.name):
                    text = tf.extractfile(m).read().decode(
                        'utf-8', 'ignore').lower()
                    by_member[m.name] = _IMDB_TOKEN.findall(text)
        self.word_idx = word_idx if word_idx is not None \
            else self._build_word_dict(by_member.values(), cutoff)
        self.docs, self.labels = [], []
        # reference order: pos first (label 0), then neg (label 1)
        unk = self.word_idx['<unk>']
        for label, sub in enumerate(('pos', 'neg')):
            prefix = 'aclImdb/%s/%s/' % (mode, sub)
            for name in sorted(by_member):
                if name.startswith(prefix):
                    self.docs.append(np.asarray(
                        [self.word_idx.get(t, unk)
                         for t in by_member[name]], np.int64))
                    self.labels.append(label)

    @staticmethod
    def _build_word_dict(token_lists, cutoff):
        freq = {}
        for tokens in token_lists:
            for t in tokens:
                freq[t] = freq.get(t, 0) + 1
        words = [w for w, c in freq.items() if c > cutoff]
        # deterministic: sort by (-freq, word), ids from 0; <unk> last
        words.sort(key=lambda w: (-freq[w], w))
        word_idx = {w: i for i, w in enumerate(words)}
        word_idx['<unk>'] = len(words)
        return word_idx

    def __getitem__(self, idx):
        return self.docs[idx], np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference text/datasets/conll05.py): parses the
    conll05st-tests tarball (words/props files gzipped inside), emitting
    per-verb samples (word_ids, ctx_n2/n1/0/p1/p2, verb_id, mark, labels)
    keyed by user-supplied word/verb/target dict files."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None,
                 download=False):
        path = _resolve(data_file, 'conll05st', 'conll05st-tests.tar.gz')
        self.word_dict = self._load_dict(word_dict_file)
        self.verb_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_dict(target_dict_file)
        self._auto_dicts = {}
        words_name = 'conll05st-release/test.wsj/words/test.wsj.words.gz'
        props_name = 'conll05st-release/test.wsj/props/test.wsj.props.gz'
        with tarfile.open(path) as tf:
            words_txt = gzip.decompress(
                tf.extractfile(words_name).read()).decode()
            props_txt = gzip.decompress(
                tf.extractfile(props_name).read()).decode()
        self.samples = list(self._parse(words_txt, props_txt))

    @staticmethod
    def _load_dict(f):
        if f is None:
            return None
        with open(f) as fh:
            return {line.strip(): i for i, line in enumerate(fh)
                    if line.strip()}

    @staticmethod
    def _sentences(words_txt, props_txt):
        sent_w, sent_p = [], []
        wlines = words_txt.splitlines()
        plines = props_txt.splitlines()
        for wl, pl in zip(wlines, plines):
            if not wl.strip():
                if sent_w:
                    yield sent_w, sent_p
                sent_w, sent_p = [], []
                continue
            sent_w.append(wl.split()[0])
            sent_p.append(pl.split())
        if sent_w:
            yield sent_w, sent_p

    def _parse(self, words_txt, props_txt):
        for words, props in self._sentences(words_txt, props_txt):
            if not props or len(props[0]) < 2:
                continue
            n_verbs = len(props[0]) - 1
            verbs = [p[0] for p in props if p[0] != '-']
            for v in range(n_verbs):
                # column v+1 holds this predicate's bracketed SRL tags
                labels = self._col_to_bio([p[v + 1] for p in props])
                verb_word = verbs[v] if v < len(verbs) else '-'
                yield self._featurize(words, verb_word, labels)

    # dicts built deterministically from the data when no dict files are
    # given (first-seen order) — never from hash(), which varies per
    # process under PYTHONHASHSEED randomization
    def _auto_id(self, kind, w):
        d = self._auto_dicts.setdefault(kind, {})
        if w not in d:
            d[w] = len(d)
        return d[w]

    @staticmethod
    def _col_to_bio(col):
        out, cur = [], None
        for tag in col:
            m = re.match(r'\(([^*()]+)\*', tag)
            if m:
                cur = m.group(1)
                out.append('B-' + cur)
            elif cur is not None:
                out.append('I-' + cur)
            else:
                out.append('O')
            if ')' in tag:
                cur = None
        return out

    def _featurize(self, words, verb, labels):
        lower = [w.lower() for w in words]
        # the predicate position comes from the LABEL column (B-V), not a
        # surface-word match: props column 0 holds lemmas which often
        # differ from the surface form (reference uses the label column)
        try:
            v_pos = labels.index('B-V')
        except ValueError:
            v_pos = 0
        n = len(words)

        def ctx(off):
            i = min(max(v_pos + off, 0), n - 1)
            return lower[i]

        def wid(w, d, kind):
            if d is None:
                return self._auto_id(kind, w)
            return d.get(w, d.get('<unk>', len(d)))

        word_ids = np.asarray([wid(w, self.word_dict, 'word')
                               for w in lower], np.int64)
        ctx_ids = [np.asarray([wid(ctx(off), self.word_dict, 'word')] * n,
                              np.int64)
                   for off in (-2, -1, 0, 1, 2)]
        verb_id = np.asarray([wid(verb.lower(), self.verb_dict, 'verb')] * n,
                             np.int64)
        mark = np.zeros(n, np.int64)
        mark[v_pos] = 1
        label_ids = np.asarray([wid(l, self.label_dict, 'label')
                                for l in labels], np.int64)
        return (word_ids, *ctx_ids, verb_id, mark, label_ids)

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [self.index,
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return '<MovieInfo id(%d), title(%s), categories(%s)>' % (
            self.index, self.title, self.categories)


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = int(age)
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __repr__(self):
        return '<UserInfo id(%d), gender(%s), age(%d), job(%d)>' % (
            self.index, 'M' if self.is_male else 'F', self.age, self.job_id)


class Movielens(Dataset):
    """MovieLens-1M ratings (reference text/datasets/movielens.py): parses
    ml-1m.zip ({movies,users,ratings}.dat with :: separators), yields
    [user features..., movie features..., rating]."""

    def __init__(self, data_file=None, mode='train', test_ratio=0.1,
                 rand_seed=0, download=False):
        path = _resolve(data_file, 'movielens', 'ml-1m.zip')
        self.movie_info, self.categories_dict, self.title_dict = \
            self._load_movies(path)
        self.user_info = self._load_users(path)
        rng = np.random.RandomState(rand_seed)
        self.data = []
        with zipfile.ZipFile(path) as zf:
            name = [n for n in zf.namelist()
                    if n.endswith('ratings.dat')][0]
            with io.TextIOWrapper(zf.open(name),
                                  encoding='latin-1') as f:
                for line in f:
                    uid, mid, rating, _ = line.strip().split('::')
                    uid, mid = int(uid), int(mid)
                    if uid not in self.user_info or \
                            mid not in self.movie_info:
                        continue
                    is_test = rng.rand() < test_ratio
                    if (mode == 'test') == is_test:
                        usr = self.user_info[uid].value()
                        mov = self.movie_info[mid].value(
                            self.categories_dict, self.title_dict)
                        self.data.append(usr + mov + [float(rating)])

    @staticmethod
    def _load_movies(path):
        movie_info, categories, titles = {}, {}, {}
        with zipfile.ZipFile(path) as zf:
            name = [n for n in zf.namelist() if n.endswith('movies.dat')][0]
            with io.TextIOWrapper(zf.open(name), encoding='latin-1') as f:
                for line in f:
                    mid, title, cats = line.strip().split('::')
                    cats = cats.split('|')
                    title = re.sub(r'\(\d{4}\)$', '', title).strip()
                    for c in cats:
                        categories.setdefault(c, len(categories))
                    for w in title.split():
                        titles.setdefault(w.lower(), len(titles))
                    movie_info[int(mid)] = MovieInfo(mid, cats, title)
        return movie_info, categories, titles

    @staticmethod
    def _load_users(path):
        users = {}
        with zipfile.ZipFile(path) as zf:
            name = [n for n in zf.namelist() if n.endswith('users.dat')][0]
            with io.TextIOWrapper(zf.open(name), encoding='latin-1') as f:
                for line in f:
                    uid, gender, age, job, _ = line.strip().split('::')
                    users[int(uid)] = UserInfo(uid, gender, age, job)
        return users

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _WMTBase(Dataset):
    START = '<s>'
    END = '<e>'
    UNK = '<unk>'

    def _build_ids(self, src_lines, trg_lines, src_dict, trg_dict):
        unk_s = src_dict[self.UNK]
        unk_t = trg_dict[self.UNK]
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for s, t in zip(src_lines, trg_lines):
            src = [src_dict.get(w, unk_s) for w in s.split()]
            trg_words = t.split()
            trg = [trg_dict[self.START]] + \
                [trg_dict.get(w, unk_t) for w in trg_words]
            trg_next = [trg_dict.get(w, unk_t) for w in trg_words] + \
                [trg_dict[self.END]]
            self.src_ids.append(np.asarray(src, np.int64))
            self.trg_ids.append(np.asarray(trg, np.int64))
            self.trg_ids_next.append(np.asarray(trg_next, np.int64))

    def __getitem__(self, idx):
        return (self.src_ids[idx], self.trg_ids[idx],
                self.trg_ids_next[idx])

    def __len__(self):
        return len(self.src_ids)


class WMT14(_WMTBase):
    """WMT14 en→fr (reference text/datasets/wmt14.py): parses the
    wmt14.tgz layout (<mode>/<name>.src|.trg parallel files + dict files
    train.dict.src/trg of the top dict_size words)."""

    def __init__(self, data_file=None, mode='train', dict_size=30000,
                 download=False):
        path = _resolve(data_file, 'wmt14', 'wmt14.tgz')
        with tarfile.open(path) as tf:
            names = tf.getnames()
            self.src_dict = self._read_dict(tf, names, 'src', dict_size)
            self.trg_dict = self._read_dict(tf, names, 'trg', dict_size)
            src_lines, trg_lines = [], []
            for n in sorted(names):
                if ('/%s/' % mode) in n and n.endswith('.src'):
                    src_lines += tf.extractfile(n).read().decode(
                        'utf-8', 'ignore').splitlines()
                    trg = n[:-4] + '.trg'
                    trg_lines += tf.extractfile(trg).read().decode(
                        'utf-8', 'ignore').splitlines()
        self._build_ids(src_lines, trg_lines, self.src_dict, self.trg_dict)

    def _read_dict(self, tf, names, side, dict_size):
        dict_name = [n for n in names
                     if n.endswith('train.dict.%s' % side)]
        d = {self.START: 0, self.END: 1, self.UNK: 2}
        if dict_name:
            words = tf.extractfile(dict_name[0]).read().decode(
                'utf-8', 'ignore').splitlines()
            for w in words:
                w = w.strip()
                if w and w not in d and len(d) < dict_size:
                    d[w] = len(d)
        return d


class WMT16(_WMTBase):
    """WMT16 en↔de (reference text/datasets/wmt16.py): parses wmt16.tar.gz
    (wmt16/{train,test,val}.{src_lang}-{trg_lang} pair files +
    vocab_{lang}.txt), building dicts of size src/trg_dict_size."""

    def __init__(self, data_file=None, mode='train', src_dict_size=-1,
                 trg_dict_size=-1, lang='en', download=False):
        path = _resolve(data_file, 'wmt16', 'wmt16.tar.gz')
        trg_lang = 'de' if lang == 'en' else 'en'
        with tarfile.open(path) as tf:
            names = tf.getnames()
            self.src_dict = self._read_vocab(tf, names, lang, src_dict_size)
            self.trg_dict = self._read_vocab(tf, names, trg_lang,
                                             trg_dict_size)
            pair = [n for n in names
                    if n.endswith('wmt16/%s' % mode)
                    or n.endswith('wmt16/%s.%s-%s' % (mode, lang, trg_lang))]
            src_lines, trg_lines = [], []
            # pair files are 'en<TAB>de': column 0 is English, so for
            # lang='de' the source is column 1 (reference wmt16 src_col
            # swap)
            src_col = 0 if lang == 'en' else 1
            if pair:
                for line in tf.extractfile(pair[0]).read().decode(
                        'utf-8', 'ignore').splitlines():
                    parts = line.split('\t')
                    if len(parts) == 2:
                        src_lines.append(parts[src_col])
                        trg_lines.append(parts[1 - src_col])
        self._build_ids(src_lines, trg_lines, self.src_dict, self.trg_dict)

    def _read_vocab(self, tf, names, lang, size):
        d = {self.START: 0, self.END: 1, self.UNK: 2}
        vocab = [n for n in names if n.endswith('vocab_%s.txt' % lang)]
        if vocab:
            for w in tf.extractfile(vocab[0]).read().decode(
                    'utf-8', 'ignore').splitlines():
                w = w.strip()
                if w and w not in d and (size < 0 or len(d) < size):
                    d[w] = len(d)
        return d


class Imikolov(Dataset):
    """PTB n-gram dataset (reference text/datasets/imikolov.py over
    simple-examples.tgz ./data/ptb.{train,valid}.txt): builds the word
    dict from train+valid, yields n-grams ('NGRAM' type) or (src, trg)
    sequence pairs ('SEQ')."""

    def __init__(self, data_file=None, data_type='NGRAM', window_size=5,
                 mode='train', min_word_freq=50, download=False):
        assert data_type in ('NGRAM', 'SEQ')
        path = _resolve(data_file, 'imikolov', 'simple-examples.tgz')
        member = 'ptb.%s.txt' % ('train' if mode == 'train' else 'valid')
        texts = {}
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if m.name.endswith(('ptb.train.txt', 'ptb.valid.txt')):
                    texts[m.name] = tf.extractfile(m).read().decode(
                        'utf-8', 'ignore')
        freq = {}
        for body in texts.values():
            for w in body.split():
                freq[w] = freq.get(w, 0) + 1
        words = sorted((w for w, c in freq.items()
                        if c >= min_word_freq and w != '<unk>'),
                       key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        self.word_idx['<unk>'] = len(self.word_idx)
        self.word_idx.setdefault('<s>', len(self.word_idx))
        self.word_idx.setdefault('<e>', len(self.word_idx))
        unk = self.word_idx['<unk>']

        body = next((t for n, t in texts.items() if n.endswith(member)),
                    '')
        self.data = []
        for line in body.splitlines():
            toks = ['<s>'] + line.split() + ['<e>']
            ids = [self.word_idx.get(w, unk) for w in toks]
            if data_type == 'NGRAM':
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(
                            np.asarray(ids[i - window_size:i], np.int64))
            else:
                if len(ids) > 2:
                    self.data.append((np.asarray(ids[:-1], np.int64),
                                      np.asarray(ids[1:], np.int64)))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]
