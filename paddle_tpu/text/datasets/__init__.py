"""NLP datasets (reference: python/paddle/text/datasets/). Zero-egress: file
loaders for local copies + FakeTextDataset for tests/benches."""
import os

import numpy as np

from ...io.dataset import Dataset

__all__ = ['Imdb', 'Conll05st', 'Movielens', 'UCIHousing', 'WMT14', 'WMT16',
           'FakeTextDataset', 'FakeLMDataset']


class FakeTextDataset(Dataset):
    """Deterministic synthetic token-classification data."""

    def __init__(self, num_samples=1024, seq_len=128, vocab_size=30522,
                 num_classes=2, seed=0):
        rng = np.random.RandomState(seed)
        self.tokens = rng.randint(0, vocab_size, size=(num_samples, seq_len))
        self.labels = rng.randint(0, num_classes, size=num_samples)

    def __getitem__(self, idx):
        return (self.tokens[idx].astype(np.int64),
                np.asarray(self.labels[idx], np.int64))

    def __len__(self):
        return len(self.labels)


class FakeLMDataset(Dataset):
    """Synthetic causal-LM data: input ids + shifted labels."""

    def __init__(self, num_samples=1024, seq_len=512, vocab_size=50304,
                 seed=0):
        self.num_samples = num_samples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self._seeds = np.random.RandomState(seed).randint(
            0, 2 ** 31 - 1, size=num_samples)

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seeds[idx])
        ids = rng.randint(0, self.vocab_size, size=self.seq_len + 1)
        return ids[:-1].astype(np.int64), ids[1:].astype(np.int64)

    def __len__(self):
        return self.num_samples


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode='train', download=True):
        base = os.environ.get('PADDLE_TPU_DATA_HOME',
                              os.path.expanduser('~/.cache/paddle_tpu'))
        path = data_file or os.path.join(base, 'uci_housing', 'housing.data')
        if not os.path.exists(path):
            raise FileNotFoundError(
                "uci housing data not found at %s (zero-egress)" % path)
        raw = np.loadtxt(path).astype(np.float32)
        feats = raw[:, :-1]
        feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
        n_train = int(0.8 * len(raw))
        if mode == 'train':
            self.x, self.y = feats[:n_train], raw[:n_train, -1:]
        else:
            self.x, self.y = feats[n_train:], raw[n_train:, -1:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class _LocalFileTextDataset(Dataset):
    REQUIRED = 'dataset archive'

    def __init__(self, *a, **k):
        raise FileNotFoundError(
            "%s requires a local copy (zero-egress env); use "
            "FakeTextDataset/FakeLMDataset for tests" % type(self).__name__)


class Imdb(_LocalFileTextDataset):
    pass


class Conll05st(_LocalFileTextDataset):
    pass


class Movielens(_LocalFileTextDataset):
    pass


class WMT14(_LocalFileTextDataset):
    pass


class WMT16(_LocalFileTextDataset):
    pass
