"""BERT/ERNIE-style encoder (capability target of BASELINE config 3; the
reference serves this via PaddleNLP on top of nn.TransformerEncoder —
python/paddle/nn/layer/transformer.py)."""
from ... import nn
from ...tensor import manipulation as M
from ...framework.core import Tensor

import jax.numpy as jnp

__all__ = ['BertModel', 'BertForSequenceClassification',
           'BertForPretraining', 'ErnieModel',
           'ErnieForSequenceClassification', 'ErnieForPretraining',
           'ernie_1_0']


class BertEmbeddings(nn.Layer):
    def __init__(self, vocab_size, hidden_size, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1):
        super().__init__()
        self.word_embeddings = nn.Embedding(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(max_position_embeddings,
                                                hidden_size)
        self.token_type_embeddings = nn.Embedding(type_vocab_size, hidden_size)
        self.layer_norm = nn.LayerNorm(hidden_size)
        self.dropout = nn.Dropout(hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(seq_len, dtype=jnp.int64)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros_like(input_ids._data))
        emb = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids) + \
            self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertPooler(nn.Layer):
    def __init__(self, hidden_size):
        super().__init__()
        self.dense = nn.Linear(hidden_size, hidden_size)
        self.activation = nn.Tanh()

    def forward(self, hidden_states):
        return self.activation(self.dense(hidden_states[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act='gelu',
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, pad_token_id=0):
        super().__init__()
        self.pad_token_id = pad_token_id
        self.embeddings = BertEmbeddings(vocab_size, hidden_size,
                                         max_position_embeddings,
                                         type_vocab_size, hidden_dropout_prob)
        enc_layer = nn.TransformerEncoderLayer(
            hidden_size, num_attention_heads, intermediate_size,
            dropout=hidden_dropout_prob, activation=hidden_act,
            attn_dropout=attention_probs_dropout_prob, act_dropout=0.0)
        self.encoder = nn.TransformerEncoder(enc_layer, num_hidden_layers)
        self.pooler = BertPooler(hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is None:
            mask = (input_ids._data != self.pad_token_id)
            attention_mask = Tensor(
                jnp.where(mask, 0.0, -1e9)[:, None, None, :].astype(jnp.float32))
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        encoded = self.encoder(emb, attention_mask)
        pooled = self.pooler(encoded)
        return encoded, pooled


class BertForSequenceClassification(nn.Layer):
    def __init__(self, bert=None, num_classes=2, dropout=0.1, **bert_kwargs):
        super().__init__()
        self.bert = bert or BertModel(**bert_kwargs)
        hidden = self.bert.pooler.dense._out_features
        self.dropout = nn.Dropout(dropout)
        self.classifier = nn.Linear(hidden, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForPretraining(nn.Layer):
    """MLM + NSP heads.

    fused_mlm=True switches the TRAINING forward to return the
    transformed hidden states instead of MLM logits, and loss() fuses
    the vocab-wide decoder matmul into a chunked cross-entropy
    (F.linear_cross_entropy) that never materializes [batch*seq, vocab]
    logits — the same contract as GPTConfig(fused_loss=True), and a
    natural fit for MLM where ~85% of positions are ignore_index.
    """

    def __init__(self, bert=None, fused_mlm=False, **bert_kwargs):
        super().__init__()
        self.bert = bert or BertModel(**bert_kwargs)
        hidden = self.bert.pooler.dense._out_features
        vocab = self.bert.embeddings.word_embeddings._num_embeddings
        self.transform = nn.Linear(hidden, hidden)
        self.act = nn.GELU()
        self.layer_norm = nn.LayerNorm(hidden)
        self.decoder = nn.Linear(hidden, vocab)
        self.seq_relationship = nn.Linear(hidden, 2)
        # loss() tells hidden states from logits by the trailing dim —
        # refuse the ambiguous vocab == hidden configuration up front
        # (same contract as GPTConfig.fused_loss)
        if fused_mlm and vocab == hidden:
            raise ValueError(
                'fused_mlm=True requires vocab_size != hidden_size '
                '(loss() distinguishes hidden states from logits by '
                'their trailing dimension); got both = %d' % vocab)
        self.fused_mlm = fused_mlm

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        encoded, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                    attention_mask)
        h = self.layer_norm(self.act(self.transform(encoded)))
        nsp = self.seq_relationship(pooled)
        if self.fused_mlm and self.training:
            return h, nsp
        return self.decoder(h), nsp

    def loss(self, mlm_out, nsp_out, mlm_labels, nsp_labels,
             ignore_index=-100):
        """Mean MLM CE over non-ignored positions + NSP CE (the reference
        ERNIE/BERT pretraining objective)."""
        from ...nn import functional as F
        hidden = self.transform._out_features
        if self.fused_mlm and self.training and \
                mlm_out.shape[-1] == hidden:
            mlm = F.linear_cross_entropy(
                mlm_out, self.decoder.weight, mlm_labels,
                bias=self.decoder.bias, ignore_index=ignore_index)
        else:
            from ...tensor import manipulation as M
            b, n, v = mlm_out.shape
            mlm = F.cross_entropy(M.reshape(mlm_out, [b * n, v]),
                                  M.reshape(mlm_labels, [b * n]),
                                  ignore_index=ignore_index)
        nsp = F.cross_entropy(nsp_out, nsp_labels)
        return mlm + nsp


# ERNIE-1.0 (BASELINE config-3 metric family) shares BERT's encoder
# architecture; the differences in the reference era were pretraining
# objectives (phrase/entity masking), not the network. Named aliases keep
# the user-facing model-zoo surface.
ErnieModel = BertModel
ErnieForSequenceClassification = BertForSequenceClassification
ErnieForPretraining = BertForPretraining


def ernie_1_0(vocab_size=18000, hidden_size=768, **kwargs):
    """ERNIE-1.0-base configuration (12 layers, 768 hidden)."""
    return ErnieModel(vocab_size=vocab_size, hidden_size=hidden_size,
                      **kwargs)
